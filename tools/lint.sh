#!/usr/bin/env bash
# Single lint entry point, CI-shaped: exit 0 iff the tree is clean.
#
#   tools/lint.sh            dnzlint + native warning build (-Werror)
#   tools/lint.sh --tsan     ... + the TSan-built native hammer smoke
#
# Everything here is also enforced as tier-1 tests (tests/test_lint.py,
# tests/test_native_build_gate.py, tests/test_native_sanitizers.py) —
# this script exists for fast local/CI runs without the pytest harness.
set -euo pipefail
cd "$(dirname "$0")/.."

fail=0

echo "== dnzlint (rules: docs/static_analysis.md)"
python -m tools.dnzlint denormalized_tpu --report LINT_REPORT.json || fail=1

# budget gate: the whole point of a tier-1 lint is that nobody skips it
# for being slow — the JSON report carries wall_clock_s so CI sees drift
if ! python - <<'EOF'
import json, sys
wall = json.load(open("LINT_REPORT.json"))["wall_clock_s"]
print(f"dnzlint wall clock: {wall}s (budget 60s)")
sys.exit(0 if wall < 60 else 1)
EOF
then
    echo "dnzlint blew its 60s wall-clock budget — profile the passes"
    fail=1
fi

echo "== bench trend gate (BENCH_HISTORY.jsonl, latest vs previous)"
python tools/bench_trend.py --gate --config simple --max-regress-pct 25 \
    || fail=1

echo "== fault-site docs drift"
table="$(python -m tools.dnzlint --fault-site-table)"
if ! python - "$table" <<'EOF'
import sys
table = sys.argv[1]
docs = open("docs/fault_tolerance.md").read()
sys.exit(0 if table in docs else 1)
EOF
then
    echo "docs/fault_tolerance.md fault-site table is stale — paste the"
    echo "output of: python -m tools.dnzlint --fault-site-table"
    fail=1
fi

echo "== replay-path docs drift"
table="$(python -m tools.dnzlint --replay-path-table)"
if ! python - "$table" <<'EOF'
import sys
table = sys.argv[1]
docs = open("docs/static_analysis.md").read()
sys.exit(0 if table in docs else 1)
EOF
then
    echo "docs/static_analysis.md replay-path table is stale — paste the"
    echo "output of: python -m tools.dnzlint --replay-path-table"
    fail=1
fi

if command -v g++ >/dev/null; then
    echo "== native warning build (-Wall -Wextra -Wshadow -Wconversion -Werror)"
    NATIVE=denormalized_tpu/native
    PY_INC="$(python -c 'import sysconfig; print(sysconfig.get_paths()["include"])')"
    WARN="-Wall -Wextra -Wshadow -Wconversion -Werror"
    tmp="$(mktemp -d)"
    trap 'rm -rf "$tmp"' EXIT
    # enumerate from disk (native_test is the standalone binary, built
    # below) so a new .cpp can never silently skip the warning build —
    # same completeness contract as test_native_build_gate.py
    for src in "$NATIVE"/*.cpp; do
        mod="$(basename "$src" .cpp)"
        [ "$mod" = native_test ] && continue
        extra=""
        [ "$mod" = kafka_client ] && extra="-lz"
        [ "$mod" = pyassemble ] && extra="-I$PY_INC"
        # shellcheck disable=SC2086
        g++ -O2 -shared -fPIC -std=c++17 $WARN \
            "$src" -o "$tmp/$mod.so" $extra \
            || { echo "WARN-BUILD FAILED: $mod"; fail=1; }
    done
    g++ -std=c++17 -g -O1 $WARN \
        "$NATIVE/native_test.cpp" -o "$tmp/native_test" -lz -ldl -lpthread \
        || { echo "WARN-BUILD FAILED: native_test"; fail=1; }

    if [ "${1:-}" = "--tsan" ]; then
        echo "== TSan hammer smoke"
        # -lpthread matters on glibc<2.34 (same reason as the pytest
        # driver) — without it a working TSan toolchain would be
        # misreported as absent
        if g++ -std=c++17 -g -fsanitize=thread \
               "$NATIVE/native_test.cpp" -o "$tmp/native_test_tsan" \
               -lz -ldl -lpthread 2>"$tmp/tsan_build.err"; then
            "$tmp/native_test_tsan" "$tmp/lsm" >/dev/null \
                || { echo "TSAN HAMMERS FAILED"; fail=1; }
        else
            echo "toolchain lacks TSan — skipping (reason follows)"
            tail -3 "$tmp/tsan_build.err" || true
        fi
    fi
else
    echo "== no g++ — native checks skipped (pure-Python fallbacks cover this box)"
fi

if [ "$fail" -eq 0 ]; then
    echo "lint: clean"
else
    echo "lint: FAILURES above"
fi
exit "$fail"
