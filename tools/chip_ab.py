"""Real-chip A/B harness: the full strategy matrix over the BASELINE.md
configs, for the moment the axon tunnel is reachable.

Runs bench.py in subprocesses (so each config gets a fresh backend and a
wedged tunnel can never hang this process) across:

    config    × {simple, sliding, highcard, join, checkpoint}
    strategy  × {scatter, pallas_dense, partial_merge}
    emission  × {full} (+ compacted via --compaction)

and writes one JSON report with rows/s, vs_baseline, and p50/p99 window
latency per cell — the VERDICT round-1 ask ("A/B scatter vs pallas_dense on
the chip for all five configs") in one command:

    python tools/chip_ab.py [--rows 8000000] [--out AB_REPORT.json]

The TPU probe follows the tunnel rules (subprocess, abandoned not killed on
timeout); if the backend is down every cell falls back to CPU and the
report says so — still useful as a host-side regression matrix.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent

CONFIGS = ["simple", "sliding", "highcard", "join", "checkpoint"]
STRATEGIES = ["scatter", "pallas_dense", "partial_merge"]
COMPACTION = [False]  # emission compaction: add True via --compaction


def run_cell(config, strategy, compaction, rows, lat_rows):
    env = dict(os.environ)
    env.update(
        BENCH_CONFIG=config,
        BENCH_DEVICE_STRATEGY=strategy,
        BENCH_ROWS=str(rows),
        BENCH_LAT_ROWS=str(lat_rows),
        BENCH_EMISSION_COMPACTION="1" if compaction else "0",
    )
    t0 = time.time()
    proc = subprocess.Popen(
        [sys.executable, str(REPO / "bench.py")],
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
        env=env,
        start_new_session=True,
    )
    cell = {
        "config": config,
        "strategy": strategy,
        "emission_compaction": compaction,
    }
    try:
        out, errout = proc.communicate(timeout=3600)
        cell["rc"] = proc.returncode
    except subprocess.TimeoutExpired:
        # ABANDON, never kill: SIGKILLing a process mid-TPU-handshake is
        # what wedges the single-client tunnel for every later user
        cell["rc"] = "timeout-abandoned"
        cell["wall_s"] = round(time.time() - t0, 1)
        return cell
    cell["wall_s"] = round(time.time() - t0, 1)
    for line in out.splitlines():
        if line.startswith("{"):
            try:
                cell.update(json.loads(line))
                break
            except json.JSONDecodeError:
                pass
    if proc.returncode != 0:
        cell["stderr_tail"] = errout[-800:]
    return cell


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=8_000_000)
    ap.add_argument("--lat-rows", type=int, default=10_000_000)
    ap.add_argument("--out", default=str(REPO / "AB_REPORT.json"))
    ap.add_argument(
        "--configs", default=",".join(CONFIGS),
        help="comma-separated subset",
    )
    ap.add_argument(
        "--strategies", default=",".join(STRATEGIES),
        help="comma-separated subset",
    )
    ap.add_argument(
        "--compaction", action="store_true",
        help="also run emission-compaction=on cells",
    )
    args = ap.parse_args()
    strategies = args.strategies.split(",")
    compaction = [False, True] if args.compaction else [False]

    # probe ONCE and pin the result for every cell: per-cell probes would
    # stack abandoned probe processes against the single-client tunnel
    sys.path.insert(0, str(REPO))
    import bench as bench_mod

    device = os.environ.get("BENCH_DEVICE") or bench_mod.pick_device()
    os.environ["BENCH_DEVICE"] = device
    print(f"device: {device}", flush=True)

    cells = []
    for config in args.configs.split(","):
        for strategy in strategies:
            for compact in compaction:
                print(
                    f"== {config} / {strategy} / "
                    f"compaction={'on' if compact else 'off'} ==",
                    flush=True,
                )
                cell = run_cell(
                    config, strategy, compact, args.rows, args.lat_rows
                )
                print(
                    f"   rc={cell['rc']} device={cell.get('device')} "
                    f"{cell.get('value', 0):,} rows/s "
                    f"p99={cell.get('p99_window_latency_ms')}ms",
                    flush=True,
                )
                cells.append(cell)
                # incremental write: a wedged later cell must not lose
                # hours of completed cells
                Path(args.out).write_text(
                    json.dumps(
                        {"partial": True, "device": device, "cells": cells},
                        indent=1,
                    )
                )
    report = {
        "generated_at_unix": int(time.time()),
        "rows": args.rows,
        "device": device,
        "cells": cells,
    }
    Path(args.out).write_text(json.dumps(report, indent=1))
    print(f"wrote {args.out} ({len(cells)} cells)")


if __name__ == "__main__":
    main()
