"""Real-chip A/B harness: the full strategy matrix over the BASELINE.md
configs, for the moment the axon tunnel is reachable.

Round-3 rework: cells run IN THIS PROCESS via ``bench.set_knobs`` +
``bench.run_config``.  The round-2 harness ran each cell as a subprocess
with its own device probe; on a single-client tunnel those probes stacked
abandoned children against the claim and each cell re-paid a multi-minute
backend acquisition.  One process = one init, one shared jit cache
(cells reuse compiled programs across strategies), zero orphans.

    python tools/chip_ab.py [--rows 8000000] [--out AB_REPORT.json]
        [--configs simple,sliding,...] [--strategies scatter,...]
        [--compaction] [--host-pipeline]

Writes one JSON report with rows/s, vs_baseline, p50/p99 window latency
and sample counts per cell; the report is rewritten after every cell so a
wedged later cell cannot lose completed ones.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
import traceback
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent

CONFIGS = ["simple", "sliding", "highcard", "join", "checkpoint"]
# pallas_dense is out of the default matrix (VERDICT r4 #8 decision): in
# the only chip evidence (AB_REPORT_r2.json) it lost every config to
# partial_merge (1.24-2.76x vs 3.19-9.56x) — behind a ~20-35 MB/s tunnel
# a row-shipping kernel cannot beat edge reduction.  It stays runnable
# via --strategies pallas_dense (chip_watch's phase 2 runs exactly that
# in its plausible-win regime: emission-heavy sliding, low cardinality).
STRATEGIES = ["scatter", "partial_merge"]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=8_000_000)
    ap.add_argument(
        "--lat-rows", type=int, default=110_000_000,
        help="paced latency-phase rows (110M -> ~109 samples/cell; the "
        "round-3 bar is >=100)",
    )
    ap.add_argument("--out", default=str(REPO / "AB_REPORT.json"))
    ap.add_argument(
        "--configs", default=",".join(CONFIGS), help="comma-separated subset"
    )
    ap.add_argument(
        "--strategies", default=",".join(STRATEGIES),
        help="comma-separated subset",
    )
    ap.add_argument(
        "--compaction", action="store_true",
        help="also run emission-compaction=on cells",
    )
    ap.add_argument(
        "--host-pipeline", action="store_true",
        help="also run host_pipeline=on cells (partial_merge only)",
    )
    ap.add_argument(
        "--cell-timeout", type=float, default=5400.0,
        help="per-cell wall bound: on expiry the partial report is "
        "written with the cell marked hung and the process exits 3 "
        "(a wedged device op cannot be cancelled in-process; rerun "
        "with --resume to continue from completed cells)",
    )
    ap.add_argument(
        "--resume", action="store_true",
        help="skip cells already present with rc==0 in --out",
    )
    ap.add_argument(
        "--finals-ab", action="store_true",
        help="also run device_finalize=off cells (partial_merge only) — "
        "isolates the on-device finalization win",
    )
    ap.add_argument(
        "--allow-cpu", action="store_true",
        help="proceed on CPU fallback instead of failing fast (a CPU "
        "A/B report is useless as chip evidence, so the default is to "
        "exit 4 when the tunnel is down and let an outer loop retry)",
    )
    ap.add_argument(
        "--quick-rows", type=int, default=300_000,
        help="row count for the quick evidence tier: before the full "
        "matrix, one reduced-rows partial_merge cell per config runs "
        "with no latency phase and no kill-recovery, so the first "
        "banked device=tpu cell costs seconds past compile rather than "
        "minutes (round-4's one relay window died with zero cells)",
    )
    ap.add_argument(
        "--no-quick", action="store_true", help="skip the quick tier",
    )
    ap.add_argument(
        "--quick-only", action="store_true",
        help="run ONLY the quick tier (smoke / first-evidence mode)",
    )
    args = ap.parse_args()
    strategies = args.strategies.split(",")
    compaction = [False, True] if args.compaction else [False]

    sys.path.insert(0, str(REPO))
    if not args.allow_cpu:
        # a dead tunnel must produce a retryable failure (exit 4), not a
        # silent CPU report (bench._tpu_init_fail honors this)
        os.environ["BENCH_TPU_INIT_REQUIRED"] = "1"
    import bench

    device = bench.init_backend()
    print(f"device: {device}", flush=True)
    probe = {}
    if device == "tpu":
        try:
            probe = bench.link_probe()
            print(f"link probe: {probe}", flush=True)
        except Exception as e:
            print(f"link probe failed: {e!r}", flush=True)

    done_keys = set()
    prior_cells = []
    if args.resume and Path(args.out).exists():
        try:
            prior = json.loads(Path(args.out).read_text())
            for c in prior.get("cells", []):
                if c.get("rc") == 0:
                    prior_cells.append(c)
                    done_keys.add((
                        c["config"], c["strategy"],
                        c.get("emission_compaction", False),
                        c.get("host_pipeline", False),
                        c.get("device_finalize", True),
                        c.get("quick", False),
                    ))
        except Exception as e:
            print(f"resume: could not read {args.out}: {e!r}", flush=True)

    def run_cell(config, strategy, compact, pipeline, finals=True,
                 quick=False):
        cell = {
            "config": config,
            "strategy": strategy,
            "emission_compaction": compact,
            "host_pipeline": pipeline,
            "device_finalize": finals,
            "quick": quick,
            # per-cell scale: the top-level rows/lat_rows describe only
            # full cells, so each cell records what it actually ran
            "rows": args.quick_rows if quick else args.rows,
            "lat_rows_run": 0 if quick else args.lat_rows,
        }
        t0 = time.time()
        # a wedged device op cannot be cancelled from inside the process:
        # on expiry, persist what we have and exit nonzero so an outer
        # loop can rerun with --resume
        import threading

        cell_done = threading.Event()

        def _hang_watch():
            if not cell_done.wait(args.cell_timeout):
                cell["rc"] = "hung"
                cell["wall_s"] = round(time.time() - t0, 1)
                cells.append(cell)
                Path(args.out).write_text(json.dumps(
                    {"partial": True, "device": device, "cells": cells},
                    indent=1,
                ))
                print(f"cell hung >{args.cell_timeout:.0f}s; exiting 3 "
                      f"(rerun with --resume)", flush=True)
                os._exit(3)

        threading.Thread(target=_hang_watch, daemon=True).start()
        bench.set_knobs(
            config=config,
            strategy=strategy,
            compaction=compact,
            host_pipeline=pipeline,
            device_finalize=finals,
            rows=args.quick_rows if quick else args.rows,
            # quick tier: lat_rows=0 skips the latency phase entirely (a
            # second compiled shape); kill_recovery off for the same reason
            lat_rows=0 if quick else args.lat_rows,
            kill_recovery=not quick,
            # run_config re-derives highcard keys/batch from env; reset
            # the generic defaults for every other cell
            keys=int(os.environ.get("BENCH_KEYS", 10)),
            batch=int(os.environ.get("BENCH_BATCH", 131_072)),
        )
        try:
            cell.update(bench.run_config(device))
            cell["rc"] = 0
        except Exception:
            cell["rc"] = 1
            cell["error"] = traceback.format_exc()[-800:]
        finally:
            cell_done.set()
        cell["wall_s"] = round(time.time() - t0, 1)
        return cell

    cells = list(prior_cells)

    def emit(cell):
        print(
            f"   rc={cell['rc']} device={cell.get('device')} "
            f"{cell.get('value', 0):,} rows/s "
            f"p99={cell.get('p99_window_latency_ms')}ms "
            f"n={cell.get('latency_samples')}",
            flush=True,
        )
        cells.append(cell)
        # incremental write: a wedged later cell must not lose hours of
        # completed cells
        Path(args.out).write_text(
            json.dumps(
                {"partial": True, "device": device, "cells": cells}, indent=1
            )
        )

    specs = []
    if not args.no_quick:
        # quick evidence tier: one tiny partial_merge cell per config, run
        # before everything else — the first banked device=tpu cell must
        # cost seconds, not minutes, on a tunnel that flaps in ~60s windows
        for config in args.configs.split(","):
            specs.append((config, "partial_merge", False, False, True, True))
    if not args.quick_only:
        for config in args.configs.split(","):
            for strategy in strategies:
                variants = [(c, False, True) for c in compaction]
                if strategy == "partial_merge":
                    if args.host_pipeline:
                        variants.append((False, True, True))
                    if args.finals_ab:
                        variants.append((False, False, False))
                for compact, pipeline, finals in variants:
                    specs.append(
                        (config, strategy, compact, pipeline, finals, False)
                    )

    def _prio(spec):
        """Coverage-first ordering for a flapping tunnel: the quick
        evidence tier runs first (tiny cells, all five configs), then the
        five full-size partial_merge base cells (the auto-selected
        headline strategy) before any second strategy, which runs before
        the pipeline/finals variants.  Within a tier, keep the BASELINE
        config order."""
        config, strategy, compact, pipeline, finals, quick = spec
        variant = compact or pipeline or not finals
        if quick:
            tier = -1
        elif strategy == "partial_merge" and not variant:
            tier = 0
        elif not variant:
            tier = 1
        else:
            tier = 2
        cfg_rank = CONFIGS.index(config) if config in CONFIGS else len(CONFIGS)
        strat_rank = (
            strategies.index(strategy) if strategy in strategies
            else len(strategies)
        )
        return (tier, cfg_rank, strat_rank)

    for spec in sorted(specs, key=_prio):
        config, strategy, compact, pipeline, finals, quick = spec
        if spec in done_keys or (
            # a completed full-size cell supersedes its quick twin
            quick and (config, strategy, compact, pipeline, finals)
            in {k[:5] for k in done_keys if not k[5]}
        ):
            print(f"== {config} / {strategy}"
                  f"{' / quick' if quick else ''} skipped (resume) ==",
                  flush=True)
            continue
        print(
            f"== {config} / {strategy} / "
            f"compaction={'on' if compact else 'off'}"
            f"{' / host_pipeline=on' if pipeline else ''}"
            f"{' / device_finalize=off' if not finals else ''}"
            f"{' / QUICK' if quick else ''} ==",
            flush=True,
        )
        emit(run_cell(config, strategy, compact, pipeline, finals, quick))
    report = {
        "generated_at_unix": int(time.time()),
        "rows": args.rows,
        "lat_rows": args.lat_rows,
        "device": device,
        "link_probe": probe,
        "cells": cells,
    }
    Path(args.out).write_text(json.dumps(report, indent=1))
    failed = sum(1 for c in cells if c.get("rc") != 0)
    print(f"wrote {args.out} ({len(cells)} cells, {failed} failed)")
    if failed:
        # failed cells are resumable (--resume skips only rc==0): exit
        # nonzero so an outer retry loop reruns them
        sys.exit(5)


if __name__ == "__main__":
    main()
