"""Regenerate the README perf table from a committed A/B artifact — the
single source of truth for every real-chip number (round-3 VERDICT weak-2:
'perf claims must be regenerable from a committed JSON').

    python tools/readme_table.py AB_REPORT_r4.json [--write]

Prints the markdown table built from the BEST cell per config (ties by
rows/s); with --write, splices it into README.md between the
`<!-- perf-table:begin -->` / `<!-- perf-table:end -->` markers and
updates the artifact name in the preamble sentence.
"""

from __future__ import annotations

import argparse
import json
import re
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent

LABELS = {
    "simple": "simple (1s tumbling count/min/max/avg, 10 keys)",
    "sliding": "sliding (1s/200ms + post-agg filter)",
    "highcard": "highcard (100K keys sum/avg)",
    "join": "join (two windowed streams)",
    "checkpoint": "checkpoint (simple + 2s barriers to the LSM)",
}
ORDER = ["simple", "sliding", "highcard", "join", "checkpoint"]


def fmt_m(v: float) -> str:
    return f"{v / 1e6:.1f}M"


def build_table(report: dict) -> str:
    best: dict[str, dict] = {}
    for c in report.get("cells", []):
        if c.get("rc") != 0 or c.get("device") != "tpu":
            continue
        # quick-tier smoke cells (reduced rows, no latency phase) are
        # chip EVIDENCE, not headline numbers — never let one into the
        # README table, even when no full-size cell completed
        if c.get("quick"):
            continue
        k = c["config"]
        if k not in best or c["value"] > best[k]["value"]:
            best[k] = c
    lines = [
        "| config | engine rows/s | vs CPU baseline | "
        "p50 / p99 window latency |",
        "|---|---|---|---|",
    ]
    for k in ORDER:
        c = best.get(k)
        if c is None:
            lines.append(f"| {LABELS[k]} | — | — | — |")
            continue
        p50, p99 = c.get("p50_window_latency_ms"), c.get("p99_window_latency_ms")
        # bench legitimately emits None latencies (too few rows to close
        # a window) on rc==0 paths
        lat = (
            f"{p50:.0f} / {p99:.0f} ms"
            if p50 is not None and p99 is not None
            else "— / — ms"
        )
        lines.append(
            f"| {LABELS[k]} | {fmt_m(c['value'])} | "
            f"{c['vs_baseline']:.1f}× | {lat} |"
        )
    return "\n".join(lines), len(best)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("artifact")
    ap.add_argument("--write", action="store_true")
    args = ap.parse_args()
    report = json.loads(Path(args.artifact).read_text())
    table, n_configs = build_table(report)
    print(table)
    if not args.write:
        return
    if n_configs == 0:
        sys.exit(
            f"{args.artifact} contains no successful TPU cells (wrong "
            "file? CPU run?) — refusing to overwrite the README table "
            "with em-dashes"
        )
    readme = REPO / "README.md"
    text = readme.read_text()
    begin, end = "<!-- perf-table:begin -->", "<!-- perf-table:end -->"
    if begin not in text or end not in text:
        sys.exit(
            "README.md is missing the perf-table markers; add "
            f"{begin!r} and {end!r} around the table first"
        )
    name = Path(args.artifact).name
    new = re.sub(
        re.escape(begin) + ".*?" + re.escape(end),
        f"{begin}\n{table}\n{end}",
        text,
        flags=re.S,
    )
    # anchored to the 'copied from' phrase, not a particular artifact
    # spelling — stays updatable across renames
    new, n_sub = re.subn(
        r"(copied from\s+)`[^`]+\.json`", rf"\1`{name}`", new, count=1
    )
    if n_sub == 0:
        print("warning: 'copied from `<artifact>`' phrase not found in "
              "README preamble; artifact name not updated", file=sys.stderr)
    readme.write_text(new)
    print(f"\nspliced into {readme} (artifact: {name})")


if __name__ == "__main__":
    main()
