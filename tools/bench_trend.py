"""Reader for the committed perf-trajectory artifact BENCH_HISTORY.jsonl.

``bench.py --record`` (or ``BENCH_RECORD=1``) appends one JSONL line per
bench run — config, headline rows/s, git sha, host cores, device.  This
tool renders the machine-readable trajectory as the table the ROADMAP
narrative used to carry by hand::

    python tools/bench_trend.py               # every config
    python tools/bench_trend.py --config simple
    python tools/bench_trend.py --json        # machine output

``--gate`` turns the trajectory into a CI gate: exit 2 when the LATEST
record of a config regresses more than ``--max-regress-pct`` (default
10) against the PREVIOUS record on the gated metric — the perf
trajectory stops being just a log.  A single-record history passes
(nothing to compare yet); records from a DIFFERENT device are never
compared against each other (a laptop run must not "regress" a TPU
number)::

    python tools/bench_trend.py --gate --config simple --max-regress-pct 15

Wired into tools/lint.sh and pinned by tests/test_bench_trend.py.
Stdlib-only (it runs in the jax-free soak/driver environments).
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path


def load_history(path: Path) -> list[dict]:
    """All history entries, file order (oldest first).  Torn tail lines
    (crash mid-append) are skipped, same policy as obs/readers.py."""
    out: list[dict] = []
    if not path.exists():
        return out
    for line in path.read_text().splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            o = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(o, dict) and "value" in o:
            out.append(o)
    return out


def by_config(entries: list[dict]) -> dict[str, list[dict]]:
    out: dict[str, list[dict]] = {}
    for e in entries:
        out.setdefault(str(e.get("config", "?")), []).append(e)
    return out


def _label(e: dict) -> str:
    return str(e.get("round") or e.get("git_sha") or "?")


def trend_rows(entries: list[dict]) -> list[dict]:
    """Per-entry rows with delta vs the previous point of the same
    config (the number the ROADMAP narrative quotes)."""
    rows = []
    prev = None
    for e in entries:
        v = e.get("value") or 0
        delta = None
        if prev:
            delta = round((v - prev) / prev * 100.0, 1)
        rows.append({
            "label": _label(e),
            "value": v,
            "delta_pct": delta,
            "device": e.get("device"),
            "git_sha": e.get("git_sha"),
            "host_cores": e.get("host_cores"),
            "vs_baseline": e.get("vs_baseline"),
        })
        prev = v
    return rows


def gate(
    entries: list[dict], max_regress_pct: float, config: str
) -> tuple[int, str]:
    """(exit_code, message) of the regression gate over ONE config's
    history: 0 = pass, 2 = the latest record regressed more than
    ``max_regress_pct`` vs the previous comparable (same-device) one."""
    if not entries:
        return 1, f"gate: no history entries for config {config!r}"
    latest = entries[-1]
    value = latest.get("value") or 0
    device = latest.get("device")
    prev = None
    for e in reversed(entries[:-1]):
        if e.get("device") == device and e.get("value"):
            prev = e
            break
    if prev is None:
        return 0, (
            f"gate: {config}: single {device or '?'} record "
            f"({_label(latest)}, {value:,}) — nothing to compare, pass"
        )
    drop_pct = (prev["value"] - value) / prev["value"] * 100.0
    line = (
        f"gate: {config}: {_label(prev)} {prev['value']:,} -> "
        f"{_label(latest)} {value:,} rows/s "
        f"({-drop_pct:+.1f}% , limit -{max_regress_pct:g}%)"
    )
    if drop_pct > max_regress_pct:
        return 2, line + " — REGRESSION"
    return 0, line + " — ok"


def render(groups: dict[str, list[dict]]) -> str:
    lines = []
    for config, entries in sorted(groups.items()):
        lines.append(f"== {config} ==")
        lines.append(
            f"{'point':>8}  {'rows/s':>14}  {'delta':>8}  "
            f"{'device':>6}  {'sha':>9}  {'cores':>5}"
        )
        for r in trend_rows(entries):
            delta = (
                f"{r['delta_pct']:+.1f}%" if r["delta_pct"] is not None
                else "—"
            )
            lines.append(
                f"{r['label']:>8}  {r['value']:>14,}  {delta:>8}  "
                f"{str(r['device'] or '?'):>6}  "
                f"{str(r['git_sha'] or '?'):>9}  "
                f"{str(r['host_cores'] or '?'):>5}"
            )
        first, last = entries[0], entries[-1]
        if first.get("value"):
            lines.append(
                f"trajectory: {first['value']:,} → {last['value']:,} "
                f"rows/s ({last['value'] / first['value']:.2f}x over "
                f"{len(entries)} recorded points)"
            )
        lines.append("")
    return "\n".join(lines).rstrip()


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python tools/bench_trend.py",
        description="render the BENCH_HISTORY.jsonl perf trajectory",
    )
    parser.add_argument(
        "--path",
        default=str(Path(__file__).resolve().parent.parent
                    / "BENCH_HISTORY.jsonl"),
    )
    parser.add_argument("--config", default=None,
                        help="restrict to one bench config")
    parser.add_argument("--json", action="store_true",
                        help="emit the trend rows as JSON")
    parser.add_argument("--gate", action="store_true",
                        help="CI mode: exit 2 when the latest record of "
                        "--config (required) regresses more than "
                        "--max-regress-pct vs the previous same-device "
                        "record")
    parser.add_argument("--max-regress-pct", type=float, default=10.0)
    args = parser.parse_args(argv)

    entries = load_history(Path(args.path))
    if not entries:
        print(f"no history at {args.path}", file=sys.stderr)
        return 1
    groups = by_config(entries)
    if args.gate:
        if not args.config:
            print("--gate requires --config", file=sys.stderr)
            return 1
        rc, msg = gate(
            groups.get(args.config, []), args.max_regress_pct, args.config
        )
        print(msg, file=sys.stderr if rc else sys.stdout)
        return rc
    if args.config:
        if args.config not in groups:
            print(
                f"no entries for config {args.config!r} "
                f"(have: {sorted(groups)})", file=sys.stderr,
            )
            return 1
        groups = {args.config: groups[args.config]}
    if args.json:
        print(json.dumps(
            {c: trend_rows(e) for c, e in groups.items()}, indent=2
        ))
    else:
        print(render(groups))
    return 0


if __name__ == "__main__":
    sys.exit(main())
