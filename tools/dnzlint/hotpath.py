"""DNZ-H001/H002 — hot-path purity.

PRs 2-3 bought the engine's throughput by removing per-row Python from
the session/window/join/decode kernels (SESSION_SCALE.json: 14x at 10k
keys).  Nothing structural stops a future edit from re-introducing a
``for row in ...`` or a ``hash(tuple(key))`` into one of those functions
— the tests would still pass, just 10-30x slower.  This pass pins the
property: functions registered in ``hotpaths.toml`` must contain

- **no ``for``/``while`` statements** (the registered kernels are the
  fully-vectorized ones; per-column comprehensions remain legal — the
  cliff is per-row *statements*, and every registered function is
  loop-free today, so any new loop is a deliberate, pragma-documented
  decision);
- **no ``.tolist()`` calls** (the canonical start of a per-row walk);
- **no ``hash(...)`` calls** (DNZ-H002 — the salted ``hash(tuple)``
  composite key was a *correctness* bug, not just slow: colliding keys
  silently merged two sessions, PARITY.md Round-6).

A function that legitimately needs a bounded loop (e.g. a per-aggregate
sweep over a fixed component list) takes an inline
``# dnzlint: allow(hot-loop) <reason>`` on the loop line — visible at
the loop, reviewed with the code.

Registering a function that the tree does not define is itself a finding
(DNZ-H001 on the config): a renamed kernel must update the registry, or
the pin silently evaporates.
"""

from __future__ import annotations

import ast
from pathlib import Path

from tools.dnzlint import Finding, _parse_toml


def load_hotpaths(path: Path) -> list[dict]:
    """``hotpaths.toml`` ``[[hotpath]]`` entries: {file, qualname}."""
    if not path.exists():
        return []
    data = _parse_toml(path)
    out = []
    for entry in data.get("hotpath", []):
        if entry.get("file") and entry.get("qualname"):
            out.append({
                "file": entry["file"],
                "qualname": entry["qualname"],
            })
    return out


def _find_function(tree: ast.AST, qualname: str):
    """Resolve ``Class.method`` / ``func`` / ``outer.inner`` to its node."""
    parts = qualname.split(".")
    node: ast.AST = tree
    for part in parts:
        found = None
        for child in ast.iter_child_nodes(node):
            if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ) and child.name == part:
                found = child
                break
        if found is None:
            return None
        node = found
    return node if isinstance(
        node, (ast.FunctionDef, ast.AsyncFunctionDef)
    ) else None


def run(root: Path, hotpaths_path: Path) -> list[Finding]:
    findings: list[Finding] = []
    entries = load_hotpaths(hotpaths_path)
    by_file: dict[str, list[str]] = {}
    for e in entries:
        by_file.setdefault(e["file"], []).append(e["qualname"])

    pkg = root.name
    for file_rel, qualnames in sorted(by_file.items()):
        # config paths are repo-style (``denormalized_tpu/...``)
        inner = file_rel[len(pkg) + 1:] if file_rel.startswith(pkg + "/") \
            else file_rel
        path = root / inner
        if not path.exists():
            for qn in qualnames:
                findings.append(Finding(
                    "DNZ-H001", file_rel, 1, qn,
                    f"hotpaths.toml registers {qn} but {file_rel} does not "
                    f"exist — update the registry for the moved/renamed "
                    f"kernel",
                ))
            continue
        tree = ast.parse(path.read_text(), filename=str(path))
        for qn in sorted(qualnames):
            fn = _find_function(tree, qn)
            if fn is None:
                findings.append(Finding(
                    "DNZ-H001", file_rel, 1, qn,
                    f"hotpaths.toml registers {qn} but it is not defined "
                    f"in {file_rel} — update the registry for the "
                    f"moved/renamed kernel",
                ))
                continue
            for node in ast.walk(fn):
                if isinstance(node, (ast.For, ast.AsyncFor, ast.While)):
                    kind = "while" if isinstance(node, ast.While) else "for"
                    findings.append(Finding(
                        "DNZ-H001", file_rel, node.lineno, qn,
                        f"`{kind}` loop inside registered hot-path "
                        f"function {qn} — this kernel is pinned "
                        f"loop-free (vectorize, or allow(hot-loop) with "
                        f"a reason)",
                    ))
                elif (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "tolist"
                ):
                    findings.append(Finding(
                        "DNZ-H001", file_rel, node.lineno, qn,
                        f".tolist() inside registered hot-path function "
                        f"{qn} — per-row materialization on a pinned "
                        f"vectorized kernel",
                    ))
                elif (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "hash"
                ):
                    findings.append(Finding(
                        "DNZ-H002", file_rel, node.lineno, qn,
                        f"hash(...) inside registered hot-path function "
                        f"{qn} — composite-key hashing collides and "
                        f"silently merges keys (PARITY.md Round-6); "
                        f"intern to dense ids instead",
                    ))
    return findings
