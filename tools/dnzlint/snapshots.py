"""DNZ-S001/S002 — snapshot/restore symmetry.

A field added to a keyed operator's snapshot payload but never read
back, or read in restore but never written, is a silent state-loss bug
that only a differential soak (hours in) or a version-skew restore
(days later) surfaces.  This pass statically matches the key/field sets
flowing into the snapshot payload against those read in the restore
path, per ``keyed_state = true`` operator in ``operators.toml``.

Method attribution works by codec seeding: a method whose body calls a
write codec (``pack_snapshot`` / ``put_snapshot`` / ``put_json``) is on
the *snapshot side*; a read codec (``unpack_snapshot`` /
``get_snapshot`` / ``get_json``) caller is on the *restore side*.  Each
side closes over private same-class / same-module helpers
(``_pack_side_cols(side_meta)``-style extraction helpers carry payload
keys too).  Within a side:

- **written keys** — string-literal dict-display keys, ``x["k"] = v``
  subscript stores, ``setdefault("k", ...)``;
- **read keys** — ``x["k"]`` subscript loads (*strict* — restore dies
  on a missing key) vs ``x.get("k", ...)`` / ``"k" in x`` (*tolerant*
  — a legacy-layout default exists).

DNZ-S001 fires on: a written key no restore path anywhere reads
(dropped from restore), a *strict* read no snapshot path anywhere
writes (phantom field — restore will KeyError on every real snapshot),
and a version-literal key (``version`` / ``snapshot_version`` /
``layout_version`` / ``fmt_version``) whose integer literals differ
between the two sides (bumped on one side only).  Computed keys
(f-strings, ``f"c{ci}|{k}"`` class-namespace slice layouts) are
invisible to the matcher by construction — only literal drift is
claimed.  Cross-codec keys (spill-block refs written by
``state/tiering.py``, read by an operator, and vice versa) are resolved
against package-wide auxiliary write/read sets rather than per-class
ones, and ``epoch`` is allowlisted (written by every operator as
provenance, deliberately read by none — restore trusts the manifest's
epoch instead).

DNZ-S002 is the registry drift rule: an ``operators.toml``-registered
class with snapshot-codec flows but no ``keyed_state = true``, or a
``keyed_state = true`` registration whose class has no snapshot flow
left.
"""

from __future__ import annotations

import ast
import dataclasses
from pathlib import Path

from tools.dnzlint import Finding, _parse_toml

_WRITE_CODECS = frozenset({
    "pack_snapshot", "put_snapshot", "put_json",
    # spill-block re-keying into the epoch namespace: its callers own
    # the spill-ref payload dicts ("side"/"bi"/"id"/... in join,
    # "keys"/"entries" in the tiers)
    "copy_block_to_epoch",
})
_READ_CODECS = frozenset({
    "unpack_snapshot", "get_snapshot", "get_json",
    "restore_block_from_epoch",
})
_VERSION_KEYS = frozenset({
    "version", "snapshot_version", "layout_version", "fmt_version",
})
#: provenance keys every operator writes and restore deliberately
#: ignores (the manifest, not the payload, is the restore's authority)
_ALLOW_UNREAD = frozenset({"epoch"})


@dataclasses.dataclass
class _SideKeys:
    written: dict[str, int] = dataclasses.field(default_factory=dict)
    strict_read: dict[str, int] = dataclasses.field(default_factory=dict)
    tolerant_read: dict[str, int] = dataclasses.field(default_factory=dict)
    version_lits: set[int] = dataclasses.field(default_factory=set)

    def merge(self, other: "_SideKeys") -> None:
        for k, v in other.written.items():
            self.written.setdefault(k, v)
        for k, v in other.strict_read.items():
            self.strict_read.setdefault(k, v)
        for k, v in other.tolerant_read.items():
            self.tolerant_read.setdefault(k, v)
        self.version_lits |= other.version_lits


def _str_const(node: ast.AST) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _collect_keys(fn: ast.AST) -> _SideKeys:
    """All literal payload-key activity in one function body."""
    out = _SideKeys()
    for node in ast.walk(fn):
        if isinstance(node, ast.Dict):
            for k, v in zip(node.keys, node.values):
                key = _str_const(k) if k is not None else None
                if key is None:
                    continue
                out.written.setdefault(key, node.lineno)
                if key in _VERSION_KEYS and isinstance(v, ast.Constant) \
                        and isinstance(v.value, int):
                    out.version_lits.add(v.value)
        elif isinstance(node, ast.Subscript):
            key = _str_const(node.slice)
            if key is None:
                continue
            if isinstance(node.ctx, (ast.Store, ast.Del)):
                out.written.setdefault(key, node.lineno)
            else:
                out.strict_read.setdefault(key, node.lineno)
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute):
            if node.func.attr in ("get", "pop") and node.args:
                key = _str_const(node.args[0])
                if key is not None:
                    out.tolerant_read.setdefault(key, node.lineno)
            elif node.func.attr == "setdefault" and node.args:
                key = _str_const(node.args[0])
                if key is not None:
                    out.written.setdefault(key, node.lineno)
        elif isinstance(node, ast.Compare) and len(node.ops) == 1 \
                and isinstance(node.ops[0], (ast.In, ast.NotIn)):
            key = _str_const(node.left)
            if key is not None:
                out.tolerant_read.setdefault(key, node.lineno)
        elif isinstance(node, ast.Assign):
            # version literal via store: snap["version"] = 2
            t = node.targets[0] if len(node.targets) == 1 else None
            if isinstance(t, ast.Subscript) \
                    and _str_const(t.slice) in _VERSION_KEYS \
                    and isinstance(node.value, ast.Constant) \
                    and isinstance(node.value.value, int):
                out.version_lits.add(node.value.value)
    # version literals compared on the read side: x["version"] == 2,
    # x.get("version", 1) — the .get default IS a version literal
    for node in ast.walk(fn):
        if isinstance(node, ast.Compare) and len(node.ops) == 1:
            left, right = node.left, node.comparators[0]
            for a, b in ((left, right), (right, left)):
                if isinstance(a, ast.Subscript) \
                        and _str_const(a.slice) in _VERSION_KEYS \
                        and isinstance(b, ast.Constant) \
                        and isinstance(b.value, int):
                    out.version_lits.add(b.value)
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "get" and len(node.args) >= 2 \
                and _str_const(node.args[0]) in _VERSION_KEYS \
                and isinstance(node.args[1], ast.Constant) \
                and isinstance(node.args[1].value, int):
            out.version_lits.add(node.args[1].value)
    return out


class _ModuleUnits:
    """One module's classes/functions with intra-module call edges."""

    def __init__(self, tree: ast.Module):
        self.functions: dict[str, ast.AST] = {}  # module-level defs
        self.classes: dict[str, dict[str, ast.AST]] = {}
        for node in tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions[node.name] = node
            elif isinstance(node, ast.ClassDef):
                methods = {}
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        methods[item.name] = item
                self.classes[node.name] = methods

    def callees(self, cls: str | None, fn: ast.AST) -> list[tuple[str | None, str]]:
        """(owner_class_or_None, name) intra-module call edges."""
        out = []
        methods = self.classes.get(cls, {}) if cls else {}
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if isinstance(f, ast.Attribute) \
                    and isinstance(f.value, ast.Name):
                if f.value.id in ("self", "cls") and f.attr in methods:
                    out.append((cls, f.attr))
                elif f.value.id in self.classes \
                        and f.attr in self.classes[f.value.id]:
                    out.append((f.value.id, f.attr))
            elif isinstance(f, ast.Name) and f.id in self.functions:
                out.append((None, f.id))
        return out

    def _calls_codec(self, fn: ast.AST, codecs: frozenset) -> bool:
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                f = node.func
                name = f.attr if isinstance(f, ast.Attribute) else (
                    f.id if isinstance(f, ast.Name) else None
                )
                if name in codecs:
                    return True
        return False

    @staticmethod
    def _self_attr_loads(fn: ast.AST) -> set[str]:
        return {
            n.attr for n in ast.walk(fn)
            if isinstance(n, ast.Attribute)
            and isinstance(n.value, ast.Name) and n.value.id == "self"
            and isinstance(n.ctx, ast.Load)
        }

    @staticmethod
    def _self_attr_stores(fn: ast.AST) -> set[str]:
        return {
            n.attr for n in ast.walk(fn)
            if isinstance(n, ast.Attribute)
            and isinstance(n.value, ast.Name) and n.value.id == "self"
            and isinstance(n.ctx, ast.Store)
        }

    @staticmethod
    def _builds_str_dict(fn: ast.AST) -> bool:
        for n in ast.walk(fn):
            if isinstance(n, ast.Dict) and any(
                isinstance(k, ast.Constant) and isinstance(k.value, str)
                for k in n.keys
            ):
                return True
        return False

    def side_units(self, cls: str, codecs: frozenset, *,
                   bridge_writes: bool = False) -> list[tuple[str, ast.AST]]:
        """Closure of ``cls``'s codec-calling methods over private
        intra-module helpers: [(qualname, fn_node)].

        With ``bridge_writes``, the closure also follows the
        deferred-payload idiom: a private method that *stores* an
        instance attribute some side unit *loads* — and that itself
        builds a string-keyed dict — joins the side
        (``_snapshot`` builds the meta, stashes it in
        ``self._pending_snapshot``, ``_release_snapshot`` persists it).
        """
        seeds = [
            (cls, m) for m, fn in self.classes.get(cls, {}).items()
            if self._calls_codec(fn, codecs)
        ]
        seen: set[tuple[str | None, str]] = set()
        order: list[tuple[str | None, str]] = []

        def expand(stack: list) -> None:
            while stack:
                ref = stack.pop()
                if ref in seen:
                    continue
                seen.add(ref)
                order.append(ref)
                owner, name = ref
                fn = self.classes[owner][name] if owner \
                    else self.functions[name]
                for callee in self.callees(owner, fn):
                    c_owner, c_name = callee
                    if callee in seen:
                        continue
                    # descend only into private helpers — public methods
                    # are independent surfaces, not extraction helpers
                    if not c_name.startswith("_") \
                            or c_name.startswith("__"):
                        continue
                    stack.append(callee)

        expand(list(seeds))
        if bridge_writes:
            changed = True
            while changed:
                changed = False
                loaded: set[str] = set()
                for owner, name in order:
                    fn = self.classes[owner][name] if owner \
                        else self.functions[name]
                    loaded |= self._self_attr_loads(fn)
                for m, fn in self.classes.get(cls, {}).items():
                    if (cls, m) in seen or not m.startswith("_") \
                            or m.startswith("__"):
                        continue
                    if self._self_attr_stores(fn) & loaded \
                            and self._builds_str_dict(fn):
                        expand([(cls, m)])
                        changed = True
        out = []
        for owner, name in order:
            fn = self.classes[owner][name] if owner else self.functions[name]
            qual = f"{owner}.{name}" if owner else name
            out.append((qual, fn))
        return out

    def has_codec_flow(self, cls: str) -> bool:
        return any(
            self._calls_codec(fn, _WRITE_CODECS | _READ_CODECS)
            for fn in self.classes.get(cls, {}).values()
        )


def load_operators(path: Path) -> list[dict]:
    if not path.exists():
        return []
    data = _parse_toml(path)
    out = []
    for entry in data.get("operator", []):
        if entry.get("class") and entry.get("file"):
            keyed = entry.get("keyed_state", False)
            out.append({
                "class": entry["class"],
                "file": entry["file"],
                # the no-tomllib fallback parses values as strings
                "keyed_state": keyed in (True, "true"),
            })
    return out


def _side_keys(mod: _ModuleUnits, cls: str, codecs: frozenset,
               *, bridge_writes: bool = False) -> tuple[_SideKeys, dict[str, str]]:
    """Merged key sets for one side, plus key -> qualname attribution."""
    keys = _SideKeys()
    where: dict[str, str] = {}
    for qual, fn in mod.side_units(cls, codecs,
                                   bridge_writes=bridge_writes):
        got = _collect_keys(fn)
        for k in got.written:
            where.setdefault(f"w:{k}", qual)
        for k in got.strict_read:
            where.setdefault(f"r:{k}", qual)
        keys.merge(got)
    return keys, where


def run(root: Path, operators_path: Path | None = None) -> list[Finding]:
    here = Path(__file__).resolve().parent
    if operators_path is None:
        operators_path = here / "operators.toml"
    entries = load_operators(operators_path)

    findings: list[Finding] = []
    pkg = root.name
    mods: dict[str, tuple[_ModuleUnits, str]] = {}

    def module_for(file_rel: str) -> _ModuleUnits | None:
        if file_rel in mods:
            return mods[file_rel][0]
        inner = file_rel[len(pkg) + 1:] if file_rel.startswith(pkg + "/") \
            else file_rel
        path = root / inner
        if not path.exists():
            return None
        tree = ast.parse(path.read_text(), filename=str(path))
        mod = _ModuleUnits(tree)
        mods[file_rel] = (mod, inner)
        return mod

    # package-wide auxiliary write/read sets: every codec-flow unit in
    # the tree contributes, so cross-codec keys (tiering spill refs,
    # rescale's rebuilt meta) resolve without per-class special cases
    aux_written: set[str] = set()
    aux_read: set[str] = set()
    for path in sorted(root.rglob("*.py")):
        if "__pycache__" in path.parts:
            continue
        rel = f"{pkg}/{path.relative_to(root)}"
        mod = module_for(rel)
        if mod is None:
            continue
        units = list(mod.functions.items()) + [
            (f"{c}.{m}", fn)
            for c, ms in mod.classes.items() for m, fn in ms.items()
        ]
        for _qual, fn in units:
            if mod._calls_codec(fn, _WRITE_CODECS):
                got = _collect_keys(fn)
                aux_written |= set(got.written)
            if mod._calls_codec(fn, _READ_CODECS):
                got = _collect_keys(fn)
                aux_read |= set(got.strict_read) | set(got.tolerant_read)

    for e in entries:
        mod = module_for(e["file"])
        if mod is None or e["class"] not in mod.classes:
            continue  # handoff.py owns missing-class drift (DNZ-M002)
        cls = e["class"]
        flows = mod.has_codec_flow(cls)
        if e["keyed_state"] and not flows:
            findings.append(Finding(
                "DNZ-S002", "tools/dnzlint/operators.toml", 1, cls,
                f"operators.toml registers {cls} keyed_state=true but "
                f"the class has no snapshot codec flow — stale "
                f"registration (state handling moved or was removed)",
            ))
            continue
        if not e["keyed_state"]:
            if flows:
                findings.append(Finding(
                    "DNZ-S002", e["file"], 1, cls,
                    f"{cls} calls snapshot codecs but operators.toml "
                    f"does not register it keyed_state=true — its "
                    f"snapshot/restore symmetry is unchecked",
                ))
            continue

        snap, snap_where = _side_keys(
            mod, cls, _WRITE_CODECS, bridge_writes=True
        )
        rest, rest_where = _side_keys(mod, cls, _READ_CODECS)
        reads_everywhere = set(rest.strict_read) | set(rest.tolerant_read)

        for key, line in sorted(snap.written.items()):
            if key in reads_everywhere or key in aux_read \
                    or key in _ALLOW_UNREAD:
                continue
            qual = snap_where.get(f"w:{key}", cls)
            findings.append(Finding(
                "DNZ-S001", e["file"], line, qual,
                f"snapshot payload key {key!r} is written by {cls}'s "
                f"snapshot path but no restore path reads it — state "
                f"silently dropped on restore (or a dead field: stop "
                f"writing it)",
            ))
        for key, line in sorted(rest.strict_read.items()):
            if key in snap.written or key in aux_written:
                continue
            qual = rest_where.get(f"r:{key}", cls)
            findings.append(Finding(
                "DNZ-S001", e["file"], line, qual,
                f"restore path reads snapshot key {key!r} strictly "
                f"(no .get default) but no snapshot path writes it — "
                f"restore will KeyError on every real snapshot; write "
                f"the field or read it with a legacy default",
            ))
        if snap.version_lits and rest.version_lits \
                and snap.version_lits != rest.version_lits:
            findings.append(Finding(
                "DNZ-S001", e["file"], 1, cls,
                f"snapshot version literals {sorted(snap.version_lits)} "
                f"!= restore-side literals {sorted(rest.version_lits)} "
                f"— the version was bumped on one side only",
            ))
    return findings
