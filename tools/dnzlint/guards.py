"""DNZ-G001/G002 — guarded-by inference (static lockset discipline).

The lock witness and TSan only see interleavings that actually happen in
a run; a race on a coordinator counter or a live-registry map can sit
unexercised through every soak.  This pass infers the *guarded-by*
relation Eraser computes dynamically, from the AST:

1. **Claim inference** — inside every class that owns a lock attribute,
   an attribute written under a held lock in ANY method (``__init__``
   excluded — the object is not yet shared during construction) is
   *claimed* by that lock.
2. **Violation** — any read or write of a claimed attribute outside a
   region holding one of its claiming locks (again outside
   ``__init__``) is DNZ-G001.  Held sets propagate through same-class
   helper calls exactly like ``locks.py``: a private helper reached
   only from call sites that hold the lock inherits the intersection of
   its callers' held sets, so moving the mutation into ``_apply()``
   does not launder the race.  A private method whose bound reference
   escapes (``x.on_detach = self._cb`` — callback registration) is
   treated as externally callable and inherits nothing.
3. **Escape hatches** — a reasoned ``# dnzlint: allow(unguarded)
   <reason>`` pragma on the access, or a ``guards.toml``
   ``[[unguarded]]`` entry for documented single-writer /
   pre-thread-start fields.  A ``guards.toml`` entry whose
   ``(class, attr)`` is no longer a lock-claimed attribute is itself a
   finding (DNZ-G002) — the registry can only shrink honestly, same
   rule as the baseline.

Scope: the ISSUE-20 surfaces (thread-spawning classes, doctor/HTTP
route owners, ``operators.toml`` operators) all satisfy the actual
trigger — owning a lock — vacuously: claims can only arise from ``with
self._lock:`` regions, so a lock-free class can never fire.  Analyzing
every lock-owning class therefore covers the listed surfaces and any
future one automatically.
"""

from __future__ import annotations

import ast
import dataclasses
from pathlib import Path

from tools.dnzlint import Finding, _parse_toml, iter_python_files, rel_path
from tools.dnzlint.locks import _ModuleScan


@dataclasses.dataclass
class _Access:
    attr: str
    kind: str  # "read" | "write"
    line: int
    held: tuple[str, ...]
    method: str


@dataclasses.dataclass
class _MethodInfo:
    name: str
    accesses: list  # [_Access]
    calls: list  # [(callee_method, held_tuple)]


def load_guards(path: Path) -> list[dict]:
    """``guards.toml`` ``[[unguarded]]`` entries: {class, attr, reason}.
    Reasons are mandatory — an unreasoned exemption defeats the audit
    trail, same contract as baseline.toml."""
    if not path.exists():
        return []
    data = _parse_toml(path)
    out = []
    for entry in data.get("unguarded", []):
        if not (entry.get("class") and entry.get("attr")):
            continue
        if not (entry.get("reason") or "").strip():
            raise ValueError(
                f"guards.toml: entry ({entry.get('class')}, "
                f"{entry.get('attr')}) has no reason — unreasoned "
                f"exemptions defeat the audit trail"
            )
        out.append({
            "class": entry["class"],
            "attr": entry["attr"],
            "reason": entry["reason"].strip(),
        })
    return out


class _ClassWalk:
    """Held-set-aware walk of one class's methods, recording every
    ``self.<attr>`` access, same-class call, and escaped method ref."""

    def __init__(self, rel: str, cls: str, scan: _ModuleScan):
        self.rel = rel
        self.cls = cls
        self.scan = scan
        self.methods: dict[str, _MethodInfo] = {}
        self.escaped: set[str] = set()  # methods whose ref escapes
        self._call_funcs: set[int] = set()  # func nodes of self-calls

    def _resolve_lock(self, expr: ast.AST) -> str | None:
        if isinstance(expr, ast.Name) \
                and expr.id in self.scan.module_locks:
            return f"{self.rel}:{expr.id}"
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name) \
                and expr.value.id == "self" \
                and (self.cls, expr.attr) in self.scan.class_locks:
            return f"{self.cls}.{expr.attr}"
        return None

    def walk_method(self, fn) -> None:
        info = _MethodInfo(fn.name, [], [])
        self.methods[fn.name] = info

        def note_exprs(nodes, held, skip_bodies=False):
            def gen(node):
                for child in ast.iter_child_nodes(node):
                    if skip_bodies and isinstance(child, (
                        ast.With, ast.AsyncWith, ast.For, ast.AsyncFor,
                        ast.While, ast.If, ast.Try, ast.Match,
                        ast.FunctionDef, ast.AsyncFunctionDef,
                        ast.ClassDef, ast.ExceptHandler,
                    )):
                        continue
                    yield child
                    yield from gen(child)

            roots = []
            for n in nodes:
                if isinstance(n, (ast.For, ast.AsyncFor)):
                    roots += [n.target, n.iter]
                elif isinstance(n, ast.While):
                    roots.append(n.test)
                elif isinstance(n, ast.If):
                    roots.append(n.test)
                elif isinstance(n, ast.Match):
                    roots.append(n.subject)
                elif isinstance(n, ast.Try):
                    continue
                else:
                    roots.append(n)
            for r in roots:
                for node in [r] + list(gen(r)):
                    self._note_node(node, info, held)

        def walk(stmts, held):
            for node in stmts:
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    # nested def: runs at an unknown time with an
                    # unknown held set — analyze lock-free-entry
                    walk(node.body, ())
                    continue
                if isinstance(node, (ast.With, ast.AsyncWith)):
                    inner = held
                    for item in node.items:
                        lock = self._resolve_lock(item.context_expr)
                        if lock is not None:
                            inner = inner + (lock,)
                        else:
                            note_exprs([item.context_expr], inner)
                        if item.optional_vars is not None:
                            note_exprs([item.optional_vars], inner)
                    walk(node.body, inner)
                    continue
                note_exprs([node], held, skip_bodies=True)
                if isinstance(node, ast.Match):
                    for case in node.cases:
                        walk(case.body, held)
                    continue
                for attr in ("body", "orelse", "finalbody", "handlers"):
                    sub = getattr(node, attr, None)
                    if sub:
                        if attr == "handlers":
                            for h in sub:
                                walk(h.body, held)
                        else:
                            walk(sub, held)

        walk(fn.body, ())

    def _note_node(self, node, info: _MethodInfo, held) -> None:
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and isinstance(node.func.value, ast.Name) \
                and node.func.value.id == "self":
            info.calls.append((node.func.attr, held))
            # the `self.m` func node is the call, not an escaping
            # bound-method reference — skip it when visited on its own
            self._call_funcs.add(id(node.func))
            return
        if isinstance(node, ast.Subscript) \
                and isinstance(node.ctx, (ast.Store, ast.Del)) \
                and isinstance(node.value, ast.Attribute) \
                and isinstance(node.value.value, ast.Name) \
                and node.value.value.id == "self":
            # self._x[k] = v mutates the guarded container through the
            # attribute — a write for claim purposes, not a bare read
            if (self.cls, node.value.attr) not in self.scan.class_locks:
                info.accesses.append(_Access(
                    node.value.attr, "write", node.lineno, held,
                    info.name,
                ))
            self._call_funcs.add(id(node.value))
            return
        if not (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"):
            return
        if id(node) in self._call_funcs:
            return
        if (self.cls, node.attr) in self.scan.class_locks:
            return  # the lock itself, not guarded data
        if isinstance(node.ctx, (ast.Store, ast.Del)):
            kind = "write"
        else:
            kind = "read"
        info.accesses.append(
            _Access(node.attr, kind, node.lineno, held, info.name)
        )

    def finish(self) -> None:
        """Post-pass: a ``self._m`` read where ``_m`` is a method is a
        bound-reference escape (callback registration), not guarded
        data — drop the access and pin the method externally
        callable."""
        names = set(self.methods)
        for info in self.methods.values():
            kept = []
            for a in info.accesses:
                if a.attr in names:
                    self.escaped.add(a.attr)
                else:
                    kept.append(a)
            info.accesses = kept

    def entry_held(self) -> dict[str, frozenset]:
        """Locks guaranteed held at entry, per method: the intersection
        over intra-class call sites of (caller's entry set + held at the
        site).  Public methods, dunders, and escaped refs are externally
        callable — entry set empty."""
        all_locks = frozenset(
            {f"{self.cls}.{a}" for (c, a) in self.scan.class_locks
             if c == self.cls}
            | {f"{self.rel}:{n}" for n in self.scan.module_locks}
        )
        sites: dict[str, list] = {m: [] for m in self.methods}
        for info in self.methods.values():
            for callee, held in info.calls:
                if callee in sites:
                    sites[callee].append((info.name, held))

        def external(m: str) -> bool:
            return (not m.startswith("_")) or m.startswith("__") \
                or m in self.escaped or not sites[m]

        entry = {
            m: (frozenset() if external(m) else all_locks)
            for m in self.methods
        }
        changed = True
        while changed:
            changed = False
            for m in self.methods:
                if external(m):
                    continue
                acc = all_locks
                for caller, held in sites[m]:
                    acc = acc & (entry[caller] | frozenset(held))
                if acc != entry[m]:
                    entry[m] = acc
                    changed = True
        return entry


def _analyze_class(rel: str, cls_node: ast.ClassDef, scan: _ModuleScan,
                   exempt: set[tuple[str, str]],
                   claimed_out: set[tuple[str, str]]) -> list[Finding]:
    cls = cls_node.name
    cw = _ClassWalk(rel, cls, scan)
    for item in cls_node.body:
        if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            cw.walk_method(item)
    cw.finish()
    entry = cw.entry_held()

    # claims: attr -> {lock: (method, line)} from locked writes outside
    # __init__ (construction precedes sharing)
    claims: dict[str, dict[str, tuple[str, int]]] = {}
    for info in cw.methods.values():
        if info.name == "__init__":
            continue
        for a in info.accesses:
            if a.kind != "write":
                continue
            eff = frozenset(a.held) | entry[a.method]
            for lock in eff:
                claims.setdefault(a.attr, {}).setdefault(
                    lock, (a.method, a.line)
                )
    findings: list[Finding] = []
    for attr, locks in sorted(claims.items()):
        claimed_out.add((cls, attr))
        if (cls, attr) in exempt:
            continue
        for info in cw.methods.values():
            if info.name == "__init__":
                continue
            for a in info.accesses:
                if a.attr != attr:
                    continue
                eff = frozenset(a.held) | entry[a.method]
                if eff & set(locks):
                    continue
                lock, (wm, wl) = sorted(locks.items())[0]
                findings.append(Finding(
                    "DNZ-G001", rel, a.line, f"{cls}.{a.method}",
                    f"{a.kind} of self.{attr} without holding {lock} — "
                    f"the attribute is claimed by that lock (written "
                    f"under it in {cls}.{wm}:{wl}); hold the lock, or "
                    f"document the single-writer contract via "
                    f"allow(unguarded) / guards.toml",
                ))
    return findings


def run(root: Path, guards_path: Path | None = None) -> list[Finding]:
    here = Path(__file__).resolve().parent
    if guards_path is None:
        guards_path = here / "guards.toml"
    entries = load_guards(guards_path)
    exempt = {(e["class"], e["attr"]) for e in entries}

    findings: list[Finding] = []
    claimed: set[tuple[str, str]] = set()
    pkg = root.name
    for path in iter_python_files(root):
        rel = rel_path(path, root)
        tree = ast.parse(path.read_text(), filename=str(path))
        scan = _ModuleScan(rel, pkg)
        scan.scan(tree)
        has_locks = bool(scan.class_locks) or bool(scan.module_locks)
        if not has_locks:
            continue
        for node in tree.body:
            if isinstance(node, ast.ClassDef):
                findings += _analyze_class(
                    rel, node, scan, exempt, claimed
                )

    # registry drift: an exemption for an attribute no lock claims any
    # more is stale — delete it so the registry only shrinks honestly
    for e in entries:
        if (e["class"], e["attr"]) not in claimed:
            findings.append(Finding(
                "DNZ-G002", "tools/dnzlint/guards.toml", 1,
                f"{e['class']}.{e['attr']}",
                f"guards.toml exempts {e['class']}.{e['attr']} but no "
                f"lock claims that attribute in the tree — the field "
                f"was fixed, renamed, or removed; delete the entry",
            ))
    return findings
