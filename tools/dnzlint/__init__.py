"""dnzlint — project-specific static analysis for the threaded runtime.

Generic linters know nothing about THIS engine's invariants: which
attributes are locks, which calls block, which functions are the
vectorized hot paths PRs 2-3 paid for, which string literals must name a
registered fault-injection site.  dnzlint encodes those invariants as
AST passes over ``denormalized_tpu/`` and runs as a tier-1 test gate
(``tests/test_lint.py``), so a regression is a test failure with a
file:line and a rule id — not a soak failure three PRs later.

Passes (rule catalog in ``docs/static_analysis.md``):

==========  ==================  =========================================
rule id     slug                what it flags
==========  ==================  =========================================
DNZ-L001    lock-order-cycle    a cycle in the static lock-acquisition
                                graph (two code paths that take the same
                                locks in opposite orders)
DNZ-L002    blocking-under-lock a blocking call (``time.sleep``, queue
                                get/put, thread join/wait, subprocess,
                                ctypes library load or native ``lib.*``
                                call, ``faults.inject`` latency site)
                                made while a lock is held
DNZ-E001    broad-except        ``except Exception``/``BaseException``/
                                bare ``except`` that neither re-raises
                                nor converts to a DenormalizedError
DNZ-F001    unknown-fault-site  ``faults.inject("x")`` where ``"x"`` is
                                not a key of ``faults.SITES``
DNZ-F002    missing-fault-site  a site registered in ``faults.SITES`` /
                                ``SITE_MODULES`` with no ``inject`` call
                                in its declared module
DNZ-H001    hot-loop            a per-row construct (``for``/``while``,
                                ``.tolist()``) inside a registered
                                hot-path function
DNZ-H002    hash-tuple          ``hash(...)`` inside a registered
                                hot-path function (the pre-vectorization
                                collision bug class, PARITY.md Round-6)
DNZ-M001    metric-registry     an ``obs.counter/gauge/histogram`` call
                                whose name literal keys nothing in
                                ``obs/catalog.py`` (or mismatches its
                                declared kind), a declared instrument no
                                module binds, or a catalog entry
                                violating the naming convention
DNZ-M002    handoff-instruments an operator class in ``physical/`` that
                                overrides the batch-processing path
                                without binding the doctor's handoff
                                instruments both ways (``_doctor_input``
                                / ``_note_input_wait`` upstream,
                                ``_note_batch`` busy bracket), or an
                                ``operators.toml`` registration drifting
                                from the tree
DNZ-G001    unguarded           a read/write of a ``self._x`` attribute
                                that some lock *claims* (it is written
                                under that lock elsewhere in the class)
                                made outside any claiming-lock region,
                                held sets resolved through same-class
                                helpers (static guarded-by inference)
DNZ-G002    guard-registry      a ``guards.toml`` exemption whose
                                ``(class, attr)`` no lock claims any
                                more — stale registry entry
DNZ-D001    replay-impure       a nondeterminism source (``time.*``,
                                ``random``, ``uuid``, ``os.urandom``,
                                salted ``hash()``, ``id()``, unordered
                                ``set`` iteration) reachable from a
                                ``replaypaths.toml``-registered
                                replay-critical kernel, transitively to
                                fixpoint through package-internal calls
DNZ-D002    replay-registry     a registered replay path whose symbol no
                                longer exists, or a snapshot-codec entry
                                point (``pack_snapshot``/``put_json``
                                caller) not covered by the registry
DNZ-S001    snapshot-asym       a snapshot payload field written by a
                                keyed operator's snapshot path but never
                                read by its restore path, or read in
                                restore but never written — without a
                                legacy-layout ``.get(k, default)``
DNZ-S002    snapshot-registry   a ``physical/`` class with snapshot
                                codec flows not registered
                                ``keyed_state`` in ``operators.toml``,
                                or a ``keyed_state`` registration whose
                                class has no snapshot flow
==========  ==================  =========================================

Suppression is explicit and reasoned, never blanket:

- inline pragma on the flagged line (or the line above)::

      except Exception:  # dnzlint: allow(broad-except) destructor must never raise

- a ``baseline.toml`` entry keyed by ``(rule, file, symbol)`` — line
  numbers shift, symbols don't — each carrying a ``reason``.  The gate
  therefore enforces zero NEW findings while keeping every accepted one
  auditable in one file.

Run locally::

    python -m tools.dnzlint denormalized_tpu

The package is stdlib-only (ast + tomllib) so the gate can never be
skipped for a missing dependency.
"""

from __future__ import annotations

import dataclasses
import sys
from pathlib import Path

if sys.version_info >= (3, 11):
    import tomllib as _toml
else:  # pragma: no cover — 3.10 image ships tomli via pip? no: parse manually
    _toml = None

#: rule id -> pragma slug (what goes inside ``allow(...)``)
RULES = {
    "DNZ-L001": "lock-order-cycle",
    "DNZ-L002": "blocking-under-lock",
    "DNZ-E001": "broad-except",
    "DNZ-F001": "unknown-fault-site",
    "DNZ-F002": "missing-fault-site",
    "DNZ-H001": "hot-loop",
    "DNZ-H002": "hash-tuple",
    "DNZ-M001": "metric-registry",
    "DNZ-M002": "handoff-instruments",
    "DNZ-G001": "unguarded",
    "DNZ-G002": "guard-registry",
    "DNZ-D001": "replay-impure",
    "DNZ-D002": "replay-registry",
    "DNZ-S001": "snapshot-asym",
    "DNZ-S002": "snapshot-registry",
}
SLUG_TO_RULE = {v: k for k, v in RULES.items()}


@dataclasses.dataclass(frozen=True)
class Finding:
    """One lint finding, printable as ``file:line [rule] symbol: message``.

    ``symbol`` is the stable anchor (``Class.method``, ``function``, or a
    pass-specific identity like a cycle's sorted node list) — it is what
    baseline entries match on, so findings survive unrelated line churn.
    """

    rule: str
    path: str  # relative to the scanned root's parent (repo-style)
    line: int
    symbol: str
    message: str

    def key(self) -> tuple[str, str, str]:
        return (self.rule, self.path, self.symbol)

    def render(self) -> str:
        return f"{self.path}:{self.line} [{self.rule}] {self.symbol}: {self.message}"


def _parse_toml(path: Path) -> dict:
    if _toml is not None:
        with open(path, "rb") as f:
            return _toml.load(f)
    # minimal fallback for [[entry]] tables of string key/values (the only
    # shapes dnzlint's own config files use) on pythons without tomllib
    out: dict = {}
    current: dict | None = None
    for raw in path.read_text().splitlines():
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        if line.startswith("[[") and line.endswith("]]"):
            name = line[2:-2].strip()
            current = {}
            out.setdefault(name, []).append(current)
        elif "=" in line and current is not None:
            k, _, v = line.partition("=")
            current[k.strip()] = v.strip().strip('"')
    return out


def load_baseline(path: Path) -> dict[tuple[str, str, str], str]:
    """``baseline.toml`` -> {(rule, file, symbol): reason}.  Every entry
    MUST carry a non-empty reason — an unreasoned suppression is itself
    an error (the whole point is auditability)."""
    if not path.exists():
        return {}
    data = _parse_toml(path)
    out: dict[tuple[str, str, str], str] = {}
    for entry in data.get("suppress", []):
        rule = entry.get("rule", "")
        file = entry.get("file", "")
        symbol = entry.get("symbol", "")
        reason = (entry.get("reason") or "").strip()
        if rule not in RULES:
            raise ValueError(f"baseline: unknown rule {rule!r} for {file}")
        if not reason:
            raise ValueError(
                f"baseline: entry ({rule}, {file}, {symbol}) has no reason "
                f"— unreasoned suppressions defeat the audit trail"
            )
        out[(rule, file, symbol)] = reason
    return out


def iter_python_files(root: Path):
    for p in sorted(root.rglob("*.py")):
        if "__pycache__" in p.parts:
            continue
        yield p


def run_all(
    root: Path,
    *,
    baseline_path: Path | None = None,
    hotpaths_path: Path | None = None,
    operators_path: Path | None = None,
    guards_path: Path | None = None,
    replaypaths_path: Path | None = None,
) -> tuple[list[Finding], list[Finding], list[tuple]]:
    """Run every pass over the package at ``root``.

    Returns ``(new, suppressed, stale_baseline)``: findings not covered
    by pragma or baseline, findings a baseline entry absorbed, and
    baseline entries that matched nothing (candidates for deletion —
    reported so the baseline can only shrink honestly).
    """
    from tools.dnzlint import (
        excepts,
        faultsites,
        guards,
        handoff,
        hotpath,
        locks,
        metricsreg,
        replay,
        snapshots,
    )
    from tools.dnzlint.pragmas import PragmaIndex

    root = Path(root)
    here = Path(__file__).resolve().parent
    if baseline_path is None:
        baseline_path = here / "baseline.toml"
    if hotpaths_path is None:
        hotpaths_path = here / "hotpaths.toml"
    baseline = load_baseline(baseline_path)

    findings: list[Finding] = []
    pragma_index = PragmaIndex()
    for path in iter_python_files(root):
        pragma_index.scan(path, rel_path(path, root))
    findings += pragma_index.malformed  # reasonless/unknown-slug pragmas
    findings += locks.run(root)
    findings += excepts.run(root)
    findings += faultsites.run(root)
    findings += metricsreg.run(root)
    findings += handoff.run(root, operators_path)
    findings += hotpath.run(root, hotpaths_path)
    findings += guards.run(root, guards_path)
    findings += replay.run(root, replaypaths_path)
    findings += snapshots.run(root, operators_path)

    new: list[Finding] = []
    suppressed: list[Finding] = []
    used_baseline: set[tuple[str, str, str]] = set()
    for f in findings:
        if pragma_index.allows(f):
            suppressed.append(f)
        elif f.key() in baseline:
            suppressed.append(f)
            used_baseline.add(f.key())
        else:
            new.append(f)
    stale = [k for k in baseline if k not in used_baseline]
    return new, suppressed, stale


def rel_path(path: Path, root: Path) -> str:
    """Repo-style path: ``<root.name>/sub/file.py``."""
    return str(Path(root.name) / path.relative_to(root))
