"""DNZ-D001/D002 — replay-determinism purity.

Every soak from SOAK_KAFKA through SOAK_CLUSTER asserts byte-identical
replay/restore; a ``time.time()`` smuggled into a snapshot encoder or a
set-ordered loop feeding a frame body breaks that contract *hours* into
a differential soak.  The mergeable-summaries discipline says the same
property is checkable at the AST: a replay-critical kernel must be a
pure function of its inputs.

``replaypaths.toml`` registers every replay-critical kernel and codec —
snapshot encode/decode in the keyed operators, ``cluster/framing.py`` /
``hashing.py`` / ``rescale.py``, ``ops/sketches.py``, the
``ops/slice_store.py`` fold paths, the checkpoint manifest writers.
This pass pins each registered symbol, **transitively to fixpoint
through package-internal calls** (the call graph ``locks.py`` already
resolves), free of:

- ``time.*`` calls (wall or monotonic — both vary across replays),
- ``random`` / ``np.random`` / ``secrets``,
- ``uuid.*``, ``os.urandom``,
- salted builtin ``hash()`` (PYTHONHASHSEED varies per process — use
  ``ops.sketches.stable_hash64``) and ``id()`` (address-dependent),
- iteration over an unordered ``set`` (``for x in {..}``, ``set(...)``,
  a local assigned a set, or set algebra) — iterate ``sorted(...)`` or
  a list-backed structure instead.  Plain ``dict`` iteration is NOT
  flagged: insertion order is a language guarantee and e.g. the UDAF
  frame codec deliberately uses "dict order IS emission row order".

Both drift directions fire (same rule as hotpaths/fault sites):

- DNZ-D002 on the config: a registered symbol the tree no longer
  defines (renamed kernel ⇒ the pin silently evaporates), and
- DNZ-D002 on the tree: a snapshot-codec entry point — any function
  calling ``pack_snapshot`` / ``unpack_snapshot`` / ``put_snapshot`` /
  ``get_snapshot`` / ``put_json`` / ``get_json`` — that the registry's
  transitive closure does not cover (new codec dodging the pin).

The closure deliberately does NOT descend into ``obs/`` (telemetry
reads wall clocks by design and never feeds replayed bytes) or
``faults.py`` (test-only injection machinery, gated off in production
replays).  A registered kernel *directly* inside those trees would
still be scanned.
"""

from __future__ import annotations

import ast
from pathlib import Path

from tools.dnzlint import Finding, _parse_toml
from tools.dnzlint.hotpath import _find_function
from tools.dnzlint.locks import _Analysis

#: call-closure boundary: reached units under these prefixes are not
#: descended into (side channels that never feed replayed bytes)
_CLOSURE_EXCLUDE = ("obs/", "faults.py")

#: terminal callee names that make a function a snapshot-codec entry
#: point (the reverse-drift trigger for DNZ-D002)
_CODEC_NAMES = frozenset({
    "pack_snapshot", "unpack_snapshot",
    "put_snapshot", "get_snapshot",
    "put_json", "get_json",
})

#: time.* members considered pure (no clock read)
_TIME_PURE = frozenset({"strptime", "struct_time"})


def load_paths(path: Path) -> list[dict]:
    """``replaypaths.toml`` ``[[path]]`` entries: {file, qualname, note}.
    ``note`` is mandatory — it becomes the docs registry table row, and
    an unexplained pin defeats the audit trail."""
    if not path.exists():
        return []
    data = _parse_toml(path)
    out = []
    for entry in data.get("path", []):
        if not (entry.get("file") and entry.get("qualname")):
            continue
        if not (entry.get("note") or "").strip():
            raise ValueError(
                f"replaypaths.toml: entry ({entry.get('file')}, "
                f"{entry.get('qualname')}) has no note — unexplained "
                f"pins defeat the audit trail"
            )
        out.append({
            "file": entry["file"],
            "qualname": entry["qualname"],
            "note": entry["note"].strip(),
        })
    return out


def _excluded(rel_in_pkg: str) -> bool:
    return rel_in_pkg.startswith(_CLOSURE_EXCLUDE[0]) \
        or rel_in_pkg == _CLOSURE_EXCLUDE[1]


class _ImpurityScan:
    """One function body (nested defs included — they are lexically part
    of the kernel) scanned for nondeterminism sources."""

    def __init__(self, rel: str, qual: str, root_entry: str):
        self.rel = rel
        self.qual = qual
        self.root_entry = root_entry
        self.findings: list[Finding] = []

    def _emit(self, line: int, what: str, why: str) -> None:
        via = "" if self.root_entry == self.qual else \
            f" (reached from registered {self.root_entry})"
        self.findings.append(Finding(
            "DNZ-D001", self.rel, line, self.qual,
            f"{what} inside replay-critical path{via} — {why}",
        ))

    def scan(self, fn: ast.AST) -> list[Finding]:
        set_locals = self._set_locals(fn)
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                self._check_call(node)
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                self._check_iter(node.iter, node.lineno, set_locals)
            elif isinstance(node, (ast.ListComp, ast.DictComp,
                                   ast.GeneratorExp)):
                # SetComp generators are deliberately exempt: building a
                # set from unordered iteration is order-insensitive
                for gen in node.generators:
                    self._check_iter(gen.iter, node.lineno, set_locals)
        return self.findings

    # -- which locals hold sets ------------------------------------------
    @staticmethod
    def _is_set_expr(expr: ast.AST) -> bool:
        if isinstance(expr, (ast.Set, ast.SetComp)):
            return True
        if isinstance(expr, ast.Call) and isinstance(expr.func, ast.Name) \
                and expr.func.id in ("set", "frozenset"):
            return True
        if isinstance(expr, ast.BinOp) and isinstance(
            expr.op, (ast.BitAnd, ast.BitOr, ast.Sub, ast.BitXor)
        ):
            # set algebra — only meaningful when an operand is set-ish;
            # treat as set only if either side syntactically is
            return _ImpurityScan._is_set_expr(expr.left) \
                or _ImpurityScan._is_set_expr(expr.right)
        return False

    @classmethod
    def _set_locals(cls, fn: ast.AST) -> set[str]:
        out: set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and cls._is_set_expr(node.value):
                out.add(node.targets[0].id)
            elif isinstance(node, ast.AnnAssign) \
                    and isinstance(node.target, ast.Name) \
                    and node.value is not None \
                    and cls._is_set_expr(node.value):
                out.add(node.target.id)
        return out

    # -- nondeterministic calls ------------------------------------------
    def _check_call(self, node: ast.Call) -> None:
        fn = node.func
        if isinstance(fn, ast.Name):
            if fn.id == "hash":
                self._emit(
                    node.lineno, "builtin hash()",
                    "PYTHONHASHSEED salts str/bytes hashes per process; "
                    "use ops.sketches.stable_hash64",
                )
            elif fn.id == "id":
                self._emit(
                    node.lineno, "id()",
                    "object addresses differ across processes/replays",
                )
            return
        if not isinstance(fn, ast.Attribute):
            return
        base = fn.value
        if isinstance(base, ast.Name):
            if base.id == "time" and fn.attr not in _TIME_PURE:
                self._emit(
                    node.lineno, f"time.{fn.attr}()",
                    "clock reads differ across replays; thread event "
                    "time / explicit parameters through instead",
                )
            elif base.id in ("random", "secrets"):
                self._emit(
                    node.lineno, f"{base.id}.{fn.attr}()",
                    "nondeterministic entropy in a replay-critical path",
                )
            elif base.id == "uuid":
                self._emit(
                    node.lineno, f"uuid.{fn.attr}()",
                    "fresh uuids differ per run; derive ids from "
                    "deterministic inputs",
                )
            elif base.id == "os" and fn.attr == "urandom":
                self._emit(
                    node.lineno, "os.urandom()",
                    "nondeterministic entropy in a replay-critical path",
                )
        elif isinstance(base, ast.Attribute) \
                and isinstance(base.value, ast.Name) \
                and base.value.id in ("np", "numpy") \
                and base.attr == "random":
            self._emit(
                node.lineno, f"np.random.{fn.attr}()",
                "nondeterministic entropy in a replay-critical path",
            )

    # -- unordered iteration ---------------------------------------------
    def _check_iter(self, it: ast.AST, line: int, set_locals: set[str]) -> None:
        set_ish = self._is_set_expr(it) or (
            isinstance(it, ast.Name) and it.id in set_locals
        )
        if not set_ish and isinstance(it, ast.Call) \
                and isinstance(it.func, ast.Name) \
                and it.func.id in ("list", "tuple") and it.args:
            # list(s)/tuple(s) preserves the set's arbitrary order —
            # laundering, not fixing
            a = it.args[0]
            set_ish = self._is_set_expr(a) or (
                isinstance(a, ast.Name) and a.id in set_locals
            )
        if set_ish:
            self._emit(
                line, "iteration over an unordered set",
                "set order is hash-seed-dependent and feeds this "
                "kernel's output; iterate sorted(...) or keep a "
                "list-backed structure",
            )


def _closure(ana: _Analysis, roots: dict[str, str]) -> dict[str, str]:
    """Transitive call closure from registered uids.  Returns
    {uid: registered_root_qualname}; first (registration-order) root
    wins for attribution.  Stops at the obs/faults boundary."""
    pkg_prefix = ana.pkg + "/"

    def rel_in_pkg(uid: str) -> str:
        rel = uid.split(":", 1)[0]
        return rel[len(pkg_prefix):] if rel.startswith(pkg_prefix) else rel

    reached: dict[str, str] = {}
    stack = list(roots.items())
    while stack:
        uid, root_q = stack.pop()
        if uid in reached:
            continue
        reached[uid] = root_q
        unit = ana.units.get(uid)
        if unit is None:
            continue
        for callee, _line, _held in unit.calls:
            if callee in reached or callee not in ana.units:
                continue
            if _excluded(rel_in_pkg(callee)):
                continue
            stack.append((callee, root_q))
    return reached


def _nested_uids(ana: _Analysis, uid: str) -> list[str]:
    """A unit's lexically nested defs (``uid.inner...``) — scanned as
    part of the kernel, and counted as covered for the reverse drift."""
    prefix = uid + "."
    return [u for u in ana.units if u.startswith(prefix)]


def run(root: Path, replaypaths_path: Path | None = None) -> list[Finding]:
    here = Path(__file__).resolve().parent
    if replaypaths_path is None:
        replaypaths_path = here / "replaypaths.toml"
    entries = load_paths(replaypaths_path)

    ana = _Analysis(root)
    ana.collect()

    findings: list[Finding] = []
    roots: dict[str, str] = {}
    for e in entries:
        rel = e["file"]
        uid = f"{rel}:{e['qualname']}"
        if uid not in ana.units:
            findings.append(Finding(
                "DNZ-D002", "tools/dnzlint/replaypaths.toml", 1,
                f"{rel}:{e['qualname']}",
                f"replaypaths.toml registers {e['qualname']} but "
                f"{rel} does not define it — update the registry for "
                f"the moved/renamed kernel, or delete the entry",
            ))
            continue
        roots.setdefault(uid, e["qualname"])

    reached = _closure(ana, roots)
    # nested defs of reached units are part of those kernels
    covered = set(reached)
    for uid in list(reached):
        for nested in _nested_uids(ana, uid):
            covered.add(nested)

    # DNZ-D001: impurity scan over every unit in the closure (the scan
    # walks nested defs itself, so nested uids need no separate scan)
    for uid in sorted(reached):
        rel, qual = uid.split(":", 1)
        if "." in qual and any(
            uid.startswith(other + ".") for other in reached if other != uid
        ):
            continue  # lexically inside an already-scanned unit
        tree = ana.trees.get(rel)
        if tree is None:
            continue
        fn = _find_function(tree, qual)
        if fn is None:
            continue
        findings += _ImpurityScan(rel, qual, reached[uid]).scan(fn)

    # DNZ-D002 reverse drift: snapshot-codec entry points outside the
    # registry closure
    pkg_prefix = ana.pkg + "/"
    for uid, unit in sorted(ana.units.items()):
        if uid in covered:
            continue
        rel = unit.rel
        rel_in = rel[len(pkg_prefix):] if rel.startswith(pkg_prefix) else rel
        if _excluded(rel_in):
            continue
        qual = uid.split(":", 1)[1]
        tree = ana.trees.get(rel)
        if tree is None:
            continue
        fn = _find_function(tree, qual)
        if fn is None:
            continue
        hit = None
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                f = node.func
                name = f.attr if isinstance(f, ast.Attribute) else (
                    f.id if isinstance(f, ast.Name) else None
                )
                if name in _CODEC_NAMES:
                    hit = (name, node.lineno)
                    break
        if hit is not None:
            findings.append(Finding(
                "DNZ-D002", rel, hit[1], qual,
                f"{qual} calls {hit[0]}() but is not covered by the "
                f"replaypaths.toml transitive closure — a snapshot "
                f"codec outside the determinism pin; register it (or "
                f"the caller that owns the path)",
            ))
    return findings


def replay_path_table(replaypaths_path: Path | None = None) -> str:
    """The docs registry table (markdown), generated from
    ``replaypaths.toml`` — drift between this and
    ``docs/static_analysis.md`` is pinned by test, same pattern as the
    fault-site table."""
    here = Path(__file__).resolve().parent
    if replaypaths_path is None:
        replaypaths_path = here / "replaypaths.toml"
    entries = load_paths(replaypaths_path)
    lines = [
        "| file | symbol | why it is replay-critical |",
        "| --- | --- | --- |",
    ]
    for e in sorted(entries, key=lambda e: (e["file"], e["qualname"])):
        lines.append(
            f"| `{e['file']}` | `{e['qualname']}` | {e['note']} |"
        )
    return "\n".join(lines)
