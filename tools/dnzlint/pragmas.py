"""Inline suppression pragmas.

Syntax (comment on the flagged line, or on the line directly above when
the construct spans the line — e.g. a decorator-less ``def`` or a long
``with``)::

    # dnzlint: allow(<slug>) <reason>

The slug is the rule's short name (``broad-except``, ``hot-loop``, ...;
see :data:`tools.dnzlint.RULES`) and the reason is REQUIRED: a pragma
with no reason does not suppress, it is reported as the original finding
(an unexplained mute is exactly the "silently swallowed" pattern the
linter exists to kill).
"""

from __future__ import annotations

import re
from pathlib import Path

from tools.dnzlint import RULES, SLUG_TO_RULE, Finding

_PRAGMA_RE = re.compile(
    r"#\s*dnzlint:\s*allow\(([a-z0-9-]+)\)\s*(.*)$"
)


class PragmaIndex:
    """All pragmas of a scanned tree: {(rel_path, line) -> (rule, reason)}."""

    def __init__(self) -> None:
        self._by_line: dict[tuple[str, int], tuple[str, str]] = {}
        self.malformed: list[Finding] = []

    def scan(self, path: Path, rel: str) -> None:
        for lineno, text in enumerate(path.read_text().splitlines(), 1):
            m = _PRAGMA_RE.search(text)
            if not m:
                continue
            slug, reason = m.group(1), m.group(2).strip()
            rule = SLUG_TO_RULE.get(slug)
            if rule is None:
                self.malformed.append(Finding(
                    "DNZ-E001", rel, lineno, f"pragma:{slug}",
                    f"pragma names unknown rule slug {slug!r} "
                    f"(known: {sorted(SLUG_TO_RULE)})",
                ))
                continue
            if not reason:
                self.malformed.append(Finding(
                    rule, rel, lineno, f"pragma:{slug}",
                    f"allow({slug}) pragma carries no reason — reasonless "
                    f"suppressions do not suppress",
                ))
                continue
            self._by_line[(rel, lineno)] = (rule, reason)

    def allows(self, finding: Finding) -> bool:
        """A pragma covers a finding when it names the finding's rule and
        sits on the finding's line or the line directly above it."""
        for line in (finding.line, finding.line - 1):
            hit = self._by_line.get((finding.path, line))
            if hit is not None and hit[0] == finding.rule:
                return True
        return False


assert set(SLUG_TO_RULE.values()) == set(RULES)
