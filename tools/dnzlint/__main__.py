"""CLI: ``python -m tools.dnzlint [path] [options]``.

Exit codes: 0 = clean (after pragmas + baseline), 1 = new findings,
2 = usage/config error.  ``--fault-site-table`` prints the generated
markdown fault-site table (what ``docs/fault_tolerance.md`` embeds) and
exits — used by ``tools/lint.sh`` and ``tests/test_lint.py`` to pin the
docs against the verified site inventory; ``--replay-path-table`` does
the same for the replay-path registry in ``docs/static_analysis.md``.

``--format=json`` (and ``--report FILE``, which writes the same JSON
alongside the text output) emits machine-readable findings: each is
``{rule, file, line, symbol, reason}`` where ``reason`` is the finding
message, plus ``wall_clock_s`` so CI can watch the lint budget.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

from tools.dnzlint import load_baseline, run_all


def _finding_obj(f) -> dict:
    return {
        "rule": f.rule,
        "file": f.path,
        "line": f.line,
        "symbol": f.symbol,
        "reason": f.message,
    }


def _report(new, suppressed, stale, n_base, wall_s, root) -> dict:
    return {
        "root": str(root),
        "wall_clock_s": round(wall_s, 3),
        "counts": {
            "new": len(new),
            "suppressed": len(suppressed),
            "baseline_entries": n_base,
            "stale_baseline": len(stale),
        },
        "new": [_finding_obj(f) for f in
                sorted(new, key=lambda f: (f.path, f.line, f.rule))],
        "suppressed": [_finding_obj(f) for f in
                       sorted(suppressed,
                              key=lambda f: (f.path, f.line, f.rule))],
        "stale_baseline": [list(k) for k in sorted(stale)],
    }


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.dnzlint",
        description="project-specific static analysis "
                    "(rule catalog: docs/static_analysis.md)",
    )
    parser.add_argument(
        "root", nargs="?", default="denormalized_tpu",
        help="package directory to scan (default: denormalized_tpu)",
    )
    parser.add_argument(
        "--baseline", default=None,
        help="baseline.toml path (default: tools/dnzlint/baseline.toml)",
    )
    parser.add_argument(
        "--no-baseline", action="store_true",
        help="ignore the baseline (show every finding)",
    )
    parser.add_argument(
        "--hotpaths", default=None,
        help="hotpaths.toml path (default: tools/dnzlint/hotpaths.toml)",
    )
    parser.add_argument(
        "--format", choices=("text", "json"), default="text",
    )
    parser.add_argument(
        "--show-suppressed", action="store_true",
        help="also list findings absorbed by pragmas/baseline",
    )
    parser.add_argument(
        "--fault-site-table", action="store_true",
        help="print the generated fault-site markdown table and exit",
    )
    parser.add_argument(
        "--metric-catalog", action="store_true",
        help="print the generated metric-catalog markdown table "
             "(docs/observability.md embeds it) and exit",
    )
    parser.add_argument(
        "--replay-path-table", action="store_true",
        help="print the generated replay-path registry markdown table "
             "(docs/static_analysis.md embeds it) and exit",
    )
    parser.add_argument(
        "--report", default=None, metavar="FILE",
        help="also write the JSON report to FILE (lint.sh writes "
             "LINT_REPORT.json this way)",
    )
    args = parser.parse_args(argv)

    root = Path(args.root)
    if not root.is_dir():
        print(f"dnzlint: {root} is not a directory", file=sys.stderr)
        return 2

    if args.fault_site_table:
        from tools.dnzlint.faultsites import fault_site_table

        print(fault_site_table(root))
        return 0

    if args.metric_catalog:
        from tools.dnzlint.metricsreg import metric_catalog_table

        print(metric_catalog_table(root))
        return 0

    if args.replay_path_table:
        from tools.dnzlint.replay import replay_path_table

        print(replay_path_table())
        return 0

    here = Path(__file__).resolve().parent
    baseline_path = (
        Path(args.baseline) if args.baseline else here / "baseline.toml"
    )
    t0 = time.perf_counter()
    try:
        if args.no_baseline:
            new, suppressed, stale = run_all(
                root,
                baseline_path=Path("/nonexistent"),
                hotpaths_path=Path(args.hotpaths) if args.hotpaths else None,
            )
        else:
            new, suppressed, stale = run_all(
                root,
                baseline_path=baseline_path,
                hotpaths_path=Path(args.hotpaths) if args.hotpaths else None,
            )
    except (ValueError, SyntaxError) as e:
        print(f"dnzlint: {e}", file=sys.stderr)
        return 2
    wall_s = time.perf_counter() - t0

    n_base = len(load_baseline(baseline_path)) if not args.no_baseline else 0
    report = _report(new, suppressed, stale, n_base, wall_s, root)
    if args.report:
        Path(args.report).write_text(json.dumps(report, indent=2) + "\n")

    if args.format == "json":
        print(json.dumps(report, indent=2))
        return 1 if new else 0

    for f in sorted(new, key=lambda f: (f.path, f.line, f.rule)):
        print(f.render())
    if args.show_suppressed:
        for f in sorted(suppressed, key=lambda f: (f.path, f.line, f.rule)):
            print(f"suppressed: {f.render()}")
    if stale:
        # stale entries don't fail the run (a fix may land before the
        # baseline edit in the same PR) but they must be visible: a
        # baseline should only ever shrink honestly
        for rule, file, symbol in sorted(stale):
            print(
                f"stale baseline entry: ({rule}, {file}, {symbol}) "
                f"matched no finding — delete it",
                file=sys.stderr,
            )
    print(
        f"dnzlint: {len(new)} new finding(s), "
        f"{len(suppressed)} suppressed "
        f"({n_base} baseline entrie(s), rest pragmas), "
        f"{len(stale)} stale baseline entrie(s) "
        f"[{wall_s:.1f}s]",
        file=sys.stderr,
    )
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
