"""DNZ-L001/L002 — lock discipline for the threaded runtime.

The engine's concurrency is a set of small, ad-hoc ``threading.Lock``s
(prefetch swap/budget locks, the native build locks, the channel
registry, the fault plan's event lock, the orchestrator epoch lock).
None of them is documented as part of a global order — so nothing stops
a future edit from taking two of them in opposite orders on two paths,
and nothing flags an I/O call that turns a millisecond critical section
into a seconds-long convoy.  This pass builds the static story:

1. **Lock inventory** — every ``threading.Lock/RLock/Condition`` bound
   to a module global or a ``self.<attr>`` becomes a node, identified
   structurally (``module.py:NAME`` or ``Class.attr``) so all instances
   of a class share one node, like a lock *class* in a runtime witness.
2. **Region extraction** — every ``with <lock>:`` in every function,
   tracking the held set through nesting.
3. **Call graph** — calls made while holding a lock are resolved (same
   class methods, ``self.<attr>``-typed objects via constructor
   assignments, package-internal imports) and each callee's *effective*
   acquisitions (transitive, computed to fixpoint) become edges
   ``held -> acquired``.
4. **DNZ-L001** — a cycle among those edges (including a plain-Lock
   self-edge, which is a self-deadlock: ``Lock`` is not reentrant).
5. **DNZ-L002** — a blocking call inside a held region: ``time.sleep``,
   queue ``get``/``put``, ``join``/``wait``/``acquire``/``result``,
   socket ops (``.connect``/``.accept``/``.recv``/``.sendall`` plus the
   module-level ``socket.create_connection``/``socket.getaddrinfo``
   dial helpers), ``selectors`` ``.select`` polls, ``subprocess.*``,
   ``ctypes.CDLL/PyDLL`` loads, calls on native library handles
   (``self._lib.*`` — these drop the GIL and can block in foreign
   code), and ``faults.inject`` (a latency rule sleeps at the site).
   The exchange redial loop (dial + hello + backoff sleep) is the
   motivating surface: any of those calls reached while an engine lock
   is held turns one slow peer into a stall for every sender.

Static resolution is deliberately conservative: an edge is only drawn
when the callee resolves unambiguously, so the pass under-reports rather
than crying wolf.  The runtime companion
(``denormalized_tpu/common/lockwitness.py``) covers the dynamic
remainder.
"""

from __future__ import annotations

import ast
import dataclasses
from pathlib import Path

from tools.dnzlint import Finding, iter_python_files, rel_path

_LOCK_CTORS = {"Lock", "RLock", "Condition"}
_BLOCKING_ATTRS = {
    "join", "wait", "acquire", "result", "recv", "send", "sendall",
    "accept", "connect", "select",
}
_QUEUE_RECV = {"get", "put"}
_SUBPROCESS_FNS = {"run", "call", "check_call", "check_output", "Popen"}
_NATIVE_HANDLES = {"_lib", "lib", "_libref", "pylib", "_LIB"}


def _lock_ctor_kind(call: ast.AST) -> str | None:
    if not isinstance(call, ast.Call):
        return None
    fn = call.func
    if isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name) \
            and fn.value.id == "threading" and fn.attr in _LOCK_CTORS:
        return fn.attr
    if isinstance(fn, ast.Name) and fn.id in _LOCK_CTORS:
        return fn.id
    return None


@dataclasses.dataclass
class _Unit:
    """One callable (module function or method) and what it does."""

    uid: str  # "rel:qualname"
    rel: str
    acquires: list  # [(lock, lineno)] — with-statements in this unit
    calls: list  # [(callee_ref, lineno, held_tuple)]
    blocking: list  # [(desc, lineno, held_tuple)] — under a held lock
    blocking_all: list  # [(desc, lineno)] — every blocking-ish call
    nest_edges: list  # [(held_lock, acquired_lock, lineno)]


class _ModuleScan(ast.NodeVisitor):
    """First pass over one module: lock definitions, classes, attr types,
    import aliases."""

    def __init__(self, rel: str, pkg: str):
        self.rel = rel
        self.pkg = pkg
        self.module_locks: dict[str, str] = {}  # NAME -> kind
        self.class_locks: dict[tuple[str, str], str] = {}  # (Cls, attr) -> kind
        self.classes: dict[str, set[str]] = {}  # Cls -> method names
        self.attr_types: dict[tuple[str, str], str] = {}  # (Cls, attr) -> Cls2
        self.aliases: dict[str, tuple[str, str]] = {}  # name -> (kind, target)
        self.lock_def_lines: dict[str, int] = {}

    def scan(self, tree: ast.Module) -> None:
        for node in tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                kind = _lock_ctor_kind(node.value)
                if kind:
                    name = node.targets[0].id
                    self.module_locks[name] = kind
                    self.lock_def_lines[f"{self.rel}:{name}"] = node.lineno
            elif isinstance(node, (ast.Import, ast.ImportFrom)):
                self._scan_import(node)
            elif isinstance(node, ast.ClassDef):
                methods = set()
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        methods.add(item.name)
                        self._scan_method_assigns(node.name, item)
                self.classes[node.name] = methods

    def _scan_import(self, node) -> None:
        prefix = self.pkg + "."
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name.startswith(prefix):
                    self.aliases[a.asname or a.name.split(".")[-1]] = (
                        "module", a.name[len(prefix):].replace(".", "/") + ".py"
                    )
        else:  # ImportFrom
            mod = node.module or ""
            if mod == self.pkg or mod.startswith(prefix):
                sub = "" if mod == self.pkg else mod[len(prefix):]
                for a in node.names:
                    # could be a submodule or an object in the module —
                    # record both candidates; resolution tries module
                    # first, then object
                    self.aliases[a.asname or a.name] = (
                        "from", f"{sub.replace('.', '/')}|{a.name}"
                    )

    def _scan_method_assigns(self, cls: str, fn) -> None:
        for node in ast.walk(fn):
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            t = node.targets[0]
            if not (isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"):
                continue
            kind = _lock_ctor_kind(node.value)
            if kind:
                self.class_locks[(cls, t.attr)] = kind
                self.lock_def_lines[f"{cls}.{t.attr}"] = node.lineno
            elif isinstance(node.value, ast.Call) \
                    and isinstance(node.value.func, ast.Name):
                # self.X = SomeClass(...) — remember for obj-typed calls
                self.attr_types[(cls, t.attr)] = node.value.func.id


class _Analysis:
    """Package-wide lock/call analysis over all scanned modules."""

    def __init__(self, root: Path):
        self.root = root
        self.pkg = root.name
        self.scans: dict[str, _ModuleScan] = {}
        self.trees: dict[str, ast.Module] = {}
        self.units: dict[str, _Unit] = {}
        self.lock_kinds: dict[str, str] = {}
        self.lock_def_lines: dict[str, int] = {}
        # global class name -> (rel, methods) — class names are unique in
        # this package; on a clash the first (sorted) module wins and
        # cross-module resolution just gets more conservative
        self.global_classes: dict[str, tuple[str, set[str]]] = {}
        self.global_attr_types: dict[tuple[str, str], str] = {}

    # -- collection ------------------------------------------------------
    def collect(self) -> None:
        for path in iter_python_files(self.root):
            rel = rel_path(path, self.root)
            tree = ast.parse(path.read_text(), filename=str(path))
            scan = _ModuleScan(rel, self.pkg)
            scan.scan(tree)
            self.scans[rel] = scan
            self.trees[rel] = tree
            for name, kind in scan.module_locks.items():
                self.lock_kinds[f"{rel}:{name}"] = kind
            for (cls, attr), kind in scan.class_locks.items():
                self.lock_kinds[f"{cls}.{attr}"] = kind
            self.lock_def_lines.update(scan.lock_def_lines)
            for cls, methods in scan.classes.items():
                self.global_classes.setdefault(cls, (rel, methods))
            self.global_attr_types.update(scan.attr_types)
        for rel, tree in sorted(self.trees.items()):
            self._walk_module(rel, tree)

    def _walk_module(self, rel: str, tree: ast.Module) -> None:
        for node in tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._walk_unit(rel, None, node.name, node)
            elif isinstance(node, ast.ClassDef):
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        self._walk_unit(
                            rel, node.name, f"{node.name}.{item.name}", item
                        )

    def _resolve_lock(self, expr: ast.AST, rel: str,
                      cls: str | None) -> str | None:
        scan = self.scans[rel]
        if isinstance(expr, ast.Name) and expr.id in scan.module_locks:
            return f"{rel}:{expr.id}"
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name) \
                and expr.value.id == "self" and cls is not None \
                and (cls, expr.attr) in scan.class_locks:
            return f"{cls}.{expr.attr}"
        return None

    def _walk_unit(self, rel: str, cls: str | None, qual: str, fn) -> None:
        unit = _Unit(f"{rel}:{qual}", rel, [], [], [], [], [])
        self.units[unit.uid] = unit

        def walk(stmts, held: tuple[str, ...]) -> None:
            for node in stmts:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    # nested def: a separate execution context — its body
                    # runs at call time with an unknown held set; analyze
                    # it as its own (conservatively lock-free-entry) unit
                    self._walk_unit(rel, cls, f"{qual}.{node.name}", node)
                    continue
                if isinstance(node, (ast.With, ast.AsyncWith)):
                    inner = held
                    for item in node.items:
                        lock = self._resolve_lock(
                            item.context_expr, rel, cls
                        )
                        if lock is not None:
                            unit.acquires.append((lock, node.lineno))
                            for h in inner:
                                unit.nest_edges.append(
                                    (h, lock, node.lineno)
                                )
                            inner = inner + (lock,)
                        else:
                            self._scan_exprs([item.context_expr], unit,
                                             rel, cls, inner)
                    walk(node.body, inner)
                    continue
                # every other statement: scan expressions for calls, then
                # recurse into compound bodies with the same held set
                self._scan_exprs(
                    [node], unit, rel, cls, held, skip_bodies=True
                )
                if isinstance(node, ast.Match):
                    # 3.10 match statements: case bodies are ordinary
                    # held-region code, invisible to the generic
                    # body/orelse recursion below
                    for case in node.cases:
                        walk(case.body, held)
                    continue
                for attr in ("body", "orelse", "finalbody", "handlers"):
                    sub = getattr(node, attr, None)
                    if sub:
                        if attr == "handlers":
                            for h in sub:
                                walk(h.body, held)
                        else:
                            walk(sub, held)

        walk(fn.body, ())

    def _scan_exprs(self, nodes, unit: _Unit, rel: str, cls: str | None,
                    held: tuple[str, ...], skip_bodies: bool = False) -> None:
        """Find calls in expression position.  ``skip_bodies`` stops the
        walk at compound-statement bodies (the caller recurses into those
        itself, preserving the held set through nested withs)."""

        def gen(node):
            for child in ast.iter_child_nodes(node):
                if skip_bodies and isinstance(child, (
                    ast.With, ast.AsyncWith, ast.For, ast.AsyncFor,
                    ast.While, ast.If, ast.Try, ast.Match,
                    ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef,
                    ast.ExceptHandler,
                )):
                    continue
                yield child
                yield from gen(child)

        roots = []
        for n in nodes:
            if isinstance(n, (ast.For, ast.AsyncFor)):
                roots.append(n.iter)
            elif isinstance(n, ast.While):
                roots.append(n.test)
            elif isinstance(n, ast.If):
                roots.append(n.test)
            elif isinstance(n, ast.Match):
                roots.append(n.subject)
            elif isinstance(n, ast.Try):
                continue
            else:
                roots.append(n)
        for r in roots:
            stack = [r] + list(gen(r))
            for node in stack:
                if isinstance(node, ast.Call):
                    self._record_call(node, unit, rel, cls, held)

    def _record_call(self, call: ast.Call, unit: _Unit, rel: str,
                     cls: str | None, held: tuple[str, ...]) -> None:
        fn = call.func
        desc = self._blocking_desc(fn, rel, cls, held)
        if desc:
            unit.blocking_all.append((desc, call.lineno))
            if held:
                unit.blocking.append((desc, call.lineno, held))
        callee = self._resolve_callee(fn, rel, cls)
        if callee is not None:
            unit.calls.append((callee, call.lineno, held))

    def _blocking_desc(self, fn, rel, cls, held) -> str | None:
        if isinstance(fn, ast.Attribute):
            base = fn.value
            if isinstance(base, ast.Name):
                if base.id == "time" and fn.attr == "sleep":
                    return "time.sleep"
                if base.id == "socket" and fn.attr in (
                    "create_connection", "getaddrinfo"
                ):
                    return f"socket.{fn.attr}"
                if base.id == "subprocess" and fn.attr in _SUBPROCESS_FNS:
                    return f"subprocess.{fn.attr}"
                if base.id == "ctypes" and fn.attr in ("CDLL", "PyDLL"):
                    return f"ctypes.{fn.attr} (native library load)"
                if base.id == "faults" and fn.attr == "inject":
                    return "faults.inject (latency rules sleep here)"
                if base.id in _NATIVE_HANDLES:
                    return f"native call {base.id}.{fn.attr} (drops the GIL)"
            if isinstance(base, ast.Attribute) and base.attr in \
                    _NATIVE_HANDLES:
                return f"native call .{base.attr}.{fn.attr} (drops the GIL)"
            recv = base.attr if isinstance(base, ast.Attribute) else (
                base.id if isinstance(base, ast.Name) else ""
            )
            if fn.attr in _QUEUE_RECV and (
                recv.rstrip("_") in ("q", "queue")
                or recv.endswith(("_q", "_queue", "queue"))
            ):
                return f"queue {recv}.{fn.attr}"
            if fn.attr == "select" and (
                recv.lstrip("_") in ("sel", "selector")
                or recv.endswith(("_sel", "_selector", "selector"))
            ):
                # selectors.BaseSelector.select blocks up to its timeout;
                # a redial loop polling under the engine lock convoys
                # every sender behind one slow peer.
                return f"selector {recv}.select"
            if fn.attr in _BLOCKING_ATTRS:
                if isinstance(base, ast.Constant):
                    return None  # b"".join / ", ".join — string, not thread
                # Condition idiom: cv.wait() while holding cv RELEASES the
                # lock — not a convoy; only flag waits on OTHER objects
                lock = self._resolve_lock(base, rel, cls)
                if fn.attr == "wait" and lock is not None and lock in held:
                    return None
                return f".{fn.attr}"
        elif isinstance(fn, ast.Name) and fn.id == "inject":
            return "faults.inject (latency rules sleep here)"
        return None

    def _resolve_callee(self, fn, rel: str, cls: str | None) -> str | None:
        scan = self.scans[rel]
        if isinstance(fn, ast.Name):
            alias = scan.aliases.get(fn.id)
            if alias and alias[0] == "from":
                sub, name = alias[1].split("|")
                target_rel = (f"{sub}/{name}.py" if sub else f"{name}.py")
                pk = f"{self.pkg}/{target_rel}"
                if pk in self.scans:
                    return None  # bare module name used as value — ignore
                owner_rel = f"{self.pkg}/{sub}.py" if sub else None
                if owner_rel and owner_rel in self.scans:
                    return self._unit_in(owner_rel, name)
                # from a.b import obj with a/b a package dir module path
                owner_rel2 = f"{self.pkg}/{sub}/__init__.py" if sub else None
                if owner_rel2 and owner_rel2 in self.scans:
                    return self._unit_in(owner_rel2, name)
                return None
            if fn.id in scan.classes or fn.id in self.global_classes:
                owner = (rel if fn.id in scan.classes
                         else self.global_classes[fn.id][0])
                return f"{owner}:{fn.id}.__init__"
            if self._defined_in(rel, fn.id):
                return f"{rel}:{fn.id}"
            return None
        if isinstance(fn, ast.Attribute):
            base = fn.value
            if isinstance(base, ast.Name):
                if base.id == "self" and cls is not None:
                    if fn.attr in self.scans[rel].classes.get(cls, set()):
                        return f"{rel}:{cls}.{fn.attr}"
                    return None
                alias = scan.aliases.get(base.id)
                if alias and alias[0] == "module":
                    owner = f"{self.pkg}/{alias[1]}"
                    if owner in self.scans:
                        return self._unit_in(owner, fn.attr)
                if alias and alias[0] == "from":
                    # from pkg import submodule; submodule.func()
                    sub, name = alias[1].split("|")
                    owner = (f"{self.pkg}/{sub}/{name}.py" if sub
                             else f"{self.pkg}/{name}.py")
                    if owner in self.scans:
                        return self._unit_in(owner, fn.attr)
                return None
            if isinstance(base, ast.Attribute) \
                    and isinstance(base.value, ast.Name) \
                    and base.value.id == "self" and cls is not None:
                # self.X.method() with self.X = SomeClass(...)
                target_cls = self.global_attr_types.get((cls, base.attr))
                if target_cls and target_cls in self.global_classes:
                    owner, methods = self.global_classes[target_cls]
                    if fn.attr in methods:
                        return f"{owner}:{target_cls}.{fn.attr}"
        return None

    def _unit_in(self, owner_rel: str, name: str) -> str | None:
        if self._defined_in(owner_rel, name):
            return f"{owner_rel}:{name}"
        if name in self.scans[owner_rel].classes:
            return f"{owner_rel}:{name}.__init__"
        return None

    def _defined_in(self, rel: str, name: str) -> bool:
        tree = self.trees.get(rel)
        if tree is None:
            return False
        return any(
            isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            and n.name == name
            for n in tree.body
        )

    # -- effective acquisitions + edges ----------------------------------
    def effective(self) -> dict[str, set[str]]:
        eff = {
            uid: {lock for lock, _ in u.acquires}
            for uid, u in self.units.items()
        }
        changed = True
        while changed:
            changed = False
            for uid, u in self.units.items():
                for callee, _, _ in u.calls:
                    extra = eff.get(callee, set()) - eff[uid]
                    if extra:
                        eff[uid] |= extra
                        changed = True
        return eff

    def edges(self) -> dict[tuple[str, str], tuple[str, int, str]]:
        """{(from_lock, to_lock): (rel, line, how)} — deduped with one
        representative location each."""
        eff = self.effective()
        out: dict[tuple[str, str], tuple[str, int, str]] = {}
        for uid in sorted(self.units):
            u = self.units[uid]
            for held, acquired, line in u.nest_edges:
                out.setdefault(
                    (held, acquired),
                    (u.rel, line, f"nested with in {uid.split(':')[1]}"),
                )
            for callee, line, held in u.calls:
                if not held:
                    continue
                for lock in sorted(eff.get(callee, ())):
                    for h in held:
                        # h == lock is kept: a callee re-acquiring a held
                        # plain Lock is a self-deadlock (self-edge)
                        out.setdefault(
                            (h, lock),
                            (u.rel, line,
                             f"{uid.split(':')[1]} calls "
                             f"{callee.split(':')[1]}"),
                        )
        return out


def _cycles(edges: dict) -> list[list[str]]:
    """Strongly connected components of size > 1, plus real self-loops,
    via Tarjan (iterative)."""
    graph: dict[str, list[str]] = {}
    for (a, b) in edges:
        graph.setdefault(a, []).append(b)
        graph.setdefault(b, [])
    index: dict[str, int] = {}
    low: dict[str, int] = {}
    on_stack: set[str] = set()
    stack: list[str] = []
    sccs: list[list[str]] = []
    counter = [0]

    def strongconnect(v0: str) -> None:
        work = [(v0, 0)]
        while work:
            v, pi = work.pop()
            if pi == 0:
                index[v] = low[v] = counter[0]
                counter[0] += 1
                stack.append(v)
                on_stack.add(v)
            recurse = False
            succs = sorted(graph[v])
            for i in range(pi, len(succs)):
                w = succs[i]
                if w not in index:
                    work.append((v, i + 1))
                    work.append((w, 0))
                    recurse = True
                    break
                if w in on_stack:
                    low[v] = min(low[v], index[w])
            if recurse:
                continue
            if low[v] == index[v]:
                scc = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.append(w)
                    if w == v:
                        break
                sccs.append(sorted(scc))
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[v])

    for v in sorted(graph):
        if v not in index:
            strongconnect(v)
    return [s for s in sccs if len(s) > 1 or (s[0], s[0]) in edges]


def run(root: Path) -> list[Finding]:
    analysis = _Analysis(root)
    analysis.collect()
    findings: list[Finding] = []

    edges = analysis.edges()
    # a self-edge on an RLock/Condition is reentrant and fine; drop it
    for (a, b) in [k for k in edges if k[0] == k[1]]:
        if analysis.lock_kinds.get(a) in ("RLock", "Condition"):
            del edges[(a, b)]

    for cycle in _cycles(edges):
        cyc_edges = sorted(
            (k, v) for k, v in edges.items()
            if k[0] in cycle and k[1] in cycle
        )
        detail = "; ".join(
            f"{a} -> {b} at {rel}:{line} ({how})"
            for (a, b), (rel, line, how) in cyc_edges
        )
        rel0, line0, _ = cyc_edges[0][1]
        findings.append(Finding(
            "DNZ-L001", rel0, line0, "cycle:" + "<->".join(cycle),
            f"lock acquisition cycle among {cycle}: {detail} — two "
            f"threads taking these in opposite orders deadlock",
        ))

    # effective blocking behavior per unit, to fixpoint — a blocking
    # call moved into a helper is still a blocking call when the caller
    # holds the lock across the helper
    eff_blk: dict[str, dict[str, str]] = {
        uid: {desc: uid for desc, _ in u.blocking_all}
        for uid, u in analysis.units.items()
    }
    changed = True
    while changed:
        changed = False
        for uid, u in analysis.units.items():
            for callee, _, _ in u.calls:
                for desc, origin in eff_blk.get(callee, {}).items():
                    if desc not in eff_blk[uid]:
                        eff_blk[uid][desc] = origin
                        changed = True

    for uid in sorted(analysis.units):
        u = analysis.units[uid]
        for desc, line, held in u.blocking:
            findings.append(Finding(
                "DNZ-L002", u.rel, line, uid.split(":")[1],
                f"{desc} while holding {list(held)} — a blocking call "
                f"inside a critical section convoys every thread that "
                f"needs the lock",
            ))
        for callee, line, held in u.calls:
            if not held:
                continue
            for desc, origin in sorted(eff_blk.get(callee, {}).items()):
                origin_q = origin.split(":")[1]
                callee_q = callee.split(":")[1]
                via = (
                    f"inside {origin_q}" if origin_q == callee_q
                    else f"inside {origin_q}, reached via {callee_q}"
                )
                findings.append(Finding(
                    "DNZ-L002", u.rel, line, uid.split(":")[1],
                    f"{desc} ({via}) while holding {list(held)} "
                    f"— a blocking call inside a critical section convoys "
                    f"every thread that needs the lock",
                ))
    return findings
