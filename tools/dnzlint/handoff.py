"""DNZ-M002 — operator handoff-instrument completeness.

The pipeline doctor attributes bottlenecks from two per-operator
signals: measured batch-processing time (``_obs_batch_ms`` via
``_note_batch``) and upstream queue-wait (``_doctor_input`` /
``_note_input_wait``).  An operator that overrides the batch-processing
path without binding BOTH directions is silently invisible to
attribution — its time shows up as its consumer's unexplained wait and
the doctor names the wrong suspect.  Like DNZ-M001 for the metric
catalog, this pass closes the loop statically, in both directions:

- every operator class in ``physical/`` that overrides the
  batch-processing path (defines a real ``run`` and consumes an input —
  references ``self.input_op`` or merges inputs via ``spawn_pump``)
  must (a) call ``self.bind_obs(...)`` in its constructor, (b) consume
  input through ``self._doctor_input(...)`` or time its own merge with
  ``self._note_input_wait(...)``, and (c) close its busy bracket with
  ``self._note_batch(...)`` (or observe ``self._obs_batch_ms``
  directly);
- every such class must be registered in ``operators.toml``, and every
  registered class must still exist — a NEW operator cannot slip in
  unregistered (and therefore unreviewed for attribution coverage), and
  a renamed one cannot leave the registry stale;
- **keyed-state coverage** (the state observatory's drift pin): every
  operator registered with ``keyed_state = true`` must bind the
  state-accounting instruments — a ``state_info()`` method AND a
  sketch watch created via ``statewatch.make_watch(...)`` — and,
  conversely, an operator that defines ``state_info`` must be flagged
  ``keyed_state = true`` in the registry.  A future stateful operator
  cannot silently be invisible to ``GET /queries/<id>/state``, memory
  budgeting, or skew verdicts.

Leaf operators (``SourceExec``) are exempt by shape: they have no
upstream handoff — their production time is attributed from their
consumer's input wait (obs/doctor/attribution.py), and their queue
signals come from the prefetch pump's own instruments.
"""

from __future__ import annotations

import ast
from pathlib import Path

from tools.dnzlint import Finding, rel_path

PHYSICAL_REL = Path("physical")


def _class_src_flags(cls: ast.ClassDef) -> dict:
    """What this operator class does, by AST: which doctor hooks it
    calls and whether it consumes an upstream input."""
    flags = {
        "has_run": False,
        "run_is_stub": False,
        "consumes_input": False,
        "binds_obs": False,
        "input_wait": False,
        "note_batch": False,
        "has_state_info": False,
        "makes_watch": False,
    }
    for node in cls.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and (
            node.name == "state_info"
        ):
            flags["has_state_info"] = True
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and (
            node.name == "run"
        ):
            flags["has_run"] = True
            body = [
                n for n in node.body
                if not isinstance(n, ast.Expr)
                or not isinstance(n.value, ast.Constant)
            ]
            flags["run_is_stub"] = (
                len(body) == 1 and isinstance(body[0], ast.Raise)
            )
    for node in ast.walk(cls):
        if isinstance(node, ast.Attribute) and isinstance(
            node.value, ast.Name
        ) and node.value.id == "self":
            if node.attr == "input_op":
                flags["consumes_input"] = True
        if isinstance(node, ast.Call):
            fn = node.func
            if isinstance(fn, ast.Attribute):
                if isinstance(fn.value, ast.Name) and fn.value.id == "self":
                    if fn.attr == "bind_obs":
                        flags["binds_obs"] = True
                    elif fn.attr in ("_doctor_input", "_note_input_wait"):
                        flags["input_wait"] = True
                    elif fn.attr == "_note_batch":
                        flags["note_batch"] = True
                # self._obs_batch_ms.observe(...)
                if (
                    fn.attr == "observe"
                    and isinstance(fn.value, ast.Attribute)
                    and fn.value.attr == "_obs_batch_ms"
                    and isinstance(fn.value.value, ast.Name)
                    and fn.value.value.id == "self"
                ):
                    flags["note_batch"] = True
            elif isinstance(fn, ast.Name) and fn.id == "spawn_pump":
                flags["consumes_input"] = True
        # statewatch.make_watch(...) — the sketch-watch constructor
        if isinstance(node, ast.Call):
            fn = node.func
            if (
                isinstance(fn, ast.Attribute)
                and fn.attr == "make_watch"
                and isinstance(fn.value, ast.Name)
                and fn.value.id == "statewatch"
            ):
                flags["makes_watch"] = True
    return flags


def discover(root: Path) -> dict[str, tuple[str, int, dict]]:
    """{class name: (rel file, lineno, flags)} for every operator class
    in ``physical/`` that overrides the batch-processing path."""
    phys = root / PHYSICAL_REL
    out: dict[str, tuple[str, int, dict]] = {}
    if not phys.is_dir():
        return out
    for path in sorted(phys.glob("*.py")):
        rel = rel_path(path, root)
        tree = ast.parse(path.read_text(), filename=str(path))
        for node in ast.walk(tree):
            if not isinstance(node, ast.ClassDef):
                continue
            flags = _class_src_flags(node)
            if not flags["has_run"] or flags["run_is_stub"]:
                continue
            if not flags["consumes_input"]:
                continue  # leaf operator: no upstream handoff exists
            out[node.name] = (rel, node.lineno, flags)
    return out


def load_operators(path: Path) -> dict[str, dict]:
    """operators.toml -> {class: {"file": ..., "keyed_state": bool}}.
    ``keyed_state`` normalizes across tomllib (bool) and the string
    fallback parser."""
    from tools.dnzlint import _parse_toml

    if not path.exists():
        return {}
    data = _parse_toml(path)
    return {
        e["class"]: {
            "file": e.get("file", ""),
            "keyed_state": str(e.get("keyed_state", "")).lower() == "true",
        }
        for e in data.get("operator", [])
        if e.get("class")
    }


def run(root: Path, operators_path: Path | None = None) -> list[Finding]:
    discovered = discover(root)
    if not discovered and operators_path is None:
        return []  # tree without a physical/ package: nothing to check
    if operators_path is None:
        operators_path = Path(__file__).resolve().parent / "operators.toml"
    registered = load_operators(operators_path)
    findings: list[Finding] = []
    for cls, (rel, lineno, flags) in discovered.items():
        missing = []
        if not flags["binds_obs"]:
            missing.append("bind_obs(...) in the constructor")
        if not flags["input_wait"]:
            missing.append(
                "input via self._doctor_input(...) (or "
                "self._note_input_wait for a merged-queue operator)"
            )
        if not flags["note_batch"]:
            missing.append(
                "a busy bracket closed by self._note_batch(...) "
                "(or self._obs_batch_ms.observe)"
            )
        for m in missing:
            findings.append(Finding(
                "DNZ-M002", rel, lineno, cls,
                f"operator overrides the batch-processing path but lacks "
                f"{m} — it would be invisible to the doctor's bottleneck "
                f"attribution",
            ))
        if cls not in registered:
            findings.append(Finding(
                "DNZ-M002", rel, lineno, cls,
                "operator class is not registered in "
                "tools/dnzlint/operators.toml — register it so handoff-"
                "instrument coverage is reviewed, not assumed",
            ))
            continue
        # keyed-state drift, both directions (state observatory pin)
        entry = registered[cls]
        if entry["keyed_state"]:
            if not flags["has_state_info"]:
                findings.append(Finding(
                    "DNZ-M002", rel, lineno, cls,
                    "operator is registered keyed_state=true but defines "
                    "no state_info() — its memory/skew would be invisible "
                    "to GET /queries/<id>/state and the budget forecast",
                ))
            if not flags["makes_watch"]:
                findings.append(Finding(
                    "DNZ-M002", rel, lineno, cls,
                    "operator is registered keyed_state=true but never "
                    "creates a sketch watch (statewatch.make_watch) — "
                    "its key distribution would be invisible to hot-key "
                    "and skew verdicts",
                ))
        elif flags["has_state_info"]:
            findings.append(Finding(
                "DNZ-M002", rel, lineno, cls,
                "operator defines state_info() (it holds keyed state) "
                "but operators.toml does not flag it keyed_state = true "
                "— flag it so state-accounting coverage is reviewed, "
                "not assumed",
            ))
    for cls, entry in registered.items():
        if cls not in discovered:
            findings.append(Finding(
                "DNZ-M002", entry["file"] or str(operators_path), 0, cls,
                f"operators.toml registers {cls!r} but no such "
                "input-consuming operator class exists in physical/ — "
                "stale registration (renamed or deleted operator)",
            ))
    return findings
