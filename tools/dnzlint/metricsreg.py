"""DNZ-M001 — metric-registry completeness + naming discipline.

The obs subsystem validates instrument names against the catalog at BIND
time, which catches a typo'd binder — but only on the code path that
binds it, and a *declared* instrument whose call site was renamed away
just silently stops reporting.  Like DNZ-F001/F002 for fault sites, this
pass closes the loop statically, in both directions:

- every ``obs.counter("x", ...)`` / ``obs.gauge`` / ``obs.histogram`` /
  ``obs.gauge_fn`` call must name a catalog key as a string literal, and
  its binder kind must match the declared kind (``gauge_fn`` binds a
  declared gauge);
- every catalog entry must have at least one binder call somewhere in
  the engine — a declaration nobody binds is a metric the docs advertise
  that never reports;
- catalog entries themselves follow the naming convention
  (``^dnz_[a-z][a-z0-9_]*$``; counters end ``_total``; histograms end in
  a unit suffix ``_ms``/``_s``/``_bytes``/``_rows``) and carry a real
  help string.

The catalog is read from the scanned tree's own ``obs/catalog.py`` **by
AST**, never by import — same contract as the fault-site pass.  The pass
also exports :func:`metric_catalog_table`, the generated markdown table
``docs/observability.md`` embeds (``python -m tools.dnzlint
--metric-catalog``), so the doc cannot drift from the declarations
(pinned by ``tests/test_lint.py``).
"""

from __future__ import annotations

import ast
import re
from pathlib import Path

from tools.dnzlint import Finding, iter_python_files, rel_path

CATALOG_REL = Path("obs") / "catalog.py"

#: binder attribute -> catalog kind it must bind
BINDERS = {
    "counter": "counter",
    "gauge": "gauge",
    "gauge_fn": "gauge",
    "histogram": "histogram",
}

_NAME_RE = re.compile(r"^dnz_[a-z][a-z0-9_]*$")
_HIST_SUFFIXES = ("_ms", "_s", "_bytes", "_rows")


def _const_str(node: ast.AST) -> str | None:
    return node.value if (
        isinstance(node, ast.Constant) and isinstance(node.value, str)
    ) else None


def load_catalog(root: Path) -> tuple[dict[str, tuple[str, str]], int]:
    """Parse ``INSTRUMENTS`` from the tree's obs/catalog.py.

    Returns ``({name: (kind, help)}, lineno)``; a tree without an obs
    package returns empty (the pass then no-ops).
    """
    path = root / CATALOG_REL
    if not path.exists():
        return {}, 0
    tree = ast.parse(path.read_text(), filename=str(path))
    out: dict[str, tuple[str, str]] = {}
    lineno = 0
    for node in ast.walk(tree):
        if not isinstance(node, ast.AnnAssign) and not isinstance(
            node, ast.Assign
        ):
            continue
        targets = (
            [node.target] if isinstance(node, ast.AnnAssign)
            else node.targets
        )
        if not any(
            isinstance(t, ast.Name) and t.id == "INSTRUMENTS"
            for t in targets
        ):
            continue
        if not isinstance(node.value, ast.Dict):
            continue
        lineno = node.lineno
        for k, v in zip(node.value.keys, node.value.values):
            name = _const_str(k)
            if name is None or not isinstance(v, ast.Tuple) or not v.elts:
                continue
            kind = _const_str(v.elts[0]) or ""
            help_str = (
                _const_str(v.elts[1]) if len(v.elts) > 1 else None
            ) or ""
            out[name] = (kind, help_str)
    return out, lineno


def _binder_calls(tree: ast.AST):
    """Yield ``(node, binder_attr, name_literal_or_None)`` for every
    ``obs.<binder>("name", ...)`` call (the engine's idiom is always a
    module-qualified call on a name bound to the obs package)."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if not (
            isinstance(fn, ast.Attribute)
            and fn.attr in BINDERS
            and isinstance(fn.value, ast.Name)
            and fn.value.id == "obs"
        ):
            continue
        yield node, fn.attr, _const_str(node.args[0]) if node.args else None


def usage_inventory(root: Path) -> dict[str, list[tuple[str, int]]]:
    """{instrument: [(module, line), ...]} across the tree (obs package
    internals excluded — the subsystem binds through dynamic names by
    design; the engine's call sites are what the catalog pins)."""
    catalog, _ = load_catalog(root)
    uses: dict[str, list[tuple[str, int]]] = {n: [] for n in catalog}
    for path in iter_python_files(root):
        if (root / "obs") in path.parents:
            continue
        rel = rel_path(path, root)
        tree = ast.parse(path.read_text(), filename=str(path))
        for node, _attr, name in _binder_calls(tree):
            if name in uses:
                uses[name].append((rel, node.lineno))
    return uses


def metric_catalog_table(root: Path) -> str:
    """The markdown metric-catalog table for ``docs/observability.md``,
    generated from the declarations + verified call sites (so a moved
    instrumentation point is a visible docs diff, not silent drift)."""
    catalog, _ = load_catalog(root)
    uses = usage_inventory(root)
    lines = [
        "| instrument | kind | help | instrumented in |",
        "|---|---|---|---|",
    ]
    for name, (kind, help_str) in catalog.items():
        mods = sorted({m for m, _l in uses.get(name, [])})
        where = ", ".join(f"`{m}`" for m in mods) or "—"
        lines.append(f"| `{name}` | {kind} | {help_str} | {where} |")
    return "\n".join(lines)


def _check_declaration(
    name: str, kind: str, help_str: str, cat_rel: str, lineno: int
) -> list[Finding]:
    findings = []

    def bad(msg: str) -> None:
        findings.append(Finding("DNZ-M001", cat_rel, lineno, name, msg))

    if kind not in ("counter", "gauge", "histogram"):
        bad(f"unknown instrument kind {kind!r}")
    if not _NAME_RE.match(name):
        bad("instrument name must match ^dnz_[a-z][a-z0-9_]*$")
    elif kind == "counter" and not name.endswith("_total"):
        bad("counter names must end in _total")
    elif kind == "histogram" and not name.endswith(_HIST_SUFFIXES):
        bad(
            "histogram names must end in a unit suffix "
            f"({'/'.join(_HIST_SUFFIXES)})"
        )
    elif kind == "gauge" and name.endswith("_total"):
        bad("_total names a counter; gauges must not use it")
    if len(help_str.strip()) < 8:
        bad("instrument help string is missing or trivially short")
    return findings


def run(root: Path) -> list[Finding]:
    catalog, cat_lineno = load_catalog(root)
    if not catalog:
        return []  # no obs package in this tree: nothing to check
    cat_rel = rel_path(root / CATALOG_REL, root)
    findings: list[Finding] = []
    for name, (kind, help_str) in catalog.items():
        findings += _check_declaration(
            name, kind, help_str, cat_rel, cat_lineno
        )

    used: dict[str, int] = {n: 0 for n in catalog}
    for path in iter_python_files(root):
        if (root / "obs") in path.parents:
            continue  # the subsystem itself binds dynamically by design
        rel = rel_path(path, root)
        tree = ast.parse(path.read_text(), filename=str(path))
        for node, attr, name in _binder_calls(tree):
            if name is None:
                findings.append(Finding(
                    "DNZ-M001", rel, node.lineno, "<dynamic>",
                    f"obs.{attr} with a non-literal instrument name — "
                    "names must be checkable string literals",
                ))
                continue
            if name not in catalog:
                findings.append(Finding(
                    "DNZ-M001", rel, node.lineno, name,
                    f"obs.{attr}({name!r}) names no entry of "
                    "obs/catalog.py INSTRUMENTS — binding would raise at "
                    "runtime; declare the instrument with a help string",
                ))
                continue
            want = BINDERS[attr]
            if catalog[name][0] != want:
                findings.append(Finding(
                    "DNZ-M001", rel, node.lineno, name,
                    f"obs.{attr}({name!r}) binds a {want} but the "
                    f"catalog declares a {catalog[name][0]}",
                ))
                continue
            used[name] += 1

    for name, count in used.items():
        if count == 0:
            findings.append(Finding(
                "DNZ-M001", cat_rel, cat_lineno, name,
                f"instrument {name!r} is declared in the catalog but no "
                "engine module binds it — a renamed or deleted "
                "instrumentation point left the catalog stale",
            ))
    return findings
