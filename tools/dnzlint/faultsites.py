"""DNZ-F001/F002 — fault-site completeness.

The fault framework (``runtime/faults.py``) validates plans against
``SITES`` at ARM time, which catches a typo'd plan — but a typo'd or
renamed **call site** (``faults.inject("lsm.putt")``) only surfaces when
a chaos run quietly fails to inject anything.  These passes close the
loop statically, in both directions:

- **DNZ-F001**: every ``faults.inject("x", ...)`` literal must be a key
  of ``SITES``.  A non-literal site name is also flagged: dynamic names
  cannot be checked, and nothing in the engine needs one.
- **DNZ-F002**: every site registered in ``SITES`` must have at least
  one ``inject`` call — in the module ``SITE_MODULES`` declares for it,
  when declared — so deleting or moving an instrumented boundary without
  updating the registry fails the gate instead of arming vacuous plans.

Both read ``SITES``/``SITE_MODULES`` from the scanned tree's own
``runtime/faults.py`` **by AST**, never by import: the linter must work
on broken fixture trees and must not trigger the engine's import-time
``DENORMALIZED_FAULT_PLAN`` arming.

The pass also exports the verified site inventory
(:func:`site_inventory`, :func:`fault_site_table`) — the fault-site
table in ``docs/fault_tolerance.md`` is generated from it, so docs and
registry cannot drift (``tests/test_lint.py`` pins the equality).
"""

from __future__ import annotations

import ast
from pathlib import Path

from tools.dnzlint import Finding, iter_python_files, rel_path

FAULTS_REL = Path("runtime") / "faults.py"


def _const_str(node: ast.AST) -> str | None:
    return node.value if (
        isinstance(node, ast.Constant) and isinstance(node.value, str)
    ) else None


def load_registry(root: Path) -> tuple[dict, dict, int]:
    """Parse ``SITES`` and ``SITE_MODULES`` from the tree's faults.py.

    Returns ``(sites, site_modules, sites_lineno)`` where ``sites`` maps
    site -> default-error-class name and ``site_modules`` maps
    site -> (module-relpath, description).  Missing file or missing
    assignments return empty dicts (the F-passes then no-op: a tree
    without a fault framework has nothing to check).
    """
    path = root / FAULTS_REL
    if not path.exists():
        return {}, {}, 0
    tree = ast.parse(path.read_text(), filename=str(path))
    sites: dict[str, str] = {}
    site_modules: dict[str, tuple[str, str]] = {}
    lineno = 0
    for node in tree.body:
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        target = node.targets[0]
        if not isinstance(target, ast.Name):
            continue
        if target.id == "SITES" and isinstance(node.value, ast.Dict):
            lineno = node.lineno
            for k, v in zip(node.value.keys, node.value.values):
                key = _const_str(k)
                if key is None:
                    continue
                sites[key] = (
                    v.id if isinstance(v, ast.Name) else ast.unparse(v)
                )
        elif target.id == "SITE_MODULES" and isinstance(node.value, ast.Dict):
            for k, v in zip(node.value.keys, node.value.values):
                key = _const_str(k)
                if key is None or not isinstance(v, ast.Tuple):
                    continue
                parts = [_const_str(e) or "" for e in v.elts]
                if len(parts) == 2:
                    site_modules[key] = (parts[0], parts[1])
    return sites, site_modules, lineno


def _inject_calls(tree: ast.AST):
    """Yield (node, site_literal_or_None) for every ``faults.inject(...)``
    or bare ``inject(...)`` call (the latter only when the module imported
    the name from the fault framework — approximated by call-name match,
    which is unambiguous in this codebase)."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        named_inject = (
            isinstance(fn, ast.Attribute) and fn.attr == "inject"
            and isinstance(fn.value, ast.Name) and fn.value.id == "faults"
        ) or (isinstance(fn, ast.Name) and fn.id == "inject")
        if not named_inject:
            continue
        site = _const_str(node.args[0]) if node.args else None
        yield node, site


def site_inventory(root: Path) -> dict[str, dict]:
    """{site: {error, module, where, calls: [(rel, line), ...]}} — the
    ground truth the docs table and DNZ-F002 both consume."""
    sites, site_modules, _ = load_registry(root)
    inv = {
        s: {
            "error": err,
            "module": site_modules.get(s, ("", ""))[0],
            "where": site_modules.get(s, ("", ""))[1],
            "calls": [],
        }
        for s, err in sites.items()
    }
    for path in iter_python_files(root):
        rel = rel_path(path, root)
        tree = ast.parse(path.read_text(), filename=str(path))
        for node, site in _inject_calls(tree):
            if site in inv:
                inv[site]["calls"].append((rel, node.lineno))
    return inv


def fault_site_table(root: Path) -> str:
    """The markdown fault-site table for ``docs/fault_tolerance.md``,
    generated from the verified inventory (module column included so a
    moved boundary is a visible docs diff, not silent drift)."""
    inv = site_inventory(root)
    lines = [
        "| site | where | module | default error |",
        "|---|---|---|---|",
    ]
    for site, meta in inv.items():
        mod = f"`{root.name}/{meta['module']}`" if meta["module"] else "—"
        lines.append(
            f"| `{site}` | {meta['where']} | {mod} | `{meta['error']}` |"
        )
    return "\n".join(lines)


def run(root: Path) -> list[Finding]:
    findings: list[Finding] = []
    sites, site_modules, sites_lineno = load_registry(root)
    faults_rel = rel_path(root / FAULTS_REL, root) if sites else ""
    seen: dict[str, list[str]] = {s: [] for s in sites}

    for path in iter_python_files(root):
        rel = rel_path(path, root)
        tree = ast.parse(path.read_text(), filename=str(path))
        in_framework = path == root / FAULTS_REL
        for node, site in _inject_calls(tree):
            if in_framework:
                continue  # the framework's own definition of inject()
            if site is None:
                findings.append(Finding(
                    "DNZ-F001", rel, node.lineno, "<dynamic>",
                    "faults.inject with a non-literal site name — sites "
                    "must be checkable string literals",
                ))
                continue
            if sites and site not in sites:
                findings.append(Finding(
                    "DNZ-F001", rel, node.lineno, site,
                    f"faults.inject({site!r}) names no key of "
                    f"faults.SITES — the plan validator can never match "
                    f"it, so a chaos run would report green without "
                    f"injecting",
                ))
                continue
            if site in seen:
                seen[site].append(rel)

    pkg_prefix = root.name + "/"
    for site, calls in seen.items():
        declared = site_modules.get(site, ("", ""))[0]
        if not calls:
            findings.append(Finding(
                "DNZ-F002", faults_rel, sites_lineno, site,
                f"site {site!r} is registered in faults.SITES but no "
                f"module contains a faults.inject({site!r}) call — a "
                f"renamed or deleted boundary left the registry stale",
            ))
        elif declared and (pkg_prefix + declared) not in calls:
            findings.append(Finding(
                "DNZ-F002", faults_rel, sites_lineno, site,
                f"site {site!r} is declared to live in {declared!r} "
                f"(faults.SITE_MODULES) but its inject calls are in "
                f"{sorted(set(calls))} — update the registry",
            ))
    return findings
