"""DNZ-E001 — error taxonomy: no silently-swallowed broad excepts.

A handler for ``Exception``, ``BaseException``, or a bare ``except`` in
engine code must do one of:

- **re-raise** — any ``raise`` statement anywhere in the handler body
  (bare re-raise, ``raise X(...) from e`` conversion to a
  :class:`DenormalizedError` subclass, anything that keeps the failure
  moving) satisfies the rule;
- **carry a pragma** — ``# dnzlint: allow(broad-except) <reason>`` on
  the ``except`` line, for the handful of places where swallowing is the
  design (destructors, best-effort teardown of already-dead resources,
  supervisor loops that re-dispatch the error as data).

Everything else is the bug class PR 1 dug out of the decode path: a
native component that silently never worked, a close() that hides the
error that explains the next failure.  Narrow handlers
(``except OSError``, ``except FormatError``) are out of scope — naming a
type is already a decision.
"""

from __future__ import annotations

import ast
from pathlib import Path

from tools.dnzlint import Finding, iter_python_files, rel_path

_BROAD = ("Exception", "BaseException")


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True  # bare except
    if isinstance(t, ast.Name):
        return t.id in _BROAD
    if isinstance(t, ast.Tuple):
        return any(
            isinstance(e, ast.Name) and e.id in _BROAD for e in t.elts
        )
    return False


def _reraises(handler: ast.ExceptHandler) -> bool:
    return any(isinstance(n, ast.Raise) for n in ast.walk(handler))


def _enclosing_symbol(stack: list[str]) -> str:
    return ".".join(stack) if stack else "<module>"


def run(root: Path) -> list[Finding]:
    findings: list[Finding] = []
    for path in iter_python_files(root):
        rel = rel_path(path, root)
        tree = ast.parse(path.read_text(), filename=str(path))

        def visit(node: ast.AST, stack: list[str]) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    visit(child, stack + [child.name])
                elif isinstance(child, ast.ClassDef):
                    visit(child, stack + [child.name])
                else:
                    if isinstance(child, ast.ExceptHandler) and _is_broad(
                        child
                    ) and not _reraises(child):
                        what = (
                            "bare except" if child.type is None
                            else f"except {ast.unparse(child.type)}"
                        )
                        findings.append(Finding(
                            "DNZ-E001", rel, child.lineno,
                            _enclosing_symbol(stack),
                            f"{what} swallows the error (no raise in the "
                            f"handler) — re-raise, convert to a "
                            f"DenormalizedError, or annotate with "
                            f"allow(broad-except) and a reason",
                        ))
                    visit(child, stack)

        visit(tree, [])
    return findings
