"""Long-running stability soak: one checkpointed streaming job, paced for
minutes, SIGKILLed and restored repeatedly, leak- and loss-checked.

The unit/property tests prove single kill/restore cycles; this proves the
ENGINE PROCESS is stable over wall-clock time: no unbounded RSS growth in
a long-lived child (state rings, LSM checkpoints, emission buffers), no
window lost or corrupted across many restores, recovery time bounded.
The reference has no analog (its de-facto soak is "run the docker example
and watch", SURVEY §4); a framework claiming checkpoint/restore parity
should demonstrate it surviving repetition.

    python tools/soak.py [--pipeline simple|sliding|join|session|udaf|approx]
                         [--minutes 12] [--pace 200000] [--kill-every 90]
                         [--out SOAK.json]

Design:
- The child process runs the chosen pipeline — ``simple`` (1s tumbling
  count/min/max/avg by key), ``sliding`` (1s/250ms, 4-way emission
  fan-out), ``join`` (a raw fact stream — skewed + late mid-run —
  band-joined to a per-second dimension stream then windowed: the
  closed-loop skew policy adapts the celebrity key live and SIGKILLs
  land mid-adaptation, docs/joins.md), ``session`` (300ms-gap session windows over a bursty
  feed: exact session bounds verified — the operator the reference
  left ``todo!()``), ``udaf`` (stateful Python accumulator on the
  host-frame path: state()/merge() snapshots), or ``approx``
  (sketch-native approx_distinct on the slice store: the parent's
  golden replays the HLL kernels from ops/sketches.py and demands
  EXACT integer equality on every committed estimate,
  docs/approx_aggregates.md) — over a DETERMINISTIC
  paced source whose
  batches are a pure function of the batch index (seeded RNG per batch),
  with checkpointing every 2s to a shared LSM dir.  The source implements
  ``offset_snapshot``/``offset_restore`` (fast-forward to batch i), so a
  restored child resumes exactly where the checkpoint cut — the same
  contract KafkaPartitionReader honors, exercised here through the public
  Source extension API.
- The parent samples child RSS from /proc, kills it with SIGKILL every
  --kill-every seconds (the LAST segment runs to EOS), respawns it, and
  finally compares the union of all segments' emitted windows against an
  incrementally-computed numpy golden.  Output is exactly-once under a
  transactional file-sink protocol: every emitted line carries its
  in-flight epoch, each restored child announces its recovery epoch,
  and the parent discards a killed segment's uncommitted suffix (the
  lines its successor's replay regenerates) — truncate-on-restore,
  applied where the union is read.  Duplicate emissions that survive
  the clip are therefore REAL duplicates and count against the run.
- Relay-aware: if the TPU tunnel relay opens mid-soak, the soak aborts
  gracefully (partial JSON, exit 0) so it never steals the single core
  from a chip-evidence run.

The parent never imports jax; the child pins jax to CPU before first use.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time
from pathlib import Path

import numpy as np

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

# Event-time origin.  Everything in the soak — batch generation, golden
# folds, window keys — is T0-relative, so its absolute value is free to
# move; the parent anchors it near wall-now (main()) so the engine's
# event-time lag metrics (wall − event time) land inside their histogram
# buckets and the telemetry percentiles are real, then hands the value
# to every child via SOAK_T0 (parent and children MUST agree — window
# keys are absolute).  Standalone/child invocations inherit or fall
# back to the legacy fixed origin.
T0 = int(os.environ.get("SOAK_T0", "0")) or 1_700_000_000_000
N_KEYS = 10
WINDOW_MS = 1000


def relay_active() -> bool:
    """Relay open for claims OR already held by a chip run.  The active
    connect probe alone is not enough: while a claim is in flight the
    single-client relay REFUSES new connects (bench.py
    ``_relay_conn_established`` rationale), so a busy tunnel would read
    "closed" and the soak would keep saturating the core under a live
    chip run.  Scan /proc/net/tcp for ANY established loopback
    connection to a relay port (chip_ab's claim shows up there) as the
    busy signal.  Probe logic and the port list come from bench — one
    source of truth."""
    import bench  # env reads only at import; no jax

    if bench._relay_open():
        return True
    # both tables: a dual-stack client's v4-mapped connection lands in
    # tcp6 (endswith covers ::ffff:127.0.0.1), same as bench's own
    # passive check
    for path in ("/proc/net/tcp", "/proc/net/tcp6"):
        try:
            with open(path) as f:
                next(f)
                for line in f:
                    parts = line.split()
                    if len(parts) < 4 or parts[3] != "01":  # ESTABLISHED
                        continue
                    ip, _, port = parts[2].partition(":")
                    if (
                        ip.endswith("0100007F")
                        and int(port, 16) in bench._RELAY_PROBE_PORTS
                    ):
                        return True
        except (OSError, ValueError):
            continue
    return False


# -- deterministic feed: batch i is a pure function of (seed, i) ---------


def batch_arrays(i: int, batch_rows: int, pace: float, seed: int = 11):
    """(ts, key_ids, vals) for batch i.  Event time advances at exactly
    ``pace`` rows per event-second, so event time == wall time when the
    feed keeps up."""
    rng = np.random.default_rng(seed * 1_000_003 + i)
    span_ms = batch_rows * 1000.0 / pace
    base = T0 + int(i * span_ms)
    ts = base + np.sort(rng.integers(0, max(1, int(span_ms)), batch_rows))
    keys = rng.integers(0, N_KEYS, batch_rows)
    vals = np.round(rng.normal(50.0, 10.0, batch_rows), 6)
    return ts.astype(np.int64), keys, vals


SEED_LEFT = 11
SEED_RIGHT = 23


# -- query-dense live-registration soak (ISSUE 16) -----------------------
# 50 concurrent windowed queries over one feed, all subsumption-shared
# into ONE slice pipeline: a handful present from the start, the rest
# joining LIVE at staggered event times (incl. mid-epoch), some leaving
# mid-run.  Every when_ts below is event time, so re-issuing the whole
# schedule verbatim after a SIGKILL/restore lands each join/leave at
# the same stream position — the registration control plane is
# replayable by construction.

QD_QUERIES = 50
QD_INITIAL = 6
QD_UNIT_MS = 1000
#: (length, slide) cycle — every spec tiles the 1000ms gcd unit
QD_SPECS = [(3000, 1000), (2000, 1000), (4000, 2000), (2000, 2000),
            (5000, 1000), (3000, 3000), (6000, 2000), (4000, 1000)]
#: reading > thr filter cycle; index 0 (weakest) is the group's base
#: predicate — every other threshold is implied by it (subsumption)
QD_THRESHOLDS = [30.0, 38.0, 42.0, 46.0, 50.0, 52.0, 55.0, 35.0]


def _dense_schedule(total_batches: int, batch_rows: int, pace: float, *,
                    n_queries: int, n_initial: int, specs: list,
                    thresholds: list, tail_ms: int) -> list:
    """Shared core of the dense control planes (query_dense and
    join_dense): one dict per query — {"qid", "L", "S", "thr"} plus
    "join" (event-time when_ts) for the live joiners and "leave" for
    the mid-run departures.  Pure function of the feed shape; parent,
    child, and the oracle child all derive the identical schedule from
    SOAK_* env."""
    span_ms = batch_rows * 1000.0 / pace
    horizon = int(total_batches * span_ms)
    queries = []
    for q in range(n_queries):
        length, slide = specs[q % len(specs)]
        queries.append({
            "qid": q, "L": length, "S": slide,
            "thr": thresholds[q % len(thresholds)],
        })
    # joiners: staggered across the middle of the event-time horizon at
    # off-second offsets (joins land mid-epoch relative to the wall-
    # clock checkpoint cadence); the tail stays join-free so every
    # joiner still closes full windows before EOS
    njoin = n_queries - n_initial
    join_lo = 4000
    join_hi = max(join_lo + 1000, horizon - tail_ms)
    for j, q in enumerate(range(n_initial, n_queries)):
        queries[q]["join"] = (
            T0 + join_lo + (join_hi - join_lo) * j // max(njoin - 1, 1)
        )
    # leavers: every fifth joiner departs a third of the horizon after
    # it joined (never in the EOS drain tail — departure must be a live
    # detach, not the pipeline close)
    for q in range(n_initial, n_queries):
        if q % 5 == 2:
            leave = min(
                queries[q]["join"] + horizon // 3, T0 + horizon - 6000
            )
            if leave > queries[q]["join"] + queries[q]["L"] + 2000:
                queries[q]["leave"] = leave
    return queries


def qd_schedule(total_batches: int, batch_rows: int, pace: float) -> list:
    """The deterministic 50-query control plane (6 initial, 44 live
    joiners, every fifth joiner departing mid-run)."""
    return _dense_schedule(
        total_batches, batch_rows, pace, n_queries=QD_QUERIES,
        n_initial=QD_INITIAL, specs=QD_SPECS, thresholds=QD_THRESHOLDS,
        tail_ms=12000,
    )


def qd_class_continuous(specs: dict, qid: int) -> bool:
    """True when ``qid``'s threshold class had some member alive from
    before its join clear through the join instant — its filter class's
    slice partials were retained, so the attach OWES a warm backfill
    (first emitted window strictly before the join time).  First-of-
    class joiners clamp forward instead (fresh-class rule) and owe
    nothing."""
    spec = specs[qid]
    join = spec["join"]
    for other in specs.values():
        if other["qid"] == qid or other["thr"] != spec["thr"]:
            continue
        born = other.get("join")
        if born is not None and born >= join:
            continue
        gone = other.get("leave")
        if gone is not None and gone <= join:
            continue
        return True
    return False


# -- join-dense shared-join soak (ISSUE 17) ------------------------------
# The query-dense scenario one operator deeper: every query windows over
# the SAME fact×dim interval join, so the whole group runs ONE
# StreamingJoinExec whose output fans into the shared slice pipeline.
# Staggered live joins/leaves and SIGKILL/restore ride the identical
# event-time-replayable control plane; verification is byte-identity
# against per-query independent join+window oracles (jd_verify reuses
# qd_verify's comparison).  Readings are rounded to INTEGERS: the join's
# output batch boundaries depend on pump interleaving (live pacing vs
# the oracle's dense replay), so float sums would drift in the last ulp
# across fold groupings — integer-valued float64 keeps every aggregate
# (sum/avg included) exact and order-free (docs/multi_query.md).

JD_QUERIES = 10
JD_INITIAL = 3
JD_UNIT_MS = 1000
JD_SPECS = [(3000, 1000), (2000, 1000), (4000, 2000), (2000, 2000),
            (3000, 3000), (4000, 1000)]
JD_THRESHOLDS = [30.0, 40.0, 46.0, 52.0, 35.0, 55.0]
#: join retention — small enough that the retention-clamped downstream
#: watermark still closes windows promptly, large enough to absorb the
#: pump-interleaving skew between the paced live run and the oracle's
#: dense replay (both sides' batches stay co-retained)
JD_RETENTION_MS = 3000


def jd_batch_arrays(i: int, batch_rows: int, pace: float):
    """Fact-side batch i for the join_dense feed: ``batch_arrays`` with
    readings rounded to integers (see the block comment above)."""
    ts, keys, vals = batch_arrays(i, batch_rows, pace, seed=SEED_LEFT)
    return ts, keys, np.round(vals)


def jd_schedule(total_batches: int, batch_rows: int, pace: float) -> list:
    """The join-dense control plane: 10 queries over one shared join (3
    initial, 7 live joiners, one mid-run departure).  The join-free
    tail is longer than query_dense's by the join retention — the
    retention-clamped watermark lags the feed by JD_RETENTION_MS, and a
    joiner attaching inside that lag would backfill against a floor the
    EOS flush then overruns."""
    return _dense_schedule(
        total_batches, batch_rows, pace, n_queries=JD_QUERIES,
        n_initial=JD_INITIAL, specs=JD_SPECS, thresholds=JD_THRESHOLDS,
        tail_ms=12000 + JD_RETENTION_MS,
    )


def _group_reduce(comp, arrays):
    """Composite-key group reduction shared by the golden folds — ONE
    argsort/unique reused across every value array: ``arrays`` is a list
    of (vals, [ufuncs]); returns (uniq_keys, counts, [[reduceat results
    per ufunc] per entry])."""
    order = np.argsort(comp, kind="stable")
    uniq, starts = np.unique(comp[order], return_index=True)
    cnts = np.diff(np.append(starts, len(comp)))
    outs = []
    for vals, ops in arrays:
        v = vals[order]
        outs.append([op.reduceat(v, starts) for op in ops])
    return uniq, cnts, outs


def _merge_tumbling(agg, uniq, cnts, mins, maxs, sums):
    """Accumulate one batch's per-(window,key) partials into the golden —
    shared by the tumbling and sliding folds."""
    for u, c, mn, mx, sm in zip(
        uniq.tolist(), cnts.tolist(), mins.tolist(), maxs.tolist(),
        sums.tolist(),
    ):
        w, k = divmod(u, N_KEYS)
        a = agg.setdefault(
            (w, f"sensor_{k}"), [0, float("inf"), float("-inf"), 0.0]
        )
        a[0] += c
        if mn < a[1]:
            a[1] = mn
        if mx > a[2]:
            a[2] = mx
        a[3] += sm


def golden_update(agg: dict, i: int, batch_rows: int, pace: float):
    """Fold batch i into the golden {(ws, key): [cnt, min, max, sum]},
    vectorized: the Python loop runs per GROUP (~2 windows x N_KEYS per
    batch), not per row — the parent must not steal the single core from
    the engine child it is measuring."""
    ts, keys, vals = batch_arrays(i, batch_rows, pace, seed=SEED_LEFT)
    ws = (ts // WINDOW_MS) * WINDOW_MS
    uniq, cnts, [[mins, maxs, sums]] = _group_reduce(
        ws * N_KEYS + keys, [(vals, [np.minimum, np.maximum, np.add])]
    )
    _merge_tumbling(agg, uniq, cnts, mins, maxs, sums)


_SK_MOD = None


def _sk():
    """ops/sketches.py loaded by FILE PATH, not package import — the
    sketch kernels are pure numpy by contract, and the parent must stay
    jax-free (module docstring).  Importing denormalized_tpu here would
    drag the whole engine (and jax) into the measuring process."""
    global _SK_MOD
    if _SK_MOD is None:
        import importlib.util

        path = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "denormalized_tpu", "ops", "sketches.py",
        )
        spec = importlib.util.spec_from_file_location(
            "_soak_sketches", path
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        _SK_MOD = mod
    return _SK_MOD


def golden_update_approx(agg: dict, i: int, batch_rows: int, pace: float):
    """Fold batch i into {(ws, key): [cnt, hll_plane]} with the SAME
    kernels the engine runs (stable_hash64 → hll_accumulate on a
    single-row int8 plane).  The HLL scatter-max is associative and
    commutative, so the parent's one-shot fold equals the child's
    slice-split, kill-interrupted, restored fold register for register
    — which is why the verify gate can demand EXACT integer equality
    on the estimates instead of an epsilon band."""
    sk = _sk()
    ts, keys, vals = batch_arrays(i, batch_rows, pace, seed=SEED_LEFT)
    ws = (ts // WINDOW_MS) * WINDOW_MS
    hashes = sk.stable_hash64(vals)
    comp = ws * N_KEYS + keys
    order = np.argsort(comp, kind="stable")
    uniq, starts = np.unique(comp[order], return_index=True)
    ends = np.append(starts[1:], len(comp))
    ho = hashes[order]
    for u, s, e in zip(uniq.tolist(), starts.tolist(), ends.tolist()):
        w, k = divmod(u, N_KEYS)
        a = agg.setdefault(
            (w, f"sensor_{k}"),
            [0, np.zeros((1, 1 << sk.HLL_P), dtype=np.int8)],
        )
        a[0] += e - s
        sk.hll_accumulate(
            a[1], np.zeros(e - s, dtype=np.int64), ho[s:e]
        )


# -- skew-adaptive interval-join soak feed (ISSUE 15) --------------------
# The join pipeline is a raw fact stream band-joined to a sparse
# per-second dimension stream, then windowed: every fact row matches
# EXACTLY the dim row of its key and event-second (band
# fact.ts − dim.ts ∈ [0, WINDOW_MS−1]), so the golden is a pure
# per-(window, key) fold of the fact feed plus the deterministic dim
# value.  A mid-run slice of the feed is SKEWED (one celebrity key takes
# JOIN_HOT_SHARE of the rows — long build chains, the closed-loop
# policy's trigger) and periodically LATE (rows shifted back
# JOIN_LATE_MS, still inside the join retention, and safe downstream
# because the join forwards its watermark clamped by retention).
JOIN_SKEW_START_FRAC = 0.30
JOIN_SKEW_END_FRAC = 0.70
JOIN_HOT_SHARE = 0.6
JOIN_LATE_EVERY = 7
JOIN_LATE_FRAC = 0.1
JOIN_LATE_MS = 2500
JOIN_BAND_MS = WINDOW_MS
JOIN_RETENTION_MS = 6000


def join_skew_slice(total_batches: int) -> tuple[int, int]:
    return (
        int(total_batches * JOIN_SKEW_START_FRAC),
        int(total_batches * JOIN_SKEW_END_FRAC),
    )


def join_batch_arrays(
    i: int, batch_rows: int, pace: float, total_batches: int
):
    """Fact-side batch i: ``batch_arrays`` plus the skewed + late
    mid-run slice.  Deterministic in (i, total_batches) — parent golden
    and child source share it."""
    ts, keys, vals = batch_arrays(i, batch_rows, pace, seed=SEED_LEFT)
    lo, hi = join_skew_slice(total_batches)
    if lo <= i < hi:
        rng = np.random.default_rng(77_000_003 + i)
        hot = rng.random(batch_rows) < JOIN_HOT_SHARE
        keys = np.where(hot, 0, keys)
        if (i - lo) % JOIN_LATE_EVERY == 0 and i > lo:
            late = rng.random(batch_rows) < JOIN_LATE_FRAC
            ts = np.where(late, ts - JOIN_LATE_MS, ts)
    return ts, keys, vals


def dim_value(k: int, second: int) -> float:
    """The dimension stream's deterministic enrichment value for key k
    during event-second ``second`` (T0-relative)."""
    return round((second % 97) * 1.5 + k * 0.25, 4)


def golden_update_join(
    agg: dict, i: int, batch_rows: int, pace: float, total_batches: int
):
    """Fold fact batch i into {(ws, key): [cnt, sum]} — with the
    exactly-one dim match per fact row, the joined window aggregate is
    count(fact rows), avg(fact readings), and the (constant within the
    window) dim value.  Vectorized per group like golden_update."""
    ts, keys, vals = join_batch_arrays(i, batch_rows, pace, total_batches)
    ws = (ts // WINDOW_MS) * WINDOW_MS
    uniq, cnts, [[sums]] = _group_reduce(
        ws * N_KEYS + keys, [(vals, [np.add])]
    )
    for u, c, sm in zip(uniq.tolist(), cnts.tolist(), sums.tolist()):
        w, k = divmod(u, N_KEYS)
        a = agg.setdefault((w, f"sensor_{k}"), [0, 0.0])
        a[0] += c
        a[1] += sm


SLIDE_MS = 250  # 1000ms window / 250ms slide = 4-way emission fan-out


def golden_update_sliding(agg: dict, i: int, batch_rows: int, pace: float):
    """Fold batch i into sliding-window golden {(ws, key): [cnt, min,
    max, sum]}: every row belongs to WINDOW_MS/SLIDE_MS consecutive
    windows (epoch-aligned slide indices, like the engine's on-device
    fan-out)."""
    ts, keys, vals = batch_arrays(i, batch_rows, pace, seed=SEED_LEFT)
    for j in range(WINDOW_MS // SLIDE_MS):
        ws = (ts // SLIDE_MS - j) * SLIDE_MS
        uniq, cnts, [[mins, maxs, sums]] = _group_reduce(
            ws * N_KEYS + keys, [(vals, [np.minimum, np.maximum, np.add])]
        )
        _merge_tumbling(agg, uniq, cnts, mins, maxs, sums)


KAFKA_PARTS = int(os.environ.get("SOAK_KAFKA_PARTS", 2))


def encode_json_rows(ts, keys, vals):
    """Vectorized emit_measurements-shaped JSON encode (np.char at C
    speed) for the kafka pipeline's staged feed."""
    s = np.char.add(b'{"occurred_at_ms":', ts.astype("S20"))
    s = np.char.add(s, b',"sensor_name":"sensor_')
    s = np.char.add(s, keys.astype("S4"))
    s = np.char.add(s, b'","reading":')
    s = np.char.add(s, vals.astype("S32"))
    s = np.char.add(s, b"}")
    return s.tolist()


def kafka_prep_and_feed(args, total_batches, log):
    """Start the parent-owned broker (the durable log that SURVIVES child
    kills — the restored child seeks back to its checkpointed offsets),
    pre-encode every chunk (the paced feed loop must only append staged
    slices), and return (broker, feed_thread, last_close_ws,
    feed_anchor).  Rows interleave across KAFKA_PARTS partitions per
    batch so both partitions' event-time ranges stay aligned
    (per-partition watermarks advance together).

    The feed is scheduled against the ABSOLUTE event-time origin: one
    calibration batch estimates the full staging wall, T0 is re-anchored
    just past the estimated staging end (rounded to a window boundary),
    and each batch is appended when the wall clock reaches its event
    time — so event time ≈ wall time with near-zero offset, which is
    what lets the engine's event-time lag histograms (bucketed
    exponentially) resolve real latency percentiles instead of one huge
    constant.  ``feed_anchor["epoch"]`` carries the wall second T0 maps
    to; the telemetry report subtracts ``feed_epoch_ms − T0`` (≈0 here)
    to convert raw event-time lag into end-to-end latency."""
    global T0
    import threading

    from denormalized_tpu.testing.mock_kafka import MockKafkaBroker

    broker = MockKafkaBroker().start()
    broker.create_topic("soak", partitions=KAFKA_PARTS)
    # calibration: stage one throwaway batch, scale to the run, pad 30%
    # + 2s (an UNDERestimate only means the first batches burst as
    # catch-up; the offset still collapses once the feed reaches its
    # schedule)
    t_cal = time.monotonic()
    cal_ts, cal_keys, cal_vals = batch_arrays(
        0, args.batch_rows, args.pace, seed=SEED_LEFT
    )
    cal_rows = encode_json_rows(cal_ts, cal_keys, cal_vals)
    for p in range(KAFKA_PARTS):
        rp = cal_rows[p::KAFKA_PARTS]
        MockKafkaBroker.stage_batched(
            rp, ts_ms=int(cal_ts[0]), records_per_batch=len(rp),
            base_offset=0,
        )
    est_s = (time.monotonic() - t_cal) * total_batches * 1.3 + 2.0
    if "SOAK_T0" not in os.environ:  # an explicit pin wins (determinism)
        T0 = (
            int((time.time() + est_s) * 1000) // WINDOW_MS
        ) * WINDOW_MS
    log(f"kafka soak: staging est {est_s:.0f}s — event origin T0={T0}")

    span_ms = int(total_batches * args.batch_rows * 1000.0 / args.pace)
    # two full windows of slack before the stream end: the child exits on
    # seeing this window, closed by the NATURAL watermark (events beyond
    # its end), no idle-hint dependence at the boundary
    last_close_ws = ((T0 + span_ms) // WINDOW_MS - 2) * WINDOW_MS
    staged = [[] for _ in range(KAFKA_PARTS)]
    base = [0] * KAFKA_PARTS
    t_prep = time.monotonic()
    for i in range(total_batches):
        ts, keys, vals = batch_arrays(i, args.batch_rows, args.pace,
                                      seed=SEED_LEFT)
        rows = encode_json_rows(ts, keys, vals)
        for p in range(KAFKA_PARTS):
            rp = rows[p::KAFKA_PARTS]
            staged[p].append(MockKafkaBroker.stage_batched(
                rp, ts_ms=int(ts[0]), records_per_batch=len(rp),
                base_offset=base[p],
            ))
            base[p] += len(rp)
        if i and i % max(1, total_batches // 10) == 0:
            log(f"kafka soak: staged {i}/{total_batches} chunks "
                f"({time.monotonic() - t_prep:.0f}s)")
    log(f"kafka soak: staged all {total_batches} chunks in "
        f"{time.monotonic() - t_prep:.0f}s; feed starts now")

    feed_anchor: dict = {"epoch": T0 / 1000.0}

    def feed():
        # absolute event-time schedule (see docstring): batch i's rows
        # end at event T0 + (i+1)*batch_span, so they are appended at
        # that WALL instant — event time tracks wall time directly
        t0_wall = T0 / 1000.0
        for i in range(total_batches):
            due = t0_wall + (i + 1) * args.batch_rows / args.pace
            delay = due - time.time()
            if delay > 0:
                time.sleep(delay)
            for p in range(KAFKA_PARTS):
                broker.append_staged("soak", p, staged[p][i])

    th = threading.Thread(target=feed, daemon=True)
    th.start()
    return broker, th, last_close_ws, feed_anchor


SESSION_GAP_MS = 300


# -- chaos mode: the kafka exactly-once soak under an armed FaultPlan ----


def chaos_plan(seed: int) -> dict:
    """The chaos schedule layered over the kafka soak.  Counters reset
    with each respawned child (the plan arms at import from the env), so
    ``times`` caps are PER SEGMENT.  Rates are tuned to the soak's fetch
    (~20/s across 2 partitions, much higher during post-kill catch-up)
    and commit (~1 per SOAK_CKPT_S) cadences so every rule fires within
    a kill interval."""
    return {
        "seed": seed,
        "rules": [
            # broker flap: transport-marker errors ride the reader's
            # log-and-reconnect path, then heal
            {"name": "fetch_flap", "site": "kafka.fetch", "kind": "error",
             "message": "recv: injected broker flap", "prob": 0.01,
             "times": 6},
            # worker crash: a non-transport error escapes the reader and
            # exercises the prefetch supervisor's restart-from-snapshot
            {"name": "worker_crash", "site": "kafka.fetch", "kind": "error",
             "message": "injected worker crash", "after": 250, "times": 1},
            # torn state write: only epoch-suffixed snapshot blobs (the
            # "@" restriction), caught by header verification at restore
            # → epoch fallback.  ONE per segment: fallback depth is
            # RETAINED_EPOCHS=2, so two tears landing in two consecutive
            # retained epochs would (by design) be unrecoverable — the
            # plan must stay inside the failure envelope it proves out
            {"name": "torn_snapshot", "site": "lsm.put", "kind": "torn",
             "key_substr": "@", "prob": 0.08, "times": 1},
            # commit-time transient error: absorbed by the coordinator's
            # bounded retry
            {"name": "commit_hiccup", "site": "checkpoint.commit",
             "kind": "error", "message": "injected commit hiccup",
             "prob": 0.15, "times": 2},
            # background jitter on state flushes
            {"name": "flush_latency", "site": "lsm.flush",
             "kind": "latency", "ms": 5, "prob": 0.05, "times": 20},
            # cold-tier spill write tear: only fires when the run is
            # budgeted enough to spill (the kafka soak's window state is
            # small, so this usually stays dormant here — the bigstate
            # soak's own plan exercises the tier deterministically).
            # Caught by copy_block_to_epoch's integrity check: the epoch
            # refuses the torn block, the previous intact epoch stays
            # the recovery point
            {"name": "spill_put_torn", "site": "lsm.spill_put",
             "kind": "torn", "prob": 0.05, "times": 1},
        ],
    }


def bigstate_fault_plan(seed: int) -> dict:
    """Spill-site chaos for the bigstate soak: transient reload flaps
    (healed by get_block's bounded retry), one eviction-write failure
    (degrades to keep-resident + backpressure, never kills the query),
    and a torn manifest write (best-effort metadata, logged only)."""
    return {
        "seed": seed,
        "rules": [
            {"name": "spill_get_flap", "site": "lsm.spill_get",
             "kind": "error", "message": "injected spill reload flap",
             "after": 20, "times": 2},
            {"name": "spill_put_fail", "site": "lsm.spill_put",
             "kind": "error", "message": "injected spill write failure",
             "after": 40, "times": 1},
            {"name": "spill_manifest_torn", "site": "spill.manifest",
             "kind": "torn", "after": 5, "times": 1},
        ],
    }


#: spill-site rules the bigstate acceptance gate requires to fire
BIGSTATE_REQUIRED_RULES = (
    "spill_get_flap", "spill_put_fail", "spill_manifest_torn",
)


#: the four failure modes the chaos acceptance gate requires to fire
CHAOS_REQUIRED_RULES = (
    "fetch_flap", "worker_crash", "torn_snapshot", "commit_hiccup",
)


def chaos_sim_sequence(spec: dict) -> list[dict]:
    """Drive a fresh plan through a fixed synthetic call sequence and
    return its event log — run twice, identical logs prove the seed fully
    determines the injection sequence."""
    from denormalized_tpu.runtime.faults import FaultPlan

    p = FaultPlan(dict(spec))
    for i in range(1200):
        try:
            p.on("kafka.fetch", key="soak:0")
        except Exception:
            pass
        if i % 20 == 0:
            try:
                p.on("lsm.put", key=f"window_1@{1000 + i}",
                     payload=b"x" * 64)
            except Exception:
                pass
        if i % 40 == 0:
            try:
                p.on("checkpoint.commit")
            except Exception:
                pass
            try:
                p.on("lsm.flush")
            except Exception:
                pass
    return p.event_log()


def read_chaos_events(paths) -> list[dict]:
    """One 'chaos' event dict per segment file that wrote one."""
    out = []
    for path in paths:
        last = None
        try:
            f = open(path)
        except FileNotFoundError:
            continue
        with f:
            for line in f:
                try:
                    o = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if o.get("event") == "chaos":
                    last = {k: v for k, v in o.items() if k != "event"}
        if last is not None:
            out.append(last)
    return out


def burst_ts(ts: "np.ndarray") -> "np.ndarray":
    """Squeeze each second's events into its first 600ms: the 400ms
    event-time silence every second (> SESSION_GAP_MS) closes one session
    per key per second; the mapping is monotonic, so batch-min watermarks
    are preserved."""
    sec = (ts // 1000) * 1000
    frac = ts - sec
    return sec + (frac * 3) // 5


def golden_update_session(agg: dict, i: int, batch_rows: int, pace: float):
    """Fold batch i into {(key, sec): [cnt, min_v, max_v, sum_v,
    min_ts, max_ts]} — one session per key per second under burst_ts;
    emitted start = min_ts, end = max_ts + SESSION_GAP_MS."""
    ts, keys, vals = batch_arrays(i, batch_rows, pace, seed=SEED_LEFT)
    bts = burst_ts(ts)
    sec = (bts // 1000) * 1000
    comp = sec * N_KEYS + keys
    uniq, cnts, [[vmins, vmaxs, vsums], [tmins, tmaxs]] = _group_reduce(
        comp, [
            (vals, [np.minimum, np.maximum, np.add]),
            (bts, [np.minimum, np.maximum]),
        ]
    )
    for u, c, mn, mx, sm, t0, t1 in zip(
        uniq.tolist(), cnts.tolist(), vmins.tolist(), vmaxs.tolist(),
        vsums.tolist(), tmins.tolist(), tmaxs.tolist(),
    ):
        w, k = divmod(u, N_KEYS)
        a = agg.setdefault(
            (w, f"sensor_{k}"),
            [0, float("inf"), float("-inf"), 0.0, float("inf"), 0],
        )
        a[0] += c
        if mn < a[1]:
            a[1] = mn
        if mx > a[2]:
            a[2] = mx
        a[3] += sm
        if t0 < a[4]:
            a[4] = t0
        if t1 > a[5]:
            a[5] = t1


# -- child ---------------------------------------------------------------


def child_main() -> None:
    sys.path.insert(0, str(REPO))
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", "cpu")

    from denormalized_tpu import Context, col
    from denormalized_tpu.api import functions as F
    from denormalized_tpu.api.context import EngineConfig
    from denormalized_tpu.common.constants import (
        WINDOW_END_COLUMN,
        WINDOW_START_COLUMN,
    )
    from denormalized_tpu.common.record_batch import RecordBatch
    from denormalized_tpu.common.schema import DataType, Field, Schema
    from denormalized_tpu.sources.base import (
        PartitionReader,
        Source,
        attach_canonical_timestamp,
        canonicalize_schema,
    )

    pipeline = os.environ.get("SOAK_PIPELINE", "simple")
    batch_rows = int(os.environ["SOAK_BATCH_ROWS"])
    pace = float(os.environ["SOAK_PACE"])
    total_batches = int(os.environ["SOAK_TOTAL_BATCHES"])
    ckpt_dir = os.environ["SOAK_CKPT_DIR"]
    out_path = os.environ["SOAK_OUT"]

    schema = Schema([
        Field("occurred_at_ms", DataType.INT64, nullable=False),
        Field("sensor_name", DataType.STRING, nullable=False),
        Field("reading", DataType.FLOAT64),
    ])
    key_names = np.array(
        [f"sensor_{k}" for k in range(N_KEYS)], dtype=object
    )

    class SoakPartition(PartitionReader):
        """Deterministic paced feed with Kafka-grade restore semantics:
        batch i regenerates from the index, so offset_restore is a pure
        fast-forward.  Pacing re-anchors at the restored index — the
        source IS the producer here, so a restored child continues at the
        paced rate from the checkpoint cut (event time simply lags wall
        clock by the downtime; window contents are index-deterministic
        either way)."""

        def __init__(self, seed):
            self._seed = seed
            self._i = 0
            self._anchor_wall = None
            self._anchor_i = 0

        def read(self, timeout_s=None):
            if self._i >= total_batches:
                return None
            now = time.monotonic()
            if self._anchor_wall is None:
                self._anchor_wall = now
                self._anchor_i = self._i
            due = self._anchor_wall + (
                (self._i - self._anchor_i) * batch_rows / pace
            )
            if now < due:
                time.sleep(min(due - now, timeout_s or (due - now)))
                if time.monotonic() < due:
                    # not due yet: an empty heartbeat batch (canonical ts
                    # column attached — downstream requires it on every
                    # batch, rowful or not)
                    return attach_canonical_timestamp(
                        RecordBatch.empty(schema), "occurred_at_ms",
                        fallback_ms=int(time.time() * 1000),
                    )
            if pipeline == "join":
                ts, keys, vals = join_batch_arrays(
                    self._i, batch_rows, pace, total_batches
                )
            elif pipeline == "join_dense":
                ts, keys, vals = jd_batch_arrays(self._i, batch_rows, pace)
            else:
                ts, keys, vals = batch_arrays(
                    self._i, batch_rows, pace, seed=self._seed
                )
            if pipeline == "session":
                ts = burst_ts(ts)
            self._i += 1
            b = RecordBatch(schema, [ts, key_names[keys], vals])
            return attach_canonical_timestamp(
                b, "occurred_at_ms", fallback_ms=int(time.time() * 1000)
            )

        def offset_snapshot(self):
            return {"i": self._i}

        def offset_restore(self, snap):
            self._i = int(snap["i"])
            self._anchor_wall = None  # re-anchor pacing at the restored i

    canon = canonicalize_schema(schema)

    class SoakSource(Source):
        def __init__(self, seed, name):
            self._seed = seed
            self.name = name

        @property
        def schema(self):
            return canon

        def partitions(self):
            return [SoakPartition(self._seed)]

        @property
        def unbounded(self):
            return False

    cfg = EngineConfig(
        min_batch_bucket=batch_rows,
        min_window_slots=32,
        checkpoint=True,
        checkpoint_interval_s=float(os.environ.get("SOAK_CKPT_S", 2.0)),
        state_backend_path=ckpt_dir,
        emit_on_close=True,
        source_idle_timeout_ms=int(
            os.environ.get("SOAK_IDLE_MS", 1000)
        ) or None,
        # per-segment JSONL telemetry stream (obs registry snapshots):
        # the parent merges segments' histograms into the report's
        # p50/p95/p99 e2e latency + max watermark lag + fault timeline.
        # Line-buffered writer — a SIGKILL still leaves the last
        # completed snapshot behind.
        metrics_jsonl_path=os.environ.get("SOAK_OBS_OUT"),
        metrics_jsonl_interval_s=1.0,
    )
    ctx = Context(cfg)

    def qd_aggs():
        # the foldable set MINUS variance: the shared store's variance
        # pivot differs from an independent oracle's, so stddev is not
        # byte-comparable across the two runs (docs/multi_query.md)
        return [
            F.count(col("reading")).alias("count"),
            F.sum(col("reading")).alias("sum"),
            F.min(col("reading")).alias("min"),
            F.max(col("reading")).alias("max"),
            F.avg(col("reading")).alias("average"),
        ]

    dim_user = Schema([
        Field("dim_at_ms", DataType.INT64, nullable=False),
        Field("dim_sensor", DataType.STRING, nullable=False),
        Field("w", DataType.FLOAT64),
    ])
    dim_schema = canonicalize_schema(dim_user)
    dim_seconds = -(-total_batches * batch_rows // int(pace)) + 1
    t0_sec = T0 // 1000

    class DimPartition(PartitionReader):
        """One batch per event-second: N_KEYS enrichment rows at the
        second's absolute boundary, value = dim_value(k, s).  Paced at
        one batch per wall second (``paced=False`` replays densely for
        the oracle children); restore fast-forwards by batch index like
        SoakPartition."""

        def __init__(self, paced=True):
            self._paced = paced
            self._i = 0
            self._anchor_wall = None
            self._anchor_i = 0

        def read(self, timeout_s=None):
            if self._i >= dim_seconds:
                return None
            if self._paced:
                now = time.monotonic()
                if self._anchor_wall is None:
                    self._anchor_wall = now
                    self._anchor_i = self._i
                due = self._anchor_wall + (self._i - self._anchor_i)
                if now < due:
                    time.sleep(min(due - now, timeout_s or (due - now)))
                    if time.monotonic() < due:
                        return attach_canonical_timestamp(
                            RecordBatch.empty(dim_user), "dim_at_ms",
                            fallback_ms=int(time.time() * 1000),
                        )
            s = self._i
            self._i += 1
            ts = np.full(
                N_KEYS, (t0_sec + s) * 1000, dtype=np.int64
            )
            vals = np.array(
                [dim_value(k, s) for k in range(N_KEYS)]
            )
            b = RecordBatch(dim_user, [ts, key_names.copy(), vals])
            return attach_canonical_timestamp(
                b, "dim_at_ms", fallback_ms=int(time.time() * 1000)
            )

        def offset_snapshot(self):
            return {"i": self._i}

        def offset_restore(self, snap):
            self._i = int(snap["i"])
            self._anchor_wall = None

    class DimSource(Source):
        name = "soak_dim"

        def __init__(self, paced=True):
            self._paced = paced

        @property
        def schema(self):
            return dim_schema

        def partitions(self):
            return [DimPartition(self._paced)]

        @property
        def unbounded(self):
            return False

    if pipeline in ("query_dense", "join_dense"):
        # ISSUE 16 acceptance: 50 queries register/deregister LIVE on
        # one shared slice pipeline (staggered event-time arrivals,
        # incl. mid-epoch joins), SIGKILLed mid-run; every query's
        # committed emissions must be byte-identical to an independent
        # uninterrupted oracle from its first exact window.  The
        # schedule is event-time keyed, so this child re-issues it
        # VERBATIM every incarnation: subscribers the restored
        # checkpoint carried adopt their snapshotted cursor (orphan
        # adoption by tag), departed tags stay departed, future ops
        # fire when stream time reaches them.
        # join_dense (ISSUE 17) is the same contract one operator
        # deeper: every query windows over the SAME fact×dim interval
        # join, so the group runs ONE StreamingJoinExec — its sides
        # snapshot in the SAME epoch cut as the slice partials and the
        # per-tag cursors.
        from denormalized_tpu.runtime.multi_query import SharedPipeline

        if pipeline == "join_dense":
            cfg.join_retention_ms = JD_RETENTION_MS
            # both sides' band values are in-order (sorted fact batches,
            # strictly increasing dim seconds), so zero slack is exact
            cfg.join_band_slack_ms = 0
            sched = jd_schedule(total_batches, batch_rows, pace)
            unit_ms = JD_UNIT_MS
            fact = ctx.from_source(
                SoakSource(SEED_LEFT, "soak_fact"), name="soak_fact"
            )
            dim = ctx.from_source(DimSource(), name="soak_dim")
            base = fact.join(
                dim, "inner", ["sensor_name"], ["dim_sensor"],
                band=("occurred_at_ms", "dim_at_ms", 0, JOIN_BAND_MS - 1),
            )
        else:
            sched = qd_schedule(total_batches, batch_rows, pace)
            unit_ms = QD_UNIT_MS
            base = ctx.from_source(
                SoakSource(SEED_LEFT, "soak_qd"), name="soak_qd"
            )
        aggs = qd_aggs()

        def q_stream(spec):
            return base.filter(col("reading") > spec["thr"]).window(
                ["sensor_name"], aggs, spec["L"], spec["S"]
            )

        with open(out_path, "a", buffering=1) as out:
            out.write(json.dumps({"event": "ready", "t": time.time()}) + "\n")
            announced: list = []

            def mk_sink(qid):
                def sink(b):
                    coord = getattr(ctx, "_last_coord", None)
                    if not announced:
                        # exactly-once output protocol: announce the
                        # recovery point before any window line (the
                        # parent clips the predecessor's uncommitted
                        # suffix at this epoch)
                        announced.append(True)
                        out.write(json.dumps({
                            "event": "restored",
                            "epoch": (
                                (coord.restored_epoch or 0)
                                if coord is not None else None
                            ),
                        }) + "\n")
                    ep = (
                        (coord.committed_epoch or 0) + 1
                        if coord is not None else None
                    )
                    ws = b.column(WINDOW_START_COLUMN)
                    names = b.column("sensor_name")
                    cols = [
                        b.column(c)
                        for c in ("count", "sum", "min", "max", "average")
                    ]
                    for i in range(b.num_rows):
                        # full float repr — the parent compares these
                        # for byte-identity, not tolerance
                        rec = {
                            "q": qid, "ws": int(ws[i]),
                            "key": str(names[i]),
                            "count": int(cols[0][i]),
                            "sum": float(cols[1][i]),
                            "min": float(cols[2][i]),
                            "max": float(cols[3][i]),
                            "avg": float(cols[4][i]),
                        }
                        if ep is not None:
                            rec["ep"] = ep
                        out.write(json.dumps(rec) + "\n")
                return sink

            initial = [s for s in sched if "join" not in s]
            sp = SharedPipeline(
                ctx,
                [(q_stream(s), mk_sink(s["qid"])) for s in initial],
                labels=[f"q{s['qid']}" for s in initial],
            )
            assert sp.root.unit_ms == unit_ms, sp.root.unit_ms
            # one build per process incarnation: live joins/leaves must
            # NEVER rebuild the shared pipeline (the parent gates on
            # at most one of these per segment)
            out.write(json.dumps({"event": "build", "t": time.time()}) + "\n")
            for s in sched:
                if "join" not in s:
                    continue
                tag = sp.register(
                    q_stream(s), mk_sink(s["qid"]),
                    label=f"q{s['qid']}", when_ts=s["join"],
                )
                assert tag == s["qid"], (tag, s["qid"])
            for s in sched:
                if "leave" in s:
                    sp.deregister(s["qid"], when_ts=s["leave"])
            sp.run()
            m = sp.root.metrics()
            out.write(json.dumps({"event": "metrics", **{
                k: v for k, v in m.items() if isinstance(v, (int, float))
            }}) + "\n")
            out.write(json.dumps({"event": "done", "t": time.time()}) + "\n")
        return

    if pipeline in ("query_dense_oracle", "join_dense_oracle"):
        # per-query independent UNINTERRUPTED oracles over the same
        # index-deterministic feed, replayed densely (no pacing): the
        # byte-identity referent for the live shared run.  Slice mode
        # pins to the shared group's gcd unit so fold order matches
        # (the aggregates carry extrema, so both runs take the lexsort
        # fold lane).  The join_dense oracle runs each query's OWN
        # fact×dim join under the same retention/band-slack config —
        # the joined row multiset is interleaving-free, so the shared
        # run must reproduce it byte for byte.
        from denormalized_tpu.sources.memory import MemorySource

        joined_oracle = pipeline == "join_dense_oracle"
        sched = (
            jd_schedule(total_batches, batch_rows, pace) if joined_oracle
            else qd_schedule(total_batches, batch_rows, pace)
        )
        feed = []
        for i in range(total_batches):
            if joined_oracle:
                ts, keys, vals = jd_batch_arrays(i, batch_rows, pace)
            else:
                ts, keys, vals = batch_arrays(
                    i, batch_rows, pace, seed=SEED_LEFT
                )
            feed.append(RecordBatch(schema, [ts, key_names[keys], vals]))
        with open(out_path, "a", buffering=1) as out:
            for spec in sched:
                ocfg = EngineConfig(
                    min_batch_bucket=batch_rows,
                    min_window_slots=32,
                    slice_windows=True,
                    slice_unit_ms=JD_UNIT_MS if joined_oracle
                    else QD_UNIT_MS,
                    emit_on_close=True,
                )
                if joined_oracle:
                    ocfg.join_retention_ms = JD_RETENTION_MS
                    ocfg.join_band_slack_ms = 0
                octx = Context(ocfg)
                src = octx.from_source(
                    MemorySource.from_batches(
                        feed, timestamp_column="occurred_at_ms"
                    ),
                    name="soak_fact" if joined_oracle else "soak_qd",
                )
                if joined_oracle:
                    src = src.join(
                        octx.from_source(
                            DimSource(paced=False), name="soak_dim"
                        ),
                        "inner", ["sensor_name"], ["dim_sensor"],
                        band=(
                            "occurred_at_ms", "dim_at_ms", 0,
                            JOIN_BAND_MS - 1,
                        ),
                    )
                ds = src.filter(col("reading") > spec["thr"]).window(
                    ["sensor_name"], qd_aggs(), spec["L"], spec["S"]
                )
                for b in ds.stream():
                    if not b.schema.has(WINDOW_START_COLUMN):
                        continue
                    ws = b.column(WINDOW_START_COLUMN)
                    names = b.column("sensor_name")
                    cols = [
                        b.column(c)
                        for c in ("count", "sum", "min", "max", "average")
                    ]
                    for i in range(b.num_rows):
                        out.write(json.dumps({
                            "q": spec["qid"], "ws": int(ws[i]),
                            "key": str(names[i]),
                            "count": int(cols[0][i]),
                            "sum": float(cols[1][i]),
                            "min": float(cols[2][i]),
                            "max": float(cols[3][i]),
                            "avg": float(cols[4][i]),
                        }) + "\n")
            out.write(json.dumps({"event": "done", "t": time.time()}) + "\n")
        return

    last_close_ws = (
        int(os.environ["SOAK_LAST_CLOSE_WS"])
        if pipeline == "kafka" else None
    )
    if pipeline == "kafka":
        # the reference-shaped path end to end: broker -> native wire
        # client -> native JSON decode -> window, checkpointed offsets
        # restored by seek.  The feed keeps running across kills (the
        # broker is the durable log), so recovery includes backlog
        # catch-up — exactly a real deployment's restart
        ds = ctx.from_topic(
            "soak",
            schema=schema,
            bootstrap_servers=os.environ["SOAK_BOOTSTRAP"],
            timestamp_column="occurred_at_ms",
        ).window(
            ["sensor_name"],
            [
                F.count(col("reading")).alias("count"),
                F.min(col("reading")).alias("min"),
                F.max(col("reading")).alias("max"),
                F.avg(col("reading")).alias("average"),
            ],
            WINDOW_MS,
        )
    elif pipeline == "udaf":
        # stateful Python accumulator (host-frame path, udaf_exec):
        # Accumulator.state()/merge() snapshots ride the checkpoint —
        # the SerializableAccumulator contract through repeated kills
        from denormalized_tpu.api.udaf import Accumulator

        class Spread(Accumulator):
            def __init__(self):
                self.lo = float("inf")
                self.hi = float("-inf")

            def update(self, values):
                if len(values):
                    self.lo = min(self.lo, float(values.min()))
                    self.hi = max(self.hi, float(values.max()))

            def merge(self, states):
                self.lo = min(self.lo, states[0])
                self.hi = max(self.hi, states[1])

            def state(self):
                return [self.lo, self.hi]

            def evaluate(self):
                return self.hi - self.lo if self.hi >= self.lo else 0.0

        spread = F.udaf(Spread, DataType.FLOAT64, "spread")
        ds = ctx.from_source(
            SoakSource(SEED_LEFT, "soak_u"), name="soak_u"
        ).window(
            ["sensor_name"],
            [
                spread(col("reading")).alias("spread"),
                F.count(col("reading")).alias("count"),
            ],
            WINDOW_MS,
        )
    elif pipeline == "approx":
        # sketch-native approximate aggregates on the slice store
        # (docs/approx_aggregates.md): approx_distinct rides an HLL
        # register plane whose scatter-max fold is associative and
        # commutative, so the plane — and its integer estimate — is
        # independent of how the feed was split across checkpoint
        # segments.  The parent's golden replays the SAME kernels
        # (ops/sketches.py loaded by file path; pure numpy, keeps the
        # parent jax-free) and holds every committed estimate to EXACT
        # integer equality through repeated SIGKILLs — the sketch
        # restore path is bit-faithful or this gate goes red.
        cfg.slice_windows = True
        cfg.slice_unit_ms = SLIDE_MS  # kills land mid-window, mid-slice
        ds = ctx.from_source(
            SoakSource(SEED_LEFT, "soak_ax"), name="soak_ax"
        ).window(
            ["sensor_name"],
            [
                F.count(col("reading")).alias("count"),
                F.approx_distinct(col("reading")).alias("distinct"),
            ],
            WINDOW_MS,
        )
    elif pipeline == "bigstate":
        # larger-than-memory session state: phase A opens SOAK_BS_KEYS
        # singleton sessions (gap = the whole phase-A event span, so all
        # of them stay open simultaneously); phase B advances the
        # watermark in waves of SOAK_BS_WAVE keys so sessions close
        # progressively instead of one giant reload-everything sweep.
        # Budgeted children (SOAK_BS_BUDGET > 0) run the cold tier +
        # checkpointing and get SIGKILLed; the reference child runs the
        # identical feed unbudgeted — emissions must match byte-for-byte.
        bs_keys = int(os.environ["SOAK_BS_KEYS"])
        bs_wave = int(os.environ["SOAK_BS_WAVE"])
        bs_budget = int(os.environ.get("SOAK_BS_BUDGET", "0") or 0)
        if bs_budget:
            cfg.state_budget_bytes = bs_budget
        else:
            # reference (unbudgeted) child: same feed, no cold tier, no
            # snapshots — the byte-identical oracle the budgeted run is
            # compared against
            cfg.checkpoint = False
        bs_gap = bs_keys  # DT = 1ms per key
        wave_rows = 64
        a_batches = -(-bs_keys // batch_rows)
        waves = -(-bs_keys // bs_wave)

        bs_user = Schema([
            Field("occurred_at_ms", DataType.INT64, nullable=False),
            Field("sensor_id", DataType.INT64, nullable=False),
            Field("reading", DataType.FLOAT64),
        ])
        bs_schema = canonicalize_schema(bs_user)

        class BigstatePartition(PartitionReader):
            """Index-deterministic feed (restore = fast-forward)."""

            def __init__(self):
                self._i = 0

            def read(self, timeout_s=None):
                i = self._i
                if i >= a_batches + waves:
                    return None
                self._i += 1
                if i < a_batches:
                    lo = i * batch_rows
                    kids = np.arange(
                        lo, min(lo + batch_rows, bs_keys), dtype=np.int64
                    )
                    ts = T0 + kids  # DT = 1ms
                else:
                    j = i - a_batches + 1
                    base = bs_keys + (j - 1) * wave_rows
                    kids = np.arange(
                        base, base + wave_rows, dtype=np.int64
                    )
                    ts = np.full(
                        wave_rows, T0 + bs_gap + j * bs_wave,
                        dtype=np.int64,
                    )
                vals = (kids % 997) * 0.5 + 1.0
                b = RecordBatch(bs_user, [ts, kids, vals])
                return attach_canonical_timestamp(
                    b, "occurred_at_ms",
                    fallback_ms=int(time.time() * 1000),
                )

            def offset_snapshot(self):
                return {"i": self._i}

            def offset_restore(self, snap):
                self._i = int(snap["i"])

        class BigstateSource(Source):
            name = "bigstate"

            @property
            def schema(self):
                return bs_schema

            def partitions(self):
                return [BigstatePartition()]

            @property
            def unbounded(self):
                return False

        ds = ctx.from_source(
            BigstateSource(), name="bigstate"
        ).session_window(
            ["sensor_id"],
            [
                F.count(col("reading")).alias("count"),
                F.min(col("reading")).alias("min"),
                F.max(col("reading")).alias("max"),
                F.avg(col("reading")).alias("average"),
            ],
            bs_gap,
        )
    elif pipeline == "session":
        ds = ctx.from_source(
            SoakSource(SEED_LEFT, "soak_s"), name="soak_s"
        ).session_window(
            ["sensor_name"],
            [
                F.count(col("reading")).alias("count"),
                F.min(col("reading")).alias("min"),
                F.max(col("reading")).alias("max"),
                F.avg(col("reading")).alias("average"),
            ],
            SESSION_GAP_MS,
        )
    elif pipeline == "join":
        # skew-adaptive interval join (ISSUE 15, docs/joins.md): a raw
        # fact stream — skewed + late mid-run (join_batch_arrays) —
        # band-joined to a sparse per-second dimension stream on the
        # sensor key (fact.ts − dim.ts ∈ [0, WINDOW_MS−1]: exactly the
        # dim row of the fact row's event-second), then windowed.  The
        # skew slice builds celebrity chains on the fact side, the
        # closed-loop policy sub-partitions the hot key live (visible in
        # the telemetry as dnz_join_adaptations_total), kills land while
        # hot blocks are live, and the restored child rebuilds them from
        # the snapshot's representative rows.
        cfg.join_retention_ms = JOIN_RETENTION_MS
        # band-aware eviction (ISSUE 17, docs/joins.md): the band is far
        # tighter than retention, so band-dead batches release early.
        # Slack = the feed's bounded lateness — late rows sit at most
        # JOIN_LATE_MS below an on-time batch's band minimum, which is
        # exactly the horizon the slack re-opens
        cfg.join_band_slack_ms = JOIN_LATE_MS
        left = ctx.from_source(
            SoakSource(SEED_LEFT, "soak_fact"), name="soak_fact"
        )
        right = ctx.from_source(DimSource(), name="soak_dim")
        ds = left.join(
            right, "inner", ["sensor_name"], ["dim_sensor"],
            band=("occurred_at_ms", "dim_at_ms", 0, JOIN_BAND_MS - 1),
        ).window(
            ["sensor_name"],
            [
                F.count(col("reading")).alias("count"),
                F.avg(col("reading")).alias("avg_t"),
                F.avg(col("w")).alias("avg_h"),
            ],
            WINDOW_MS,
        )
    else:
        ds = ctx.from_source(SoakSource(SEED_LEFT, "soak"), name="soak").window(
            ["sensor_name"],
            [
                F.count(col("reading")).alias("count"),
                F.min(col("reading")).alias("min"),
                F.max(col("reading")).alias("max"),
                F.avg(col("reading")).alias("average"),
            ],
            WINDOW_MS,
            SLIDE_MS if pipeline == "sliding" else None,
        )
    it = ds.stream()
    if pipeline == "bigstate":
        # the drive loop only wakes on EMITTED batches, and phase A
        # emits nothing for minutes — a side sampler thread records the
        # state accounting (working set, spill counters) on a wall
        # cadence into its own file (no interleaving with the emission
        # stream; state_info reads are single-writer-defensive by
        # contract)
        import threading as _threading

        def _state_sampler():
            with open(out_path + ".state", "a", buffering=1) as sf:
                while True:
                    time.sleep(1.0)
                    try:
                        root = getattr(ctx, "_last_physical", None)
                        if root is None:
                            continue
                        info = None
                        stack = [root]
                        while stack:
                            cur = stack.pop()
                            if type(cur).__name__ == "SessionWindowExec":
                                info = cur.state_info()
                                break
                            stack.extend(cur.children)
                        if info:
                            sf.write(json.dumps({
                                "event": "state",
                                "bytes": info.get("state_bytes"),
                                "evictable": info.get("evictable_bytes"),
                                "live_keys": info.get("live_keys"),
                                "spilled_bytes": info.get(
                                    "spilled_bytes", 0
                                ),
                                "spilled_keys": info.get(
                                    "spilled_keys", 0
                                ),
                                "spill": info.get("spill"),
                            }) + "\n")
                    except Exception:
                        pass

        _threading.Thread(
            target=_state_sampler, daemon=True, name="bs-state"
        ).start()
    stop = False
    coord = None
    announced = False
    last_chaos_write = 0.0
    chaos_log_seen = 0

    def write_chaos_event(out) -> None:
        """Snapshot of self-healing/fault state, rewritten every few
        seconds so a SIGKILLed segment still leaves its (nearly) final
        fault log behind — the parent keeps the LAST one per segment."""
        try:
            from denormalized_tpu.runtime import faults as fault_mod
            from denormalized_tpu.state.lsm import get_global_state_backend

            chaos: dict = {}
            if coord is not None:
                chaos["commit_retries"] = coord.commit_retries
                chaos["restored_from_fallback"] = bool(
                    coord.restored_from_fallback
                )
            try:
                chaos["replay_truncated"] = int(
                    get_global_state_backend().replay_truncated
                )
            except Exception:
                pass
            try:
                # restart counts must ride THIS snapshot (which survives
                # SIGKILL) — the 'metrics' event only exists for segments
                # that reach EOS, i.e. never the killed ones
                from denormalized_tpu.runtime.tracing import collect_metrics

                chaos["prefetch_restarts"] = sum(
                    m.get("prefetch_restarts", 0)
                    for m in collect_metrics(ctx._last_physical).values()
                )
            except Exception:
                pass
            p = fault_mod.plan()
            if p is not None:
                chaos["fault_log"] = p.event_log()
            if chaos:
                out.write(json.dumps({"event": "chaos", **chaos}) + "\n")
            if pipeline == "bigstate":
                # state accounting snapshot (survives SIGKILL like the
                # chaos event): the parent derives the unbudgeted
                # working set and the budgeted run's resident bound
                # from these
                op = ctx._last_physical
                info = None
                stack = [op]
                while stack:
                    cur = stack.pop()
                    if type(cur).__name__ == "SessionWindowExec":
                        info = cur.state_info()
                        break
                    stack.extend(cur.children)
                if info is not None:
                    out.write(json.dumps({
                        "event": "state",
                        "bytes": info.get("state_bytes"),
                        "evictable": info.get("evictable_bytes"),
                        "live_keys": info.get("live_keys"),
                        "spilled_bytes": info.get("spilled_bytes", 0),
                        "spilled_keys": info.get("spilled_keys", 0),
                        "spill": info.get("spill"),
                    }) + "\n")
        except Exception:
            pass

    with open(out_path, "a", buffering=1) as out:
        out.write(json.dumps({"event": "ready", "t": time.time()}) + "\n")
        for batch in it:
            # snapshot chaos state on a 5s cadence AND immediately when
            # the fault log grew — an injection in the last pre-SIGKILL
            # seconds must not vanish from the segment's record (the
            # acceptance gate counts required rules from these events)
            mono = time.monotonic()
            try:
                from denormalized_tpu.runtime import faults as _fm

                _p = _fm.plan()
                log_len = len(_p.events) if _p is not None else 0
            except Exception:
                log_len = 0
            if mono - last_chaos_write > 5.0 or log_len > chaos_log_seen:
                last_chaos_write = mono
                chaos_log_seen = log_len
                write_chaos_event(out)
            if not announced:
                # exactly-once output protocol: announce the recovery
                # point (frozen at coordinator construction) BEFORE any
                # window line.  The parent clips the PREVIOUS segment's
                # lines tagged beyond this epoch — they are the
                # uncommitted suffix this incarnation's replay
                # regenerates (a transactional sink's
                # truncate-on-restore, done reader-side).
                coord = getattr(ctx, "_last_coord", None)
                out.write(json.dumps({
                    "event": "restored",
                    "epoch": (
                        (coord.restored_epoch or 0)
                        if coord is not None else None
                    ),
                }) + "\n")
                announced = True
            if not batch.schema.has(WINDOW_START_COLUMN):
                continue
            now = time.time()
            ws = batch.column(WINDOW_START_COLUMN)
            names = batch.column(
                "sensor_id" if pipeline == "bigstate" else "sensor_name"
            )
            for i in range(batch.num_rows):
                if pipeline == "udaf":
                    rec = {
                        "t": round(now, 3),
                        "ws": int(ws[i]),
                        "key": str(names[i]),
                        "count": int(batch.column("count")[i]),
                        "spread": round(float(batch.column("spread")[i]), 4),
                    }
                elif pipeline in ("session", "bigstate"):
                    rec = {
                        "t": round(now, 3),
                        "ws": int(ws[i]),
                        "key": (
                            int(names[i]) if pipeline == "bigstate"
                            else str(names[i])
                        ),
                        "we": int(batch.column(WINDOW_END_COLUMN)[i]),
                        "count": int(batch.column("count")[i]),
                        "min": round(float(batch.column("min")[i]), 4),
                        "max": round(float(batch.column("max")[i]), 4),
                        "avg": round(float(batch.column("average")[i]), 4),
                    }
                elif pipeline == "join":
                    rec = {
                        "t": round(now, 3),
                        "ws": int(ws[i]),
                        "key": str(names[i]),
                        "count": int(batch.column("count")[i]),
                        "avg_t": round(float(batch.column("avg_t")[i]), 4),
                        "avg_h": round(float(batch.column("avg_h")[i]), 4),
                    }
                elif pipeline == "approx":
                    # the estimate is an INT — no rounding tolerance;
                    # the golden recomputes it with the same kernels
                    rec = {
                        "t": round(now, 3),
                        "ws": int(ws[i]),
                        "key": str(names[i]),
                        "count": int(batch.column("count")[i]),
                        "distinct": int(batch.column("distinct")[i]),
                    }
                else:
                    rec = {
                        "t": round(now, 3),
                        "ws": int(ws[i]),
                        "key": str(names[i]),
                        "count": int(batch.column("count")[i]),
                        "min": round(float(batch.column("min")[i]), 4),
                        "max": round(float(batch.column("max")[i]), 4),
                        "avg": round(float(batch.column("average")[i]), 4),
                    }
                if coord is not None:
                    # in-flight epoch tag: this line is committed once
                    # epoch `ep` commits — emissions between barrier N
                    # and N+1 belong to (uncommitted) epoch N+1, and
                    # stream order guarantees the commit never precedes
                    # the write
                    rec["ep"] = (coord.committed_epoch or 0) + 1
                out.write(json.dumps(rec) + "\n")
                if last_close_ws is not None and rec["ws"] >= last_close_ws:
                    stop = True  # unbounded source: close at the target
            if stop:
                it.close()
                break
        try:
            from denormalized_tpu.runtime.tracing import collect_metrics

            sums: dict = {}
            for m in collect_metrics(ctx._last_physical).values():
                for k, v in m.items():
                    if isinstance(v, (int, float)):
                        sums[k] = sums.get(k, 0) + v
            out.write(json.dumps({
                "event": "metrics",
                **{k: sums[k] for k in (
                    "late_rows", "rows_out", "rows_in", "batches_out",
                    "prefetch_restarts", "prefetch_restarted_partitions",
                    "salvaged_rows", "hot_keys", "adaptations",
                ) if k in sums},
            }) + "\n")
        except Exception:
            pass
        write_chaos_event(out)
        out.write(json.dumps({"event": "done", "t": time.time()}) + "\n")


# -- parent --------------------------------------------------------------


def read_emissions(paths):
    """ALL COMMITTED emitted window rows across segment files →
    ({(ws,key): [tuple, ...]}, duplicate_emissions, done_seen,
    child_metrics, uncommitted_clipped) — every committed occurrence is
    kept, so a wrong first emission can't hide behind a correct
    re-emission after restore.  ``child_metrics`` is one dict per
    'metrics' event found (only children that reached EOS write one —
    SIGKILLed segments leave none).  A torn tail line (SIGKILL
    mid-write) is skipped.

    Exactly-once output: each line carries ``ep``, the in-flight epoch
    at write time, and each restored child announces the epoch it
    recovered from.  A killed segment's lines tagged BEYOND the epoch
    the successor restored from are the uncommitted suffix that
    successor's replay regenerates — the recovery reader discards them
    (the transactional sink's truncate-on-restore, applied where the
    union is read).  The clip boundary for segment i is the restore
    epoch of the next segment that emitted windows: an intermediate
    windowless segment may have advanced commits without re-emitting
    anything, and clipping by ITS restore point would drop lines nobody
    regenerates.  Lines without ``ep`` (no checkpointing) are always
    kept — at-least-once counting, as before."""
    done = False
    metrics: list = []
    segments: list = []  # (seg_idx, restored_epoch|None, [line dicts])
    for seg_idx, path in enumerate(paths, 1):
        restored = None
        lines: list = []
        try:
            f = open(path)
        except FileNotFoundError:
            segments.append((seg_idx, restored, lines))
            continue
        with f:
            for line in f:
                try:
                    o = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if o.get("event") == "done":
                    done = True
                elif o.get("event") == "restored":
                    restored = o.get("epoch")
                elif o.get("event") == "metrics":
                    metrics.append({k: v for k, v in o.items()
                                    if k != "event"})
                elif "ws" in o:
                    lines.append(o)
        segments.append((seg_idx, restored, lines))

    clipped = 0
    kept: list = []  # (seg_idx, line)
    for i, (seg_idx, _restored, lines) in enumerate(segments):
        boundary = None  # None = final (or no emitting successor): keep all
        for j in range(i + 1, len(segments)):
            if segments[j][2]:  # next segment that emitted windows
                boundary = segments[j][1]
                break
        for o in lines:
            ep = o.get("ep")
            if (
                boundary is not None
                and ep is not None
                and ep > (boundary or 0)
            ):
                clipped += 1
                continue
            kept.append((seg_idx, o))

    wins: dict = {}
    dupes = 0
    for seg_idx, o in kept:
        if "q" in o:  # query-dense record: per-query key, full precision
            k = (o["ws"], o["key"], o["q"])
            occ = wins.setdefault(k, [])
            if occ:
                dupes += 1
            occ.append((
                (o["count"], o["sum"], o["min"], o["max"], o["avg"]),
                seg_idx,
            ))
            continue
        k = (o["ws"], o["key"])
        occ = wins.setdefault(k, [])
        if occ:
            dupes += 1
        if "avg_t" in o:  # join pipeline record
            vals = (o["count"], o["avg_t"], o["avg_h"])
        elif "we" in o:  # session record: bounds + aggregates
            vals = (o["count"], o["min"], o["max"],
                    o["avg"], o["ws"], o["we"])
        elif "spread" in o:  # udaf record
            vals = (o["count"], o["spread"])
        elif "distinct" in o:  # approx record: exact integer estimate
            vals = (o["count"], o["distinct"])
        else:
            vals = (o["count"], o["min"], o["max"], o["avg"])
        # segment attribution rides along for diagnosis but stays OUT
        # of the compared tuple
        occ.append((vals, seg_idx))
    return wins, dupes, done, metrics, clipped


def qd_verify(args, env, work, wins, seg_paths, total_batches, *,
              sched_fn=qd_schedule,
              oracle_pipeline="query_dense_oracle") -> dict:
    """Dense-pipeline acceptance (query_dense and join_dense): spawn
    the oracle child (independent uninterrupted runs over the same
    feed), then hold every live query's committed emissions to
    BYTE-identity with its oracle from its first exact window — late
    joiners' backfilled windows included, departed queries' prefixes
    included, duplicate committed occurrences each checked.  Also
    counts pipeline builds per segment (live joins/leaves must never
    rebuild the shared pipeline)."""
    oracle_path = os.path.join(work, "qd_oracle.jsonl")
    oenv = dict(env)
    oenv["SOAK_PIPELINE"] = oracle_pipeline
    oenv["SOAK_OUT"] = oracle_path
    rc = subprocess.call(
        [sys.executable, os.path.abspath(__file__), "--child"],
        env=oenv, stdout=sys.stderr, stderr=sys.stderr,
    )
    oracle: dict = {}  # qid -> {(key, ws): vals}
    if rc == 0:
        with open(oracle_path) as f:
            for line in f:
                try:
                    o = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if "ws" not in o:
                    continue
                oracle.setdefault(o["q"], {})[(o["key"], o["ws"])] = (
                    o["count"], o["sum"], o["min"], o["max"], o["avg"]
                )

    builds_per_seg = []
    for p in seg_paths:
        n = 0
        try:
            with open(p) as f:
                for line in f:
                    if '"event": "build"' in line:
                        n += 1
        except FileNotFoundError:
            pass
        builds_per_seg.append(n)

    per_q: dict = {}  # qid -> {(key, ws): [vals, ...]}
    for (ws, key, q), occs in wins.items():
        per_q.setdefault(q, {}).setdefault((key, ws), []).extend(
            v for v, _seg in occs
        )

    sched = sched_fn(total_batches, args.batch_rows, args.pace)
    specs = {s["qid"]: s for s in sched}
    failures: list = []
    silent: list = []
    backfilled = 0
    backfill_missing: list = []
    for q, spec in specs.items():
        got = per_q.get(q)
        if not got:
            silent.append(q)
            continue
        want_all = oracle.get(q, {})
        min_ws = min(ws for (_k, ws) in got)
        max_ws = max(ws for (_k, ws) in got)
        leave = spec.get("leave")
        if leave is None:
            # survivor: exact through the EOS flush — every oracle
            # window from the first emitted one onward, byte-identical
            want = {kw: v for kw, v in want_all.items() if kw[1] >= min_ws}
        else:
            want = {
                kw: v for kw, v in want_all.items()
                if min_ws <= kw[1] <= max_ws
            }
            if max_ws > leave + spec["L"]:
                failures.append(
                    (q, "emitted past its leave", max_ws, leave)
                )
        incoherent = [
            kw for kw, vs in got.items() if any(v != vs[0] for v in vs[1:])
        ]
        if incoherent:
            failures.append(
                (q, "inconsistent duplicate emissions", incoherent[:2], None)
            )
        flat = {kw: vs[0] for kw, vs in got.items()}
        if flat != want:
            failures.append((q, "diverged from oracle", {
                "missing": sorted(set(want) - set(flat))[:2],
                "extra": sorted(set(flat) - set(want))[:2],
                "value_diff": [
                    kw for kw in set(flat) & set(want)
                    if flat[kw] != want[kw]
                ][:2],
            }, None))
        join = spec.get("join")
        if join is not None:
            if min_ws < join:
                backfilled += 1
            elif qd_class_continuous(specs, q):
                backfill_missing.append(q)
    return {
        "oracle_rc": rc,
        "oracle_windows": sum(len(v) for v in oracle.values()),
        "queries": len(specs),
        "joined_live": sum(1 for s in sched if "join" in s),
        "departed": sum(1 for s in sched if "leave" in s),
        "pipeline_builds_per_segment": builds_per_seg,
        "max_builds_per_segment": max(builds_per_seg, default=0),
        "queries_silent": silent,
        "backfilled_joiners": backfilled,
        "backfill_missing": backfill_missing,
        "failures": len(failures),
        "failure_sample": failures[:3],
    }


def _obs_readers():
    """Load the obs read-side helpers WITHOUT importing the engine
    package (the soak parent never imports jax; the module is stdlib-only
    by contract — see denormalized_tpu/obs/readers.py)."""
    import importlib.util

    path = REPO / "denormalized_tpu" / "obs" / "readers.py"
    spec = importlib.util.spec_from_file_location("_soak_obs_readers", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def derive_telemetry(obs_paths, anchor_epoch_ms=None) -> dict:
    """The report's time-series section, derived entirely from the
    segments' JSONL telemetry streams: p50/p95/p99 end-to-end latency
    and max watermark lag (histograms merged across killed segments),
    plus the fault-event timeline (per-site injection deltas).

    The engine's lag metrics are event-time-relative (wall − event
    time), so a paced feed replaying from T0 carries a constant offset
    ``anchor_epoch_ms − T0``; when the feed anchor is known (kafka
    pipeline) the report also emits anchored values = true end-to-end
    latency."""
    R = _obs_readers()

    def final_hists(snaps, prefix):
        # matched by PREFIX, not one hardcoded op label: the session and
        # udaf pipelines emit their lag series under op="session"/"udaf"
        last: dict = {}
        for snap in reversed(snaps):
            m = snap.get("metrics", {})
            if any(k.startswith(prefix) for k in m):
                last = m
                break
        return [
            v for k, v in last.items()
            if k.startswith(prefix) and isinstance(v, dict)
        ]

    finals_emit, finals_wm = [], []
    timeline: list = []
    adapt_timeline: list = []
    adapt_by_seg: list = []
    n_snaps = 0
    segs_reporting = 0
    peak_state = 0.0
    peak_spilled = 0.0
    salvaged = 0.0
    state_hot: list = []
    for seg_i, path in enumerate(obs_paths):
        snaps = R.read_stream(path)
        if not snaps:
            continue
        segs_reporting += 1
        n_snaps += len(snaps)
        finals_emit += final_hists(snaps, "dnz_emit_event_lag_ms")
        finals_wm += final_hists(snaps, "dnz_watermark_lag_hist_ms")
        # timeline per SEGMENT: each killed child restarts its counters
        # from zero, so the delta baseline must reset with it
        timeline += R.counter_timeline(snaps, "dnz_fault_injections_total")
        # closed-loop adaptation events (dnz_join_adaptations_total,
        # labeled action=adapt|fold + side): same per-segment delta
        # derivation — a kill while the counter is ahead of its folds
        # landed MID-ADAPTATION (hot sub-partitions live at the cut)
        seg_adapt = R.counter_timeline(
            snaps, "dnz_join_adaptations_total"
        )
        adapt_timeline += seg_adapt
        final_counts: dict = {}
        for snap in snaps:
            vals = {
                k: v for k, v in snap.get("metrics", {}).items()
                if k.startswith("dnz_join_adaptations_total")
                and isinstance(v, (int, float))
            }
            if vals:
                final_counts = vals
        if final_counts:
            adapts = sum(
                v for k, v in final_counts.items()
                if 'action="adapt"' in k
            )
            folds = sum(
                v for k, v in final_counts.items()
                if 'action="fold"' in k
            )
            adapt_by_seg.append({
                "segment": seg_i + 1,
                "adapt": round(adapts),
                "fold": round(folds),
                "hot_blocks_live_at_end": round(adapts - folds) > 0,
            })
        # state observatory: peak total state bytes across the segment's
        # snapshots, and the segment's final top-K hot keys (the
        # dnz_state_hot_key_share gauges a stateful operator refreshes)
        seg_peak = 0.0
        for snap in snaps:
            tot = sum(
                v for k, v in snap.get("metrics", {}).items()
                if k.startswith("dnz_state_bytes")
                and isinstance(v, (int, float))
            )
            if tot > seg_peak:
                seg_peak = tot
        if seg_peak > peak_state:
            peak_state = seg_peak
        # cold-tier + salvage gauges: the segment's FINAL values (both
        # are monotone within a segment's life for salvage; spilled
        # bytes peak tracked like state bytes)
        seg_salvaged = 0.0
        for snap in snaps:
            m = snap.get("metrics", {})
            sp = sum(
                v for k, v in m.items()
                if k.startswith("dnz_state_spilled_bytes")
                and isinstance(v, (int, float))
            )
            if sp > peak_spilled:
                peak_spilled = sp
            sv = sum(
                v for k, v in m.items()
                if k.startswith("dnz_source_salvaged_rows")
                and isinstance(v, (int, float))
            )
            if sv > seg_salvaged:
                seg_salvaged = sv
        salvaged += seg_salvaged
        final_shares = {}
        for snap in snaps:  # last snapshot carrying hot-key series wins
            shares = {
                k: v for k, v in snap.get("metrics", {}).items()
                if k.startswith("dnz_state_hot_key_share") and v
            }
            if shares:
                final_shares = shares
        if final_shares:
            top = sorted(
                final_shares.items(), key=lambda kv: -kv[1]
            )[:8]
            state_hot.append({
                "segment": seg_i,
                "peak_state_bytes": round(seg_peak),
                "top_keys": [
                    {"series": k, "share": round(v, 4)} for k, v in top
                ],
            })
    timeline.sort(key=lambda e: e["t"] or 0)
    adapt_timeline.sort(key=lambda e: e["t"] or 0)
    emit = R.merge_histogram(finals_emit)
    wm = R.merge_histogram(finals_wm)
    tele: dict = {
        "segments_reporting": segs_reporting,
        "snapshots": n_snaps,
        "fault_timeline": timeline,
    }
    if adapt_timeline or adapt_by_seg:
        tele["adaptations"] = {
            "events": adapt_timeline,
            "by_segment": adapt_by_seg,
            "total": sum(s["adapt"] + s["fold"] for s in adapt_by_seg),
        }
    if peak_state:
        tele["peak_state_bytes"] = round(peak_state)
    if peak_spilled:
        tele["peak_spilled_bytes"] = round(peak_spilled)
    # poison records skipped by salvage decode, summed across segments —
    # silent data loss surfaced into the soak report (0 on clean feeds)
    tele["salvaged_rows"] = round(salvaged)
    if state_hot:
        tele["state_hot_keys"] = state_hot
    if emit:
        tele["e2e_event_lag_ms"] = {
            k: round(emit[k], 2) for k in ("p50", "p95", "p99", "max")
            if emit.get(k) is not None
        }
        tele["e2e_event_lag_ms"]["samples"] = emit["count"]
    if wm:
        tele["max_watermark_lag_ms"] = round(wm["max"], 2)
    if anchor_epoch_ms is not None:
        off = anchor_epoch_ms - T0
        tele["feed_anchor_offset_ms"] = round(off, 1)
        if emit:
            tele["e2e_latency_ms"] = {
                k: round(emit[k] - off, 2)
                for k in ("p50", "p95", "p99", "max")
                if emit.get(k) is not None
            }
        if wm:
            tele["max_watermark_lag_anchored_ms"] = round(wm["max"] - off, 2)
    return tele


def read_state_events(paths) -> list[dict]:
    """Every 'state' accounting event across the given files (bigstate
    soak: emission segments + their .state sampler streams)."""
    out = []
    for path in paths:
        try:
            f = open(path)
        except FileNotFoundError:
            continue
        with f:
            for line in f:
                try:
                    o = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if o.get("event") == "state":
                    o["_path"] = str(path)
                    out.append(o)
    return out


def bigstate_main(args) -> None:
    """Larger-than-memory acceptance drive (ROADMAP item 3): one
    unbudgeted reference run over a deterministic feed of
    ``--keys`` simultaneously-open sessions, then the SAME feed under a
    state budget ~5x smaller with the cold tier + checkpointing active,
    SIGKILLed mid-run and restored.  Gates: byte-identical emissions
    across the two runs (and across the kill), resident state bounded by
    the budget, a materially lower RSS ceiling, the spill machinery
    demonstrably exercised, and the armed spill-site fault rules all
    fired + healed."""
    import shutil
    import tempfile

    work = tempfile.mkdtemp(prefix="soak_bs_")
    a_batches = -(-args.keys // args.batch_rows)
    waves = -(-args.keys // args.wave_keys)
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "SOAK_BATCH_ROWS": str(args.batch_rows),
        "SOAK_PACE": str(args.pace),
        "SOAK_TOTAL_BATCHES": str(a_batches + waves),
        "SOAK_PIPELINE": "bigstate",
        "SOAK_BS_KEYS": str(args.keys),
        "SOAK_BS_WAVE": str(args.wave_keys),
        "SOAK_T0": str(T0),
        "SOAK_CKPT_S": str(args.ckpt_s),
    })
    report: dict = {
        "pipeline": "bigstate",
        "keys": args.keys,
        "wave_keys": args.wave_keys,
        "batch_rows": args.batch_rows,
        "kill_every_s": args.kill_every,
        "phaseA_batches": a_batches,
        "close_waves": waves,
    }

    def run_child(out_path, obs_path, ckpt_dir, budget, kill_every,
                  max_kills):
        seg_env = dict(env)
        seg_env["SOAK_BS_BUDGET"] = str(budget)
        seg_env["SOAK_CKPT_DIR"] = ckpt_dir
        if budget and args.chaos_spill:
            seg_env["DENORMALIZED_FAULT_PLAN"] = json.dumps(
                bigstate_fault_plan(args.chaos_seed)
            )
        segs, rss, kills, crashes = [], [], 0, 0
        done = False
        seg = 0
        while not done:
            seg += 1
            seg_out = f"{out_path}.{seg}"
            segs.append(seg_out)
            seg_env["SOAK_OUT"] = seg_out
            seg_env["SOAK_OBS_OUT"] = f"{obs_path}.{seg}"
            t_spawn = time.monotonic()
            proc = subprocess.Popen(
                [sys.executable, os.path.abspath(__file__), "--child"],
                env=seg_env, stdout=sys.stderr, stderr=sys.stderr,
            )
            kill_at = t_spawn + kill_every
            while True:
                rc = proc.poll()
                if rc is not None:
                    if rc == 0:
                        done = True
                    else:
                        crashes += 1
                        if crashes > 5:
                            raise RuntimeError(
                                f"bigstate child crashed {crashes}x "
                                f"(rc={rc})"
                            )
                    break
                if (r := rss_kb(proc.pid)):
                    rss.append(r)
                if (
                    kills < max_kills
                    and time.monotonic() >= kill_at
                ):
                    os.kill(proc.pid, signal.SIGKILL)
                    kills += 1
                    proc.wait(10)
                    break
                time.sleep(0.5)
        return segs, rss, kills, crashes

    try:
        # -- run 1: the unbudgeted oracle --------------------------------
        ckpt_ref = os.path.join(work, "ckpt_ref")
        os.makedirs(ckpt_ref)
        t0 = time.monotonic()
        ref_segs, ref_rss, _, _ = run_child(
            os.path.join(work, "ref.jsonl"),
            os.path.join(work, "ref_obs.jsonl"),
            ckpt_ref, budget=0, kill_every=float("inf"), max_kills=0,
        )
        ref_wall = time.monotonic() - t0
        wins_ref, ref_dupes, ref_done, _m, _c = read_emissions(ref_segs)
        ref_states = read_state_events(
            ref_segs + [p + ".state" for p in ref_segs]
        )
        working_set = max(
            (s.get("bytes") or 0 for s in ref_states), default=0
        )
        budget = args.state_budget or max(working_set // 5, 1_000_000)
        report.update({
            "reference": {
                "wall_s": round(ref_wall, 1),
                "sessions": len(wins_ref),
                "duplicate_emissions": ref_dupes,
                "rss_kb_max": max(ref_rss) if ref_rss else None,
                "working_set_bytes": working_set,
            },
            "budget_bytes": budget,
            "budget_ratio": (
                round(working_set / budget, 2) if budget else None
            ),
        })
        # -- run 2: budgeted + kills -------------------------------------
        ckpt_b = os.path.join(work, "ckpt_b")
        os.makedirs(ckpt_b)
        t0 = time.monotonic()
        b_segs, b_rss, kills, crashes = run_child(
            os.path.join(work, "bud.jsonl"),
            os.path.join(work, "bud_obs.jsonl"),
            ckpt_b, budget=budget, kill_every=args.kill_every,
            max_kills=args.max_kills,
        )
        b_wall = time.monotonic() - t0
        wins_b, dupes, done_seen, _m2, clipped = read_emissions(b_segs)
        b_states = read_state_events(
            b_segs + [p + ".state" for p in b_segs]
        )
        resident_max = max(
            (s.get("bytes") or 0 for s in b_states), default=0
        )
        evictable_max = max(
            (s.get("evictable") or 0 for s in b_states), default=0
        )
        # counters reset with each respawned incarnation: sum each
        # segment's LAST spill snapshot for the run totals
        last_per_seg: dict = {}
        for s in b_states:
            if s.get("spill"):
                last_per_seg[s["_path"]] = s["spill"]
        spill_final: dict = {}
        for sp in last_per_seg.values():
            for k, v in sp.items():
                if isinstance(v, (int, float)):
                    spill_final[k] = spill_final.get(k, 0) + v
        chaos_events = read_chaos_events(b_segs)
        fired: dict = {}
        for ev in chaos_events:
            for e in ev.get("fault_log", []):
                name = e.get("name", f"rule{e.get('rule')}")
                fired[name] = fired.get(name, 0) + 1
        # -- drift: EVERY budgeted occurrence must equal the oracle's ----
        lost, spurious, mismatched = [], [], 0
        for k, occs in wins_ref.items():
            want = occs[0][0]
            got = wins_b.get(k)
            if not got:
                lost.append(k)
                continue
            for vals, _seg in got:
                if vals != want:
                    mismatched += 1
        for k in wins_b:
            if k not in wins_ref:
                spurious.append(k)
        expected_sessions = args.keys + waves * 64
        spill_blocks = (
            (spill_final or {}).get("spill_blocks_total", 0)
        )
        required_fired = (
            sorted(r for r in BIGSTATE_REQUIRED_RULES if r in fired)
            if args.chaos_spill else []
        )
        rss_ratio = (
            round(max(b_rss) / max(ref_rss), 3)
            if b_rss and ref_rss else None
        )
        # the RSS gate is relative to the WORKING SET, not a bare
        # ratio: both runs keep the interner key index resident (the
        # documented membership-filter floor, ~2.8GB at 10M int keys),
        # so the budgeted run must shed at least 35% of the evictable
        # working set from RAM — a gate that scales with the workload
        # instead of hardcoding the index share
        rss_saved_bytes = (
            (max(ref_rss) - max(b_rss)) * 1024
            if b_rss and ref_rss else None
        )
        rss_flat_ok = (
            rss_saved_bytes is not None
            and rss_saved_bytes >= 0.35 * working_set
            and (rss_ratio is None or rss_ratio <= 0.9)
        )
        report.update({
            "budgeted": {
                "wall_s": round(b_wall, 1),
                "segments": len(b_segs),
                "kills": kills,
                "crash_restarts": crashes,
                "sessions": len(wins_b),
                "duplicate_emissions": dupes,
                "uncommitted_clipped": clipped,
                "rss_kb_max": max(b_rss) if b_rss else None,
                "resident_state_bytes_max": resident_max,
                "evictable_state_bytes_max": evictable_max,
                "spill": spill_final,
            },
            "chaos_spill": {
                "armed": bool(args.chaos_spill),
                "fired_rules": fired,
                "required_rules_fired": required_fired,
            },
            "sessions_expected": expected_sessions,
            "sessions_lost": len(lost),
            "sessions_spurious": len(spurious),
            "sessions_mismatched": mismatched,
            "rss_budgeted_over_reference": rss_ratio,
            "rss_saved_mb": (
                round(rss_saved_bytes / 2**20) if rss_saved_bytes else None
            ),
            "ok": (
                ref_done and done_seen
                and len(wins_ref) == expected_sessions
                and not lost and not spurious and not mismatched
                and kills >= 1
                and spill_blocks > 0
                # EVICTABLE resident state stays bounded by the budget
                # (25% slack covers estimate-vs-exact gap + the
                # protected current batch); the interned-key index is
                # the documented un-evictable resident floor, reported
                # via resident_state_bytes_max (docs/state_spill.md)
                and evictable_max <= budget * 1.25
                and rss_flat_ok
                and (
                    not args.chaos_spill
                    or len(required_fired) == len(BIGSTATE_REQUIRED_RULES)
                )
            ),
        })
        Path(args.out).write_text(json.dumps(report, indent=1))
        print(json.dumps({
            "ok": report["ok"],
            "sessions": len(wins_b),
            "kills": kills,
            "spill_blocks": spill_blocks,
            "rss_ratio": rss_ratio,
            "budget_ratio": report.get("budget_ratio"),
        }))
    finally:
        shutil.rmtree(work, ignore_errors=True)


def rss_kb(pid: int) -> int | None:
    try:
        with open(f"/proc/{pid}/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1])
    except OSError:
        return None
    return None


def _cluster_cell(args, partial: bool) -> dict:
    """Run one cluster soak cell and return its report dict.

    Both cells stream the same paced job over N worker processes with a
    SIGKILLed worker mid-stream plus one injected torn exchange frame,
    and hold the surviving output to EXACTLY-ONCE vs the uninterrupted
    single-process oracle (0 lost / 0 spurious / 0 duplicates).  They
    differ in the recovery contract under test:

    - ``full_restart`` (partial=False): fail-stop fallback — any death
      or tear restarts the WHOLE cluster from the last committed epoch
      (gate: restarts >= 2, one per injected failure).
    - ``partial`` (partial=True): single-worker recovery — the tear is
      keyed to the killed worker's outbound edges, so both failures are
      attributed to that one worker and only IT respawns; survivors
      must never restart (``max_restarts=0`` turns any full restart
      into a hard error), only the dead worker's slot may grow partial
      segments, and the coordinator's recovery-duration histogram
      (``dnz_cluster_recovery_ms``) lands in the report."""
    import shutil
    import tempfile
    from collections import Counter

    from denormalized_tpu import obs
    from denormalized_tpu.cluster import ClusterSpec, run_cluster
    from denormalized_tpu.cluster import benchjob
    from denormalized_tpu.cluster.reader import read_cluster

    n_workers = args.cluster_workers
    partitions = args.cluster_partitions
    # stream sized from --minutes at a paced, checkpoint-friendly rate
    batches = max(20, int(args.minutes * 60 / 0.05 / 2))
    job_args = {
        "partitions": partitions,
        "batches": batches,
        "rows": min(args.batch_rows, 1024),
        "keys": 97,
        "batch_span_ms": 250,
        "window_ms": 1000,
        "pace_s": 0.05,
    }
    per_worker_wall = (partitions / n_workers) * batches * 0.05
    t_start = time.time()
    mode = "partial" if partial else "full_restart"
    print(f"cluster soak [{mode}]: {n_workers} workers, {partitions} "
          f"partitions, {batches} batches/partition "
          f"(~{per_worker_wall:.0f}s of stream per worker)",
          file=sys.stderr)
    oracle = benchjob.oracle_rows(job_args, string_keys=True)
    work = tempfile.mkdtemp(prefix="soak_cluster_")
    victim = n_workers - 1
    # one torn exchange frame mid-stream, detected by the receiver's
    # CRC/length check.  full_restart tears worker 0's edge (both ends
    # fail, coordinator restarts the cluster); partial tears the
    # VICTIM's outbound edge so the failure is attributed to the same
    # worker the SIGKILL targets — two partial recoveries of one
    # worker, peers never stop
    fault_plan = {
        "seed": args.chaos_seed,
        "rules": [{
            "site": "exchange.send", "kind": "torn",
            "key_substr": f"{victim}->" if partial else "0->",
            # partial recovery pins the respawn to the last CLUSTER
            # commit, so the partial cell's tear waits until the first
            # 1s-interval barrier has provably committed
            "after": 150 if partial else 40, "times": 1,
            "name": "torn-exchange-frame",
        }],
    }
    spec = ClusterSpec(
        workdir=work,
        n_workers=n_workers,
        job="denormalized_tpu.cluster.benchjob:soak_job",
        job_args=job_args,
        checkpoint_interval_s=1.0,
        sink="jsonl",
        # partial: ANY full-cluster restart is a hard failure — the
        # survivors-keep-streaming contract is the point of the cell
        max_restarts=0 if partial else 4,
        liveness_timeout_s=300.0,
        metrics_jsonl=True,
        fault_plan=fault_plan,
        partial_recovery=partial,
    )
    kill_at = min(args.kill_every, per_worker_wall * 0.4)
    result = run_cluster(
        spec,
        kill_worker_after_s=kill_at,
        kill_worker_id=victim,
    )
    got = read_cluster(result["segments"])
    rows = [benchjob.canonical_row(r) for r in got["rows"]]
    counts = Counter(rows)
    dupes = sum(c - 1 for c in counts.values() if c > 1)
    want = Counter(oracle)
    lost = sum((want - counts).values())
    spurious = sum((counts - want).values()) - dupes
    # fault evidence: the torn frame fired in generation 0 (its obs
    # stream carries the dnz_fault_injections_total counter) and cost
    # at least one restart/recovery beyond the SIGKILL's
    merged = _obs_readers().merge_final_snapshots(
        sorted(
            os.path.join(work, "obs", f)
            for f in os.listdir(os.path.join(work, "obs"))
        )
    ) if os.path.isdir(os.path.join(work, "obs")) else {"series": {}}
    fault_fired = sum(
        v for k, v in merged["series"].items()
        if k.startswith("dnz_fault_injections_total")
        and "exchange" in k and isinstance(v, (int, float))
    )
    # a tear can kill the worker before the next JSONL export cycle:
    # the coordinator's crash log is the durable secondary evidence
    torn_crashes = sum(
        1 for why in result.get("crashes", [])
        if "torn" in (why or "")
    )
    fault_fired = max(int(fault_fired), torn_crashes)
    report = {
        "mode": mode,
        "workers": n_workers,
        "partitions": partitions,
        "total_rows": partitions * batches * job_args["rows"],
        "oracle_windows": len(oracle),
        "emitted_windows_kept": len(rows),
        "clipped_uncommitted": got["clipped"],
        "lost": lost,
        "spurious": spurious,
        "duplicate_emissions": dupes,
        "sigkills": result.get("killed_workers", 0),
        "exchange_faults_fired": int(fault_fired),
        "restarts": result["restarts"],
        "commits": result["commits"],
        "status": result["status"],
        "wall_s": round(time.time() - t_start, 1),
        "host_cores": os.cpu_count(),
    }
    if partial:
        partials = [s for s in result["segments"] if s.get("partial")]
        # the coordinator runs in THIS process: its recovery-duration
        # histogram is read straight off the live obs registry
        hist = {
            k: v for k, v in obs.registry().snapshot().items()
            if k.startswith("dnz_cluster_recovery_ms")
        }
        report.update({
            "worker_restarts": result["worker_restarts"],
            "aborted_epochs": result["aborted_epochs"],
            "recoveries": result["recoveries"],
            "recovery_ms_histogram": hist,
            "crashes": result.get("crashes", []),
            "partial_segment_workers": sorted(
                {s["worker"] for s in partials}
            ),
        })
        report["pass"] = bool(
            result["status"] == "done"
            and lost == 0 and spurious == 0 and dupes == 0
            and result.get("killed_workers", 0) >= 1
            and fault_fired >= 1
            # survivors never restarted; only the victim replayed
            and result["restarts"] == 0
            and result["worker_restarts"] >= 1
            and partials
            and all(s["worker"] == victim for s in partials)
            and all(s["restored"] >= 1 for s in partials)
            and any(r["worker"] == victim and r["ms"] > 0
                    for r in result["recoveries"])
        )
    else:
        report["pass"] = bool(
            result["status"] == "done"
            and lost == 0 and spurious == 0 and dupes == 0
            and result.get("killed_workers", 0) >= 1
            and fault_fired >= 1
            and result["restarts"] >= 2
        )
    shutil.rmtree(work, ignore_errors=True)
    return report


def cluster_main(args) -> None:
    """Multi-process cluster soak (see ``_cluster_cell``): the
    full-restart fallback cell and the partial-recovery cell, one
    report with both.  ``--partial`` runs only the partial cell (quick
    iteration on the single-worker recovery path).

    Unlike the single-process soaks this parent imports the engine (the
    oracle runs in-process); the workers are real spawned processes."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    modes = [True] if args.partial else [False, True]
    cells = {}
    for partial in modes:
        cell = _cluster_cell(args, partial)
        cells[cell["mode"]] = cell
    report = {
        "pipeline": "cluster",
        "cells": cells,
        "pass": all(c["pass"] for c in cells.values()),
    }
    with open(args.out, "w") as f:
        json.dump(report, f, indent=2)
    print(json.dumps(report, indent=2))
    if not report["pass"]:
        sys.exit(1)


def main():
    global T0
    ap = argparse.ArgumentParser()
    ap.add_argument("--child", action="store_true")
    ap.add_argument("--minutes", type=float, default=12.0)
    ap.add_argument("--pace", type=float, default=200_000.0)
    ap.add_argument("--batch-rows", type=int, default=4096)
    ap.add_argument("--kill-every", type=float, default=90.0)
    ap.add_argument("--pipeline",
                    choices=("simple", "sliding", "join", "session",
                             "udaf", "approx", "kafka", "bigstate",
                             "cluster", "query_dense", "join_dense"),
                    default="simple")
    ap.add_argument("--cluster-workers", type=int, default=3,
                    help="cluster: engine worker processes")
    ap.add_argument("--cluster-partitions", type=int, default=6,
                    help="cluster: source partitions (static assignment)")
    ap.add_argument("--partial", action="store_true",
                    help="cluster: run ONLY the partial-recovery cell "
                    "(single-worker replay while peers keep streaming); "
                    "default runs the full-restart fallback cell AND "
                    "the partial cell")
    ap.add_argument("--keys", type=int, default=10_000_000,
                    help="bigstate: simultaneously-open sessions")
    ap.add_argument("--wave-keys", type=int, default=100_000,
                    help="bigstate: sessions closed per watermark wave")
    ap.add_argument("--state-budget", type=int, default=0,
                    help="bigstate: budget bytes (0 = working set / 5)")
    ap.add_argument("--ckpt-s", type=float, default=20.0,
                    help="bigstate: checkpoint interval")
    ap.add_argument("--max-kills", type=int, default=2,
                    help="bigstate: SIGKILLs issued mid-run")
    ap.add_argument("--chaos-spill", action="store_true", default=True,
                    help="bigstate: arm the spill-site fault plan "
                    "(transient reload flap, eviction-write failure, "
                    "torn manifest; default on)")
    ap.add_argument("--no-chaos-spill", dest="chaos_spill",
                    action="store_false")
    ap.add_argument("--chaos", action="store_true",
                    help="arm the seeded FaultPlan (broker flaps, worker "
                    "crashes, torn state writes, commit hiccups) on top "
                    "of the kafka exactly-once soak; implies "
                    "--pipeline kafka")
    ap.add_argument("--chaos-seed", type=int, default=1234)
    ap.add_argument("--out", default=None, help="default derives from "
                    "--pipeline: SOAK.json / SOAK_SLIDING.json / "
                    "SOAK_JOIN.json / SOAK_SESSION.json / SOAK_UDAF.json "
                    "/ SOAK_APPROX.json / SOAK_CHAOS.json (never "
                    "cross-clobbers artifacts)")
    args = ap.parse_args()
    if args.chaos:
        if args.pipeline not in ("simple", "kafka"):
            ap.error("--chaos runs on the kafka pipeline only")
        args.pipeline = "kafka"
    if args.out is None:
        args.out = str(REPO / (
            "SOAK_CHAOS.json" if args.chaos else {
                "simple": "SOAK.json",
                "join": "SOAK_JOIN.json",
                "session": "SOAK_SESSION.json",
                "udaf": "SOAK_UDAF.json",
                "approx": "SOAK_APPROX.json",
                "sliding": "SOAK_SLIDING.json",
                "kafka": "SOAK_KAFKA.json",
                "bigstate": "SOAK_BIGSTATE.json",
                "cluster": "SOAK_CLUSTER.json",
                "query_dense": "SOAK_QUERY_DENSE.json",
                "join_dense": "SOAK_JOIN_DENSE.json",
            }[args.pipeline]
        ))
    if args.child:
        child_main()
        return
    if args.pipeline == "bigstate":
        bigstate_main(args)
        return
    if args.pipeline == "cluster":
        cluster_main(args)
        return

    import shutil
    import tempfile

    total_batches = int(args.minutes * 60 * args.pace / args.batch_rows)
    work = tempfile.mkdtemp(prefix="soak_")
    ckpt_dir = os.path.join(work, "ckpt")
    os.makedirs(ckpt_dir)
    if "SOAK_T0" not in os.environ:
        # anchor event time near wall-now, rounded to a window boundary
        # (see the T0 comment above; the kafka feed re-anchors once its
        # staging estimate is known)
        T0 = int(time.time()) * 1000 // WINDOW_MS * WINDOW_MS
    kafka_broker = None
    kafka_last_close_ws = None
    kafka_feed_anchor: dict = {}
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "SOAK_BATCH_ROWS": str(args.batch_rows),
        "SOAK_PACE": str(args.pace),
        "SOAK_TOTAL_BATCHES": str(total_batches),
        "SOAK_CKPT_DIR": ckpt_dir,
        "SOAK_PIPELINE": args.pipeline,
    })
    chaos_spec = None
    chaos_deterministic = None
    if args.chaos:
        chaos_spec = chaos_plan(args.chaos_seed)
        # determinism proof: the same seed must reproduce the same
        # injection sequence — two fresh plans driven through the same
        # synthetic call sequence must log identical decisions
        seq_a = chaos_sim_sequence(chaos_spec)
        seq_b = chaos_sim_sequence(chaos_spec)
        chaos_deterministic = bool(seq_a and seq_a == seq_b)
        chaos_sim_count = len(seq_a)
        env["DENORMALIZED_FAULT_PLAN"] = json.dumps(chaos_spec)
        # pure-Python LSM engine: its replay accounting (replay_truncated)
        # is part of what the chaos run asserts on
        env["DENORMALIZED_LSM_PY"] = "1"
    if args.pipeline == "kafka":
        kafka_broker, _feed_th, kafka_last_close_ws, kafka_feed_anchor = (
            kafka_prep_and_feed(
                args, total_batches, lambda m: print(m, file=sys.stderr)
            )
        )
        env["SOAK_BOOTSTRAP"] = kafka_broker.bootstrap
        env["SOAK_LAST_CLOSE_WS"] = str(kafka_last_close_ws)
    # AFTER the kafka branch: the feed's staging calibration re-anchors
    # T0, and every child must see the final value (window keys are
    # absolute — parent golden and child emissions must agree)
    env["SOAK_T0"] = str(T0)

    report = {
        "pipeline": args.pipeline,
        "minutes": args.minutes,
        "pace_rows_per_s": args.pace,
        "total_rows": total_batches * args.batch_rows,
        "kill_every_s": args.kill_every,
        "segments": [],
    }
    if args.chaos:
        report["chaos"] = {
            "seed": args.chaos_seed,
            "plan": chaos_spec,
            "fault_plan_deterministic": chaos_deterministic,
            "sim_injections": chaos_sim_count,
        }

    def write(extra=None):
        report.update(extra or {})
        Path(args.out).write_text(json.dumps(report, indent=1))

    golden: dict = {}
    _fold = {
        "join": lambda agg, i, br, pc: golden_update_join(
            agg, i, br, pc, total_batches
        ),
        "session": golden_update_session,
        "sliding": golden_update_sliding,
        "approx": golden_update_approx,
        # query_dense/join_dense verify against per-query ORACLE RUNS
        # (qd_verify) after the drive loop, not an incremental golden
        # fold — the loop still advances golden_i to track feed
        # exhaustion
        "query_dense": lambda agg, i, br, pc: None,
        "join_dense": lambda agg, i, br, pc: None,
    }.get(args.pipeline, golden_update)  # udaf golden == tumbling fold
    golden_i = 0
    seg_paths = []
    obs_paths = []
    seg = 0
    kills_issued = 0
    t_start = time.monotonic()
    aborted = None
    recovery_times = []
    done = False
    proc = None
    try:
        while not done:
            seg += 1
            out_path = os.path.join(work, f"emit_{seg}.jsonl")
            seg_paths.append(out_path)
            obs_path = os.path.join(work, f"obs_{seg}.jsonl")
            obs_paths.append(obs_path)
            seg_env = dict(env)
            seg_env["SOAK_OUT"] = out_path
            seg_env["SOAK_OBS_OUT"] = obs_path
            t_spawn = time.monotonic()
            proc = subprocess.Popen(
                [sys.executable, os.path.abspath(__file__), "--child"],
                env=seg_env, stdout=sys.stderr, stderr=sys.stderr,
            )
            # first-emission latency after spawn = recovery time (seg > 1)
            first_emit = None
            seg_rss = []  # sampled only AFTER first emission: a pre-exec
            # or mid-import sample (~4KB) says nothing about the engine
            kill_at = t_spawn + args.kill_every
            while True:
                rc = proc.poll()
                if rc is not None:
                    if rc != 0:
                        aborted = f"segment {seg} child rc={rc}"
                    done = True
                    break
                now = time.monotonic()
                if first_emit is not None and (r := rss_kb(proc.pid)):
                    seg_rss.append(r)
                if first_emit is None:
                    wins, _, _, _, _ = read_emissions([out_path])
                    if wins:
                        first_emit = now - t_spawn
                        if seg > 1:
                            recovery_times.append(round(first_emit, 2))
                # fold golden forward while the child streams (parent is
                # otherwise idle); stay ahead of the feed
                target_i = min(
                    total_batches,
                    int((now - t_start) * args.pace / args.batch_rows)
                    + 200,
                )
                while golden_i < target_i:
                    _fold(golden, golden_i, args.batch_rows, args.pace)
                    golden_i += 1
                if relay_active():
                    aborted = "relay active (yielding core to chip run)"
                    proc.kill()
                    proc.wait(10)
                    done = True
                    break
                if now >= kill_at:
                    # never kill the final drain: once the feed's event
                    # time is exhausted, let the segment run to EOS
                    if golden_i >= total_batches:
                        kill_at = float("inf")
                        time.sleep(0.5)
                        continue
                    os.kill(proc.pid, signal.SIGKILL)
                    kills_issued += 1
                    proc.wait(10)
                    break
                time.sleep(0.5)
            report["segments"].append({
                "segment": seg,
                "wall_s": round(time.monotonic() - t_spawn, 1),
                "rss_kb_start": seg_rss[0] if seg_rss else None,
                "rss_kb_max": max(seg_rss) if seg_rss else None,
                "rss_kb_end": seg_rss[-1] if seg_rss else None,
                "first_emit_s": (
                    round(first_emit, 2) if first_emit else None
                ),
            })
            write()
            if aborted:
                break
        # finish golden
        while golden_i < total_batches and not aborted:
            _fold(golden, golden_i, args.batch_rows, args.pace)
            golden_i += 1
        wins, dupes, done_seen, child_metrics, clipped = read_emissions(
            seg_paths
        )
        if args.pipeline in ("query_dense", "join_dense"):
            dense_join = args.pipeline == "join_dense"
            qd = (
                None if aborted
                else qd_verify(
                    args, env, work, wins, seg_paths, total_batches,
                    sched_fn=jd_schedule if dense_join else qd_schedule,
                    oracle_pipeline=(
                        "join_dense_oracle" if dense_join
                        else "query_dense_oracle"
                    ),
                )
            )
            try:
                telemetry = derive_telemetry(obs_paths)
            except Exception as e:  # dnzlint: allow(broad-except) telemetry derivation is reporting, not verification
                telemetry = {"error": str(e)}
            # join_dense runs a 10-query plane (the join oracles replay
            # the full feed per query), so its warm-backfill floor scales
            # down with it
            min_backfilled = 3 if dense_join else 10
            ok = bool(
                not aborted and done_seen and kills_issued >= 2
                and qd is not None
                and qd["oracle_rc"] == 0 and qd["oracle_windows"] > 0
                and qd["failures"] == 0 and not qd["queries_silent"]
                and not qd["backfill_missing"]
                and qd["backfilled_joiners"] >= min_backfilled
                and qd["max_builds_per_segment"] == 1
            )
            write({
                "aborted": aborted,
                "telemetry": telemetry,
                "eos_done_seen": done_seen,
                "kills": kills_issued,
                "recovery_first_emit_s": recovery_times,
                "emitted_rows": sum(len(v) for v in wins.values()),
                "duplicate_emissions": dupes,
                "uncommitted_clipped": clipped,
                "child_metrics": child_metrics,
                args.pipeline: qd,
                "ok": ok,
            })
            print(json.dumps({
                "ok": ok,
                "kills": kills_issued,
                "queries": qd and qd["queries"],
                "joined_live": qd and qd["joined_live"],
                "departed": qd and qd["departed"],
                "backfilled": qd and qd["backfilled_joiners"],
                "failures": qd and qd["failures"],
                "aborted": aborted,
            }))
            return
        if args.pipeline == "kafka" and not aborted:
            # the unbounded source ends at last_close_ws by design: windows
            # past it may or may not close (idle-hint timing) before the
            # child exits — clip BOTH sides to the deterministic range
            golden = {
                k: g for k, g in golden.items()
                if k[0] <= kafka_last_close_ws
            }
            wins = {
                k: v for k, v in wins.items()
                if k[0] <= kafka_last_close_ws
            }
        if args.pipeline == "session" and not aborted:
            # golden keys are (burst second, key); emissions key on the
            # session START (min ts in the burst) — remap for comparison
            golden = {
                (int(g[4]), k[1]): g for k, g in golden.items()
            }
        lost = []
        spurious = []
        mismatched = []
        if not aborted:
            for k, g in golden.items():
                occs = wins.get(k)
                if not occs:
                    lost.append(k)
                    continue
                if args.pipeline == "join":
                    cnt, sm = g
                    # exactly-one dim match per fact row: count and avg
                    # come from the fact fold, avg_h is the window's
                    # (constant) deterministic dim value
                    want = (
                        cnt,
                        round(sm / cnt, 4),
                        dim_value(
                            int(k[1].rsplit("_", 1)[1]),
                            k[0] // 1000 - T0 // 1000,
                        ),
                    )
                elif args.pipeline == "session":
                    cnt, mn, mx, sm, t0, t1 = g
                    want = (cnt, round(mn, 4), round(mx, 4),
                            round(sm / cnt, 4), t0, t1 + SESSION_GAP_MS)
                elif args.pipeline == "udaf":
                    cnt, mn, mx, _sm = g
                    want = (cnt, round(mx - mn, 4))
                elif args.pipeline == "approx":
                    # EXACT integer equality: the golden's plane was
                    # folded with the engine's own kernels, and HLL
                    # max-merge is split-invariant — any deviation is
                    # a real sketch restore/fold bug, not "noise"
                    cnt, plane = g
                    want = (cnt, int(_sk().hll_estimate(plane)[0]))
                else:
                    cnt, mn, mx, sm = g
                    want = (cnt, round(mn, 4), round(mx, 4),
                            round(sm / cnt, 4))
                for got, seg_idx in occs:  # EVERY occurrence, dupes too
                    if len(got) != len(want) or any(
                        abs(a - b) > 1e-3 for a, b in zip(got, want)
                    ):
                        mismatched.append((k, got, want,
                                           {"segment": seg_idx}))
            # spurious: emitted keys the golden never produced (corrupted
            # ws/key after a restore would land here)
            spurious = [k for k in wins if k not in golden]
        chaos_report = {}
        if args.chaos:
            chaos_events = read_chaos_events(seg_paths)
            fired_rules: dict = {}
            fired_sites: dict = {}
            for ev in chaos_events:
                for e in ev.get("fault_log", []):
                    name = e.get("name", f"rule{e.get('rule')}")
                    fired_rules[name] = fired_rules.get(name, 0) + 1
                    fired_sites[e["site"]] = fired_sites.get(e["site"], 0) + 1
            chaos_report = {
                "segments_reporting": len(chaos_events),
                "injections_fired": sum(fired_rules.values()),
                "fired_rules": fired_rules,
                "fired_sites": fired_sites,
                "required_rules_fired": sorted(
                    r for r in CHAOS_REQUIRED_RULES if r in fired_rules
                ),
                "commit_retries": sum(
                    ev.get("commit_retries", 0) for ev in chaos_events
                ),
                "fallback_restores": sum(
                    1 for ev in chaos_events
                    if ev.get("restored_from_fallback")
                ),
                "replay_truncated": sum(
                    ev.get("replay_truncated", 0) for ev in chaos_events
                ),
                "prefetch_restarts": sum(
                    ev.get("prefetch_restarts", 0) for ev in chaos_events
                ),
            }
            report["chaos"].update(chaos_report)
        try:
            telemetry = derive_telemetry(
                obs_paths,
                anchor_epoch_ms=(
                    kafka_feed_anchor["epoch"] * 1000.0
                    if kafka_feed_anchor.get("epoch") else None
                ),
            )
        except Exception as e:  # dnzlint: allow(broad-except) telemetry derivation is reporting, not verification — a malformed snapshot stream must not turn a green soak red
            telemetry = {"error": str(e)}
        write({
            "aborted": aborted,
            "telemetry": telemetry,
            "eos_done_seen": done_seen,
            "kills": kills_issued,
            "recovery_first_emit_s": recovery_times,
            "golden_windows": len(golden),
            "emitted_windows": len(wins),
            "duplicate_emissions": dupes,
            "uncommitted_clipped": clipped,
            "child_metrics": child_metrics,
            "windows_lost": len(lost),
            "windows_spurious": len(spurious),
            "windows_mismatched": len(mismatched),
            "mismatch_sample": mismatched[:3],
            "spurious_sample": spurious[:3],
            "ok": (
                not aborted and done_seen and not lost and not spurious
                and not mismatched and len(wins) == len(golden) > 0
                and (
                    not args.chaos
                    or (
                        chaos_deterministic
                        and len(chaos_report.get(
                            "required_rules_fired", []
                        )) == len(CHAOS_REQUIRED_RULES)
                    )
                )
            ),
        })
        print(json.dumps({
            "ok": report.get("ok"),
            "kills": report.get("kills"),
            "windows": len(wins),
            "lost": len(lost),
            "dupes": dupes,
            "aborted": aborted,
            **({"chaos_rules": chaos_report.get("fired_rules"),
                "fallbacks": chaos_report.get("fallback_restores")}
               if args.chaos else {}),
        }))
    finally:
        if proc is not None and proc.poll() is None:
            proc.kill()
        if kafka_broker is not None:
            kafka_broker.stop()
        shutil.rmtree(work, ignore_errors=True)


if __name__ == "__main__":
    main()
