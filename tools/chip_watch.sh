#!/bin/bash
# Round-5 chip-evidence runner: wait for the axon tunnel relay to open,
# then run the A/B harness over the BASELINE configs, retrying through
# tunnel drops (chip_ab exits 4 on a dead tunnel, 3 on a hung cell; both
# are resumable — the report is rewritten after every cell).
#
#   setsid nohup tools/chip_watch.sh > /tmp/chip_watch.log 2>&1 &
#
# Round-5 hardening (r4's one ~60s relay window died in backend init with
# zero cells banked):
#   - chip_ab now runs a QUICK tier first: 300K-row partial_merge cells
#     for all five configs, no latency phase — first evidence in seconds
#     past compile;
#   - bench.init_backend banks CHIP_CLAIM.jsonl the instant the claim
#     succeeds, before any compile, and enables the persistent XLA
#     compilation cache (.jax_cache/) so a retry after a flap skips
#     recompilation entirely.
#
# The driver-bench's stale-holder sweep may SIGKILL this process at
# end-of-round; AB_REPORT_r5.json keeps every completed cell either way.
cd "$(dirname "$0")/.." || exit 1

OUT=AB_REPORT_r5.json

relay_open() {
    for p in 8082 8083 8087 8092 8093 8097; do
        if (exec 3<>"/dev/tcp/127.0.0.1/$p") 2>/dev/null; then
            exec 3>&- 2>/dev/null
            return 0
        fi
    done
    return 1
}

echo "$(date -u +%H:%M:%S) chip_watch: waiting for relay"
until relay_open; do sleep 15; done
echo "$(date -u +%H:%M:%S) chip_watch: relay OPEN"

# Two phases, both driven through the same retry loop so a tunnel flap
# during either is resumed, not dropped:
#   main   — quick tier (automatic), then partial_merge full cells, then
#            scatter, then host_pipeline/finals variants;
#   pallas — pallas_dense decision cells (VERDICT r4 #8): its plausible
#            win regime is emission-heavy sliding windows at low
#            cardinality — one A/B on the chip decides keep-vs-demote.
# Attempts are consumed only by runs that got past backend init (rc=4 =
# init-time tunnel drop: ran zero cells, costs seconds — re-wait instead,
# so a flapping relay cannot exhaust the budget before any work happens).
run_phase() {
    # 2>&1: bench's log() writes diagnostics (relay waits, backend-init
    # progress, watchdog state) to stderr — r5's first launch had fd2 on
    # /dev/null and the wait loop was invisible.  Keep it in the log.
    case "$1" in
    main)
        python tools/chip_ab.py \
            --out "$OUT" --resume --finals-ab --host-pipeline \
            --strategies partial_merge,scatter \
            --cell-timeout 1800 2>&1
        ;;
    pallas)
        python tools/chip_ab.py \
            --out "$OUT" --resume --no-quick \
            --configs sliding,simple --strategies pallas_dense \
            --cell-timeout 1800 2>&1
        ;;
    esac
}

phase=main
attempt=0
while [ "$attempt" -lt 6 ]; do
    echo "$(date -u +%H:%M:%S) chip_watch: run $phase (attempt $attempt/6)"
    run_phase "$phase"
    rc=$?
    echo "$(date -u +%H:%M:%S) chip_watch: chip_ab[$phase] rc=$rc"
    if [ "$rc" -eq 0 ]; then
        if [ "$phase" = main ]; then
            echo "$(date -u +%H:%M:%S) chip_watch: main matrix DONE"
            phase=pallas
            continue
        fi
        echo "$(date -u +%H:%M:%S) chip_watch: DONE"
        exit 0
    fi
    if [ "$rc" -eq 4 ]; then
        echo "$(date -u +%H:%M:%S) chip_watch: tunnel dead at init; re-waiting"
        sleep 30
        until relay_open; do sleep 15; done
    else
        # rc=3 (hung cell) / rc=5 (failed cells): resumable — retry
        attempt=$((attempt + 1))
        sleep 10
    fi
done
echo "$(date -u +%H:%M:%S) chip_watch: attempts exhausted"
exit 1
