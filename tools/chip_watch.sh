#!/bin/bash
# Round-4 chip-evidence runner: wait for the axon tunnel relay to open,
# then run the A/B harness over the BASELINE configs, retrying through
# tunnel drops (chip_ab exits 4 on a dead tunnel, 3 on a hung cell; both
# are resumable — the report is rewritten after every cell).
#
#   setsid nohup tools/chip_watch.sh > /tmp/chip_watch.log 2>&1 &
#
# The driver-bench's stale-holder sweep may SIGKILL this process at
# end-of-round; AB_REPORT_r4.json keeps every completed cell either way.
cd "$(dirname "$0")/.." || exit 1

relay_open() {
    for p in 8082 8083 8087 8092 8093 8097; do
        if (exec 3<>"/dev/tcp/127.0.0.1/$p") 2>/dev/null; then
            exec 3>&- 2>/dev/null
            return 0
        fi
    done
    return 1
}

echo "$(date -u +%H:%M:%S) chip_watch: waiting for relay"
until relay_open; do sleep 15; done
echo "$(date -u +%H:%M:%S) chip_watch: relay OPEN"

# attempts are consumed only by runs that got past backend init (rc=4 =
# init-time tunnel drop: ran zero cells, costs seconds — re-wait instead,
# so a flapping relay cannot exhaust the budget before any work happens)
attempt=0
while [ "$attempt" -lt 6 ]; do
    echo "$(date -u +%H:%M:%S) chip_watch: run (attempt $attempt/6)"
    # partial_merge first: it is the headline (auto-selected) strategy —
    # if the tunnel flaps mid-matrix the report still has the cells that
    # matter most
    python tools/chip_ab.py \
        --out AB_REPORT_r4.json --resume --finals-ab --host-pipeline \
        --strategies partial_merge,scatter \
        --cell-timeout 1800
    rc=$?
    echo "$(date -u +%H:%M:%S) chip_watch: chip_ab rc=$rc"
    if [ "$rc" -eq 0 ]; then
        echo "$(date -u +%H:%M:%S) chip_watch: DONE"
        exit 0
    fi
    if [ "$rc" -eq 4 ]; then
        echo "$(date -u +%H:%M:%S) chip_watch: tunnel dead at init; re-waiting"
        sleep 30
        until relay_open; do sleep 15; done
    else
        # rc=3 (hung cell) / rc=5 (failed cells): resumable — retry
        attempt=$((attempt + 1))
        sleep 10
    fi
done
echo "$(date -u +%H:%M:%S) chip_watch: attempts exhausted"
exit 1
