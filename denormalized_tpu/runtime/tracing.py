"""Lightweight tracing/metrics.

Observability mirror of the reference: `tracing`/`tracing-subscriber` span
events wired in the rideshare example (kafka_rideshare.rs:16-22) and the
per-operator DataFusion `BaselineMetrics` exposed through
``ExecutionPlan::metrics`` (streaming_window.rs:211,491).  Here:

- every physical operator already keeps a metrics dict (rows_in,
  device_steps, late_rows, ...) exposed via ``ExecOperator.metrics()``;
- :func:`collect_metrics` aggregates them over a plan tree;
- :func:`enable_tracing` turns on span logging: :class:`span` context
  managers emit enter/close events with wall-time, like tracing-subscriber's
  span events.
"""

from __future__ import annotations

import contextlib
import logging
import time

logger = logging.getLogger("denormalized_tpu")

_TRACING = False


def enable_tracing(level: int = logging.INFO) -> None:
    global _TRACING
    _TRACING = True
    if not logging.getLogger().handlers:
        logging.basicConfig(
            level=level,
            format="%(asctime)s %(levelname)s %(name)s %(message)s",
        )
    logger.setLevel(level)


def tracing_enabled() -> bool:
    return _TRACING


@contextlib.contextmanager
def span(name: str, **fields):
    """Span with enter/close events (tracing-subscriber
    `with_span_events(ENTER|CLOSE)` analog)."""
    if not _TRACING:
        yield
        return
    t0 = time.perf_counter()
    logger.info("enter %s %s", name, fields or "")
    try:
        yield
    finally:
        logger.info(
            "close %s time.busy=%.3fms", name, (time.perf_counter() - t0) * 1e3
        )


def collect_metrics(root) -> dict[str, dict]:
    """Per-operator metrics over a physical plan tree, keyed by the same
    DFS ids used for checkpoint node ids."""
    from denormalized_tpu.state.checkpoint import assign_node_ids, walk

    ids = assign_node_ids(root)
    out = {}
    for op in walk(root):
        m = op.metrics()
        if m:
            out[ids[id(op)]] = m
    return out


def log_metrics(root) -> None:
    if _TRACING:
        for node, m in collect_metrics(root).items():
            logger.info("metrics %s %s", node, m)
