"""Lightweight tracing/metrics.

Observability mirror of the reference: `tracing`/`tracing-subscriber` span
events wired in the rideshare example (kafka_rideshare.rs:16-22) and the
per-operator DataFusion `BaselineMetrics` exposed through
``ExecutionPlan::metrics`` (streaming_window.rs:211,491).  Here:

- every physical operator already keeps a metrics dict (rows_in,
  device_steps, late_rows, ...) exposed via ``ExecOperator.metrics()``;
- :func:`collect_metrics` aggregates them over a plan tree;
- :func:`enable_tracing` turns on span logging: :class:`span` context
  managers emit enter/close events with wall-time, like tracing-subscriber's
  span events.
"""

from __future__ import annotations

import contextlib
import logging
import time

logger = logging.getLogger("denormalized_tpu")

_TRACING = False


def enable_tracing(level: int = logging.INFO) -> None:
    global _TRACING
    _TRACING = True
    if not logging.getLogger().handlers:
        logging.basicConfig(
            level=level,
            format="%(asctime)s %(levelname)s %(name)s %(message)s",
        )
    logger.setLevel(level)


def tracing_enabled() -> bool:
    return _TRACING


@contextlib.contextmanager
def span(name: str, **fields):
    """Span with enter/close events (tracing-subscriber
    `with_span_events(ENTER|CLOSE)` analog).

    Two recording surfaces, independently enabled:

    - log lines when :func:`enable_tracing` is on — the close line
      carries the entry fields AND the error status (a span that exits
      via exception logs ``status=ExcType``, not a plain close that
      reads like success);
    - the structured ring recorder
      (:func:`denormalized_tpu.obs.spans.enable_span_recording`), which
      dumps Perfetto-loadable Chrome trace JSON for whole-pipeline
      profiling.  Failed spans carry ``args.error`` there.
    """
    from denormalized_tpu.obs import spans as obs_spans

    rec = obs_spans.recorder()
    if not _TRACING and rec is None:
        yield
        return
    t0 = time.perf_counter()
    if _TRACING:
        logger.info("enter %s %s", name, fields or "")
    err: str | None = None
    try:
        yield
    except BaseException as e:
        # record, never swallow: the span must report failure (the old
        # code logged a plain `close` indistinguishable from success)
        err = type(e).__name__
        raise
    finally:
        dur = time.perf_counter() - t0
        if _TRACING:
            logger.info(
                "close %s time.busy=%.3fms status=%s %s",
                name, dur * 1e3, err or "ok", fields or "",
            )
        if rec is not None:
            rec.record(name, t0, dur, fields or None, error=err)


def collect_metrics(root) -> dict[str, dict]:
    """Per-operator metrics over a physical plan tree, keyed by the same
    DFS ids used for checkpoint node ids."""
    from denormalized_tpu.state.checkpoint import assign_node_ids, walk

    ids = assign_node_ids(root)
    out = {}
    for op in walk(root):
        m = op.metrics()
        if m:
            out[ids[id(op)]] = m
    return out


def log_metrics(root) -> None:
    if _TRACING:
        for node, m in collect_metrics(root).items():
            logger.info("metrics %s %s", node, m)
