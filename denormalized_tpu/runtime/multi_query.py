"""Multi-query runtime: N concurrently registered queries, one ingest.

Production traffic is many concurrent windowed queries over the same
topics (per-user dashboards, alerting rules) — not one pipeline.  This
runtime takes a batch of registered queries, runs the sharing pass
(planner/sharing.py), and executes each share group through ONE
physical pipeline: one SourceExec (one fetch+decode pass), one shared
interner, one :class:`SliceWindowExec` with a
:class:`~denormalized_tpu.physical.slice_exec.SliceSubscriber` per
query — emissions fan out to per-query sinks by subscriber tag.
Unshareable queries (UDAFs, sessions, different filters, cost-rejected
slide sets) fall back to the normal single-query executor, unchanged.

Checkpointing rides the existing epoch-consistent protocol: the shared
group takes ONE snapshot per epoch (slice partials + interner + every
subscriber's emission cursor) under the same in-band marker alignment
and coordinator commit the single-query executor uses; restore resumes
every subscriber exactly at its own cursor.

The pipeline doctor files one :class:`QueryHandle` per subscriber
query (``doctor.register_shared``): shared nodes report busy time and
state bytes SCALED by 1/N per handle, so ``/queries/<id>/plan`` and
``/queries/<id>/state`` stay truthful per query instead of charging
the whole shared operator to whichever query registered first.
"""

from __future__ import annotations

import threading
from typing import Callable

from denormalized_tpu.common.errors import PlanError
from denormalized_tpu.common.record_batch import RecordBatch
from denormalized_tpu.physical.base import EndOfStream, ExecOperator, Marker
from denormalized_tpu.physical.slice_exec import (
    SliceSubscriber,
    SliceWindowExec,
    SubscriberBatch,
)
from denormalized_tpu.planner import predicates as pr
from denormalized_tpu.planner.sharing import (
    ShareGroup,
    classify,
    detect_sharing,
)


def _find_shared_join(op):
    """First StreamingJoinExec under the shared root's child subtree
    (None when the group windows a join-free input) — the operator
    whose measured build/probe/gather cost the doctor attributes across
    subscribers instead of 1/N."""
    from denormalized_tpu.physical.join_exec import StreamingJoinExec

    stack = [op]
    while stack:
        cur = stack.pop()
        if isinstance(cur, StreamingJoinExec):
            return cur
        stack.extend(cur.children)
    return None


def build_shared_root(
    ctx, group: ShareGroup, labels: list[str] | None = None
) -> ExecOperator:
    """Build the shared physical pipeline for one share group: the
    common input subtree planned once (the BASE member's — weakest —
    filter included), topped by a tagged SliceWindowExec with one
    subscriber per member query; members with a strictly stronger
    predicate carry it as a residual the operator re-applies.  Must run
    under the query's bound obs registry (the caller's job — see
    run_queries)."""
    from denormalized_tpu.planner.planner import Planner

    child = Planner(ctx.config).create_physical_plan(group.input_plan)
    subs = [
        SliceSubscriber(
            w.aggr_exprs,
            w.length_ms,
            w.slide_ms or w.length_ms,
            tag=k,
            label=labels[k] if labels else None,
            filter_expr=(
                group.filters[k] if k < len(group.filters) else None
            ),
            filter_sig=(
                group.filter_sigs[k] if k < len(group.filter_sigs) else ""
            ),
        )
        for k, w in enumerate(group.windows)
    ]
    root = SliceWindowExec(
        child,
        group.windows[0].group_exprs,
        subs,
        tagged=True,
        emit_on_close=getattr(ctx.config, "emit_on_close", True),
        unit_ms=getattr(ctx.config, "slice_unit_ms", None),
        sort_lane=getattr(ctx.config, "slice_sort_lane", False),
    )
    join = _find_shared_join(child)
    if join is not None:
        # a shared join feeds this group: turn on its stage timers and
        # hand the slice operator its measured cost so shared_fractions
        # apportions join time by kept-rows share, not 1/N
        join.enable_shared_attribution()
        root._upstream_cost_fn = join.shared_cost_ms
    return root


def drive_shared(
    root: ExecOperator,
    sinks: list[Callable[[RecordBatch], None]],
    coord=None,
) -> None:
    """Pump one shared pipeline to completion, routing each tagged
    emission to its subscriber's sink and committing drained epochs —
    the share-group analog of the executor's drive loop."""
    for item in root.run():
        if isinstance(item, SubscriberBatch):
            sinks[item.tag](item.batch)
        elif isinstance(item, Marker) and coord is not None:
            coord.commit(item.epoch)
        elif isinstance(item, EndOfStream):
            break


class SharedPipeline:
    """Live multi-query serving over ONE shared slice pipeline: a
    thread-safe registry of subscriber queries that can join and leave
    MID-STREAM, without restarting the shared operator or cold-starting
    an independent pipeline per query.

    Built from an initial batch of queries that must form one share
    group (``detect_sharing``), it exposes:

    - :meth:`register` — queue a new query; it attaches at a slice
      boundary on the operator thread and WARMS from the slice store's
      retained partials (windows the gcd slices already cover backfill
      immediately, exact from the query's first exact window — see
      docs/multi_query.md for the exactness contract);
    - :meth:`deregister` — queue a leave; the cursor detaches at a
      slice boundary and partials no survivor needs are pruned.

    Both accept ``when_ts``, an event-time threshold: the op fires at
    the first batch whose min timestamp reaches it.  Event-time
    scheduling makes a registration schedule REPLAYABLE — after a
    kill/restore, re-issuing the same requests lands every join/leave
    at the same stream position, and subscribers present in the
    restored checkpoint adopt their snapshotted cursor instead of
    backfilling (tags are assigned sequentially and deterministically).

    A registering query must share the pipeline's source+keys and carry
    a filter the group's base predicate already admits (identical, or
    implied under subsumption) — the live ingest cannot widen.
    """

    def __init__(
        self,
        ctx,
        queries,
        *,
        labels: list[str] | None = None,
        checkpoint: bool | None = None,
    ) -> None:
        from denormalized_tpu import obs
        from denormalized_tpu.runtime import executor

        if not queries:
            raise PlanError("SharedPipeline needs at least one query")
        self._ctx = ctx
        self._checkpoint = checkpoint
        plans = [ds._plan for ds, _sink in queries]
        subsumption = getattr(ctx.config, "mq_subsumption", True)
        groups = detect_sharing(plans, subsumption=subsumption)
        shared = [g for g in groups if g.shared]
        if len(queries) > 1 and (
            len(shared) != 1 or len(shared[0].members) != len(queries)
        ):
            reasons = "; ".join(
                g.reason or "?" for g in groups if not g.shared
            )
            raise PlanError(
                "initial queries do not form one share group: " + reasons
            )
        group = shared[0] if shared else _singleton_group(plans[0])
        self._group = group
        key0, entry0 = classify(plans[group.members[0]])
        self._key = key0
        self._base_sig = (
            group.base_sig if group.base_sig is not None
            else entry0.filter_sig
        )
        base_entry = entry0
        for i in group.members:
            _k, e = classify(plans[i])
            if e.filter_sig == self._base_sig:
                base_entry = e
                break
        self._base_cons = base_entry.cons
        self._lock = threading.Lock()
        # per-tag planning facts (preds, cons, filter_sig): the base
        # re-derivation on deregister needs every live member's full
        # predicate to find the survivors' weakest (ISSUE 17 sat. 1)
        self._member_facts: dict[int, tuple] = {}
        for k, i in enumerate(group.members):
            _k2, e = classify(plans[i])
            self._member_facts[k] = (e.preds, e.cons, e.filter_sig)
        # tags for initial members are their member index; live joiners
        # continue the sequence (deterministic across a replay)
        self._sinks: dict[int, Callable] = {
            k: queries[i][1] for k, i in enumerate(group.members)
        }
        self._next_tag = len(group.members)
        self._labels = labels or [f"member{i}" for i in group.members]
        self._reg = executor._resolve_registry(ctx)
        with obs.bound_registry(self._reg):
            self._root: SliceWindowExec = build_shared_root(
                ctx, group, self._labels
            )
        self._root.on_detach = self._on_detach

    @property
    def root(self) -> SliceWindowExec:
        return self._root

    def register(
        self,
        ds,
        sink: Callable[[RecordBatch], None],
        *,
        label: str | None = None,
        when_ts: int | None = None,
    ) -> int:
        """Queue a live subscription (any thread); returns the tag its
        emissions carry.  Validates shareability up front so a bad
        query is rejected HERE, not on the operator thread mid-drive."""
        key, entry = classify(ds._plan)
        if key is None:
            raise PlanError(f"query cannot join a shared pipeline: {entry}")
        if key != self._key:
            raise PlanError(
                "query does not share the pipeline's source, projection "
                "and group keys"
            )
        w = entry.window
        length = int(w.length_ms)
        slide = int(w.slide_ms) if w.slide_ms else length
        unit = self._root.unit_ms
        if length % unit or slide % unit:
            raise PlanError(
                f"window {length}ms/{slide}ms does not tile the shared "
                f"group's {unit}ms slices"
            )
        with self._lock:
            # predicate gate and membership insert are one atomic step:
            # _on_detach re-derives the base from the surviving members
            # under this same lock, so checking against a base the
            # detach hook is about to replace cannot admit a widening
            # query (TOCTOU otherwise)
            if entry.filter_sig != self._base_sig and not pr.implies(
                entry.cons, self._base_cons
            ):
                raise PlanError(
                    "query filter is not implied by the shared pipeline's "
                    "base predicate — the live ingest cannot widen; run it "
                    "as an independent pipeline"
                )
            base_sig = self._base_sig
            tag = self._next_tag
            self._next_tag += 1
            self._sinks[tag] = sink
            self._member_facts[tag] = (
                entry.preds, entry.cons, entry.filter_sig
            )
        sub = SliceSubscriber(
            w.aggr_exprs,
            length,
            slide,
            tag=tag,
            label=label if label is not None else f"live{tag}",
            filter_expr=(
                None if entry.filter_sig == base_sig
                else pr.conjoin(entry.preds)
            ),
            filter_sig=entry.filter_sig,
        )
        self._root.request_attach(sub, when_ts)
        return tag

    def deregister(self, tag: int, *, when_ts: int | None = None) -> None:
        """Queue a live unsubscription (any thread)."""
        self._root.request_detach(tag, when_ts)

    def _on_detach(self, tag: int) -> None:
        """Operator-thread hook, fired inside the slice boundary that
        detached ``tag``.  When the departed member held the group's
        BASE (weakest) predicate, the shared ingest would otherwise
        keep admitting rows only that member could reach, forever —
        correct but wasteful.  Re-derive the base from the survivors:
        their weakest member's predicate (``predicates.weakest``)
        becomes the new ingest filter — every survivor's full predicate
        implies it, so the residual re-filters stay exact — and the
        registration gate tightens to the new base (the live ingest
        still cannot widen).  Pairwise-incomparable survivors keep the
        old, wider predicate: no single survivor predicate admits every
        row the others need.  Replayed detaches of already-departed
        tags are no-ops."""
        with self._lock:
            facts = self._member_facts.pop(tag, None)
            if facts is None or facts[2] != self._base_sig:
                return
            if not self._member_facts:
                return
            tags = sorted(self._member_facts)
            if any(
                self._member_facts[t][2] == self._base_sig for t in tags
            ):
                return  # another live member still holds the base
            idx = pr.weakest([self._member_facts[t][1] for t in tags])
            if idx is None:
                return
            preds, cons, sig = self._member_facts[tags[idx]]
            self._base_sig = sig
            self._base_cons = cons
            self._root.set_ingest_pred(pr.conjoin(preds))

    def run(self) -> None:
        """Drive the shared pipeline to EndOfStream on the calling
        thread, routing tagged emissions (including attach-time
        backfills) to each subscriber's sink."""
        from denormalized_tpu import obs
        from denormalized_tpu.obs import doctor
        from denormalized_tpu.runtime import executor

        ctx = self._ctx
        orch = coord = exporters = None
        handles: list = []
        with obs.bound_registry(self._reg):
            try:
                orch, coord = executor._attach_checkpointing(
                    self._root, ctx, self._checkpoint
                )
                ctx._last_coord = coord
                exporters = obs.start_exporters(
                    ctx.config, registry=self._reg
                )
                handles = doctor.register_shared(
                    self._root, len(self._group.members),
                    config=ctx.config, registry=self._reg,
                    labels=self._labels,
                )
                for item in self._root.run():
                    if isinstance(item, SubscriberBatch):
                        with self._lock:
                            sink = self._sinks.get(item.tag)
                        if sink is not None:
                            sink(item.batch)
                    elif isinstance(item, Marker) and coord is not None:
                        coord.commit(item.epoch)
                    elif isinstance(item, EndOfStream):
                        break
            finally:
                if orch is not None:
                    orch.stop()
                for h in handles:
                    h.finish()
                if exporters is not None:
                    exporters.stop()


def _singleton_group(plan) -> ShareGroup:
    """A one-member ShareGroup for a SharedPipeline started with a
    single query (it still runs the slice operator in tagged mode so
    live joiners can attach)."""
    key, entry = classify(plan)
    if key is None:
        raise PlanError(f"query cannot seed a shared pipeline: {entry}")
    w = entry.window
    slide = int(w.slide_ms) if w.slide_ms else int(w.length_ms)
    import math

    return ShareGroup(
        [0],
        shared=True,
        windows=[w],
        input_plan=w.input,
        unit_ms=math.gcd(int(w.length_ms), slide),
        filters=[None],
        filter_sigs=[entry.filter_sig],
        base_sig=entry.filter_sig,
    )


def run_queries(
    ctx,
    queries,
    *,
    sharing: bool = True,
    checkpoint: bool | None = None,
) -> dict:
    """Execute a batch of concurrently registered queries.

    ``queries`` is a list of ``(DataStream, sink_fn)`` pairs; each
    sink_fn receives that query's emitted RecordBatches in order.
    Returns a planning/execution report::

        {"queries": N,
         "groups": [{"members": [...], "shared": bool,
                     "unit_ms": g | None, "reason": str | None,
                     "query_ids": [doctor ids] | None}, ...],
         "shared_queries": n, "independent_queries": m}

    With ``sharing=False`` every query runs through the normal
    single-query executor (the A/B baseline).

    Execution contract: groups run SEQUENTIALLY in first-member order,
    each drained to EndOfStream before the next starts — so this entry
    point serves bounded (replay/batch) feeds.  With an unbounded
    source, the first group never ends and later groups never run:
    drive each group on its own thread/process instead (one
    build_shared_root + drive_shared per group), the same rule as any
    two concurrent queries today."""
    from denormalized_tpu import obs
    from denormalized_tpu.obs import doctor
    from denormalized_tpu.physical.simple_execs import CallbackSink
    from denormalized_tpu.runtime import executor

    plans = [ds._plan for ds, _sink in queries]
    if sharing:
        groups = detect_sharing(
            plans,
            subsumption=getattr(ctx.config, "mq_subsumption", True),
        )
    else:
        groups = [
            ShareGroup([i], shared=False, reason="sharing disabled")
            for i in range(len(queries))
        ]
    report = {
        "queries": len(queries),
        "groups": [],
        "shared_queries": 0,
        "independent_queries": 0,
    }
    for group in groups:
        entry = {
            "members": list(group.members),
            "shared": group.shared,
            "unit_ms": group.unit_ms,
            "reason": group.reason,
            "query_ids": None,
        }
        if not group.shared:
            report["independent_queries"] += len(group.members)
            for i in group.members:
                ds, sink = queries[i]
                ds._execute(CallbackSink(sink), checkpoint=checkpoint)
            report["groups"].append(entry)
            continue
        report["shared_queries"] += len(group.members)
        sinks = [queries[i][1] for i in group.members]
        labels = [f"member{i}" for i in group.members]
        reg = executor._resolve_registry(ctx)
        orch = coord = exporters = None
        handles: list = []
        with obs.bound_registry(reg):
            root = build_shared_root(ctx, group, labels)
            try:
                orch, coord = executor._attach_checkpointing(
                    root, ctx, checkpoint
                )
                ctx._last_coord = coord
                exporters = obs.start_exporters(ctx.config, registry=reg)
                handles = doctor.register_shared(
                    root, len(group.members),
                    config=ctx.config, registry=reg, labels=labels,
                )
                entry["query_ids"] = [h.query_id for h in handles]
                drive_shared(root, sinks, coord)
            finally:
                if orch is not None:
                    orch.stop()
                for h in handles:
                    h.finish()
                if exporters is not None:
                    exporters.stop()
        report["groups"].append(entry)
    return report
