"""Multi-query runtime: N concurrently registered queries, one ingest.

Production traffic is many concurrent windowed queries over the same
topics (per-user dashboards, alerting rules) — not one pipeline.  This
runtime takes a batch of registered queries, runs the sharing pass
(planner/sharing.py), and executes each share group through ONE
physical pipeline: one SourceExec (one fetch+decode pass), one shared
interner, one :class:`SliceWindowExec` with a
:class:`~denormalized_tpu.physical.slice_exec.SliceSubscriber` per
query — emissions fan out to per-query sinks by subscriber tag.
Unshareable queries (UDAFs, sessions, different filters, cost-rejected
slide sets) fall back to the normal single-query executor, unchanged.

Checkpointing rides the existing epoch-consistent protocol: the shared
group takes ONE snapshot per epoch (slice partials + interner + every
subscriber's emission cursor) under the same in-band marker alignment
and coordinator commit the single-query executor uses; restore resumes
every subscriber exactly at its own cursor.

The pipeline doctor files one :class:`QueryHandle` per subscriber
query (``doctor.register_shared``): shared nodes report busy time and
state bytes SCALED by 1/N per handle, so ``/queries/<id>/plan`` and
``/queries/<id>/state`` stay truthful per query instead of charging
the whole shared operator to whichever query registered first.
"""

from __future__ import annotations

from typing import Callable

from denormalized_tpu.common.record_batch import RecordBatch
from denormalized_tpu.physical.base import EndOfStream, ExecOperator, Marker
from denormalized_tpu.physical.slice_exec import (
    SliceSubscriber,
    SliceWindowExec,
    SubscriberBatch,
)
from denormalized_tpu.planner.sharing import ShareGroup, detect_sharing


def build_shared_root(
    ctx, group: ShareGroup, labels: list[str] | None = None
) -> ExecOperator:
    """Build the shared physical pipeline for one share group: the
    common input subtree planned once, topped by a tagged
    SliceWindowExec with one subscriber per member query.  Must run
    under the query's bound obs registry (the caller's job — see
    run_queries)."""
    from denormalized_tpu.planner.planner import Planner

    child = Planner(ctx.config).create_physical_plan(group.input_plan)
    subs = [
        SliceSubscriber(
            w.aggr_exprs,
            w.length_ms,
            w.slide_ms or w.length_ms,
            tag=k,
            label=labels[k] if labels else None,
        )
        for k, w in enumerate(group.windows)
    ]
    return SliceWindowExec(
        child,
        group.windows[0].group_exprs,
        subs,
        tagged=True,
        emit_on_close=getattr(ctx.config, "emit_on_close", True),
        unit_ms=getattr(ctx.config, "slice_unit_ms", None),
        sort_lane=getattr(ctx.config, "slice_sort_lane", False),
    )


def drive_shared(
    root: ExecOperator,
    sinks: list[Callable[[RecordBatch], None]],
    coord=None,
) -> None:
    """Pump one shared pipeline to completion, routing each tagged
    emission to its subscriber's sink and committing drained epochs —
    the share-group analog of the executor's drive loop."""
    for item in root.run():
        if isinstance(item, SubscriberBatch):
            sinks[item.tag](item.batch)
        elif isinstance(item, Marker) and coord is not None:
            coord.commit(item.epoch)
        elif isinstance(item, EndOfStream):
            break


def run_queries(
    ctx,
    queries,
    *,
    sharing: bool = True,
    checkpoint: bool | None = None,
) -> dict:
    """Execute a batch of concurrently registered queries.

    ``queries`` is a list of ``(DataStream, sink_fn)`` pairs; each
    sink_fn receives that query's emitted RecordBatches in order.
    Returns a planning/execution report::

        {"queries": N,
         "groups": [{"members": [...], "shared": bool,
                     "unit_ms": g | None, "reason": str | None,
                     "query_ids": [doctor ids] | None}, ...],
         "shared_queries": n, "independent_queries": m}

    With ``sharing=False`` every query runs through the normal
    single-query executor (the A/B baseline).

    Execution contract: groups run SEQUENTIALLY in first-member order,
    each drained to EndOfStream before the next starts — so this entry
    point serves bounded (replay/batch) feeds.  With an unbounded
    source, the first group never ends and later groups never run:
    drive each group on its own thread/process instead (one
    build_shared_root + drive_shared per group), the same rule as any
    two concurrent queries today."""
    from denormalized_tpu import obs
    from denormalized_tpu.obs import doctor
    from denormalized_tpu.physical.simple_execs import CallbackSink
    from denormalized_tpu.runtime import executor

    plans = [ds._plan for ds, _sink in queries]
    if sharing:
        groups = detect_sharing(plans)
    else:
        groups = [
            ShareGroup([i], shared=False, reason="sharing disabled")
            for i in range(len(queries))
        ]
    report = {
        "queries": len(queries),
        "groups": [],
        "shared_queries": 0,
        "independent_queries": 0,
    }
    for group in groups:
        entry = {
            "members": list(group.members),
            "shared": group.shared,
            "unit_ms": group.unit_ms,
            "reason": group.reason,
            "query_ids": None,
        }
        if not group.shared:
            report["independent_queries"] += len(group.members)
            for i in group.members:
                ds, sink = queries[i]
                ds._execute(CallbackSink(sink), checkpoint=checkpoint)
            report["groups"].append(entry)
            continue
        report["shared_queries"] += len(group.members)
        sinks = [queries[i][1] for i in group.members]
        labels = [f"member{i}" for i in group.members]
        reg = executor._resolve_registry(ctx)
        orch = coord = exporters = None
        handles: list = []
        with obs.bound_registry(reg):
            root = build_shared_root(ctx, group, labels)
            try:
                orch, coord = executor._attach_checkpointing(
                    root, ctx, checkpoint
                )
                ctx._last_coord = coord
                exporters = obs.start_exporters(ctx.config, registry=reg)
                handles = doctor.register_shared(
                    root, len(group.members),
                    config=ctx.config, registry=reg, labels=labels,
                )
                entry["query_ids"] = [h.query_id for h in handles]
                drive_shared(root, sinks, coord)
            finally:
                if orch is not None:
                    orch.stop()
                for h in handles:
                    h.finish()
                if exporters is not None:
                    exporters.stop()
        report["groups"].append(entry)
    return report
