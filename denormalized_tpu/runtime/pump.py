"""Shutdown-safe queue pumps shared by multi-input operators.

One implementation of the pattern both ``SourceExec`` (per-partition reader
threads, the analog of the reference's per-partition tokio tasks feeding an
mpsc channel, kafka_stream_read.rs:148) and ``StreamingJoinExec`` (one pump
per input side) need: a producer thread that

- never blocks forever on a bounded queue (it re-checks the consumer's
  ``done`` event while waiting),
- surfaces exceptions as queue items so the consumer re-raises them instead
  of mistaking a dead producer for clean end-of-input,
- always delivers a final ``sentinel``.
"""

from __future__ import annotations

import queue as queue_mod
import threading
from typing import Callable, Iterable


def checked_put(
    q: queue_mod.Queue, done: threading.Event, item, timeout: float = 0.1
) -> bool:
    """Bounded put that keeps observing ``done``; False if shutdown won."""
    while not done.is_set():
        try:
            q.put(item, timeout=timeout)
            return True
        except queue_mod.Full:
            continue
    return False


def spawn_pump(
    q: queue_mod.Queue,
    done: threading.Event,
    items: Callable[[], Iterable],
    sentinel,
    wrap: Callable = lambda x: x,
) -> threading.Thread:
    """Start a daemon thread feeding ``wrap(item)`` for each item of
    ``items()`` into ``q``; exceptions are enqueued wrapped too; ``sentinel``
    is always enqueued last (pre-wrapped by the caller)."""

    def run():
        try:
            for item in items():
                if not checked_put(q, done, wrap(item)):
                    return
        except BaseException as e:  # dnzlint: allow(broad-except) not swallowed — the exception is enqueued as data and the consumer re-raises it (see module docstring)
            checked_put(q, done, wrap(e))
        finally:
            checked_put(q, done, sentinel)

    t = threading.Thread(target=run, daemon=True)
    t.start()
    return t
