"""Deterministic, seedable fault injection for the I/O boundaries.

Every failure mode the engine claims to survive is only *proven* survivable
when it can be produced on demand.  The SIGKILL soaks (tools/soak.py) cover
whole-process death; this module covers everything BELOW that granularity —
a broker connection flapping mid-epoch, one torn state write, a prefetch
worker dying, a transient error inside ``commit`` — as first-class,
reproducible events.

A process-global :class:`FaultPlan` is threaded through named **injection
sites** at the engine's I/O boundaries::

    kafka.fetch         KafkaClient fetch           (raises SourceError)
    kafka.produce       KafkaClient produce         (raises SourceError)
    decode              decoder output, per rowful  (raises SourceError)
                        batch, both decode paths
    sink.write          KafkaSinkWriter.write       (raises SourceError)
    lsm.put             LsmStore.put                (StateError / torn value)
    lsm.get             LsmStore.get                (raises StateError)
    lsm.flush           LsmStore.flush              (raises StateError)
    checkpoint.commit   CheckpointCoordinator.commit(raises StateError)
    lsm.spill_put       SpillController.put_block   (StateError / torn value)
    lsm.spill_get       SpillController.get_block   (raises StateError)
    spill.manifest      SpillController.write_manifest (StateError / torn)
    exchange.connect    ExchangeClient.connect      (raises SourceError)
    exchange.send       ExchangeClient.send         (SourceError / torn frame)
    exchange.recv       exchange server recv loop   (raises SourceError)
    exchange.reconnect  ExchangeClient redial of a  (raises SourceError)
                        down edge, per attempt
    cluster.rejoin      respawned worker's rejoin   (raises StateError)
                        handshake, before ready
    cluster.replay      buffered-frame replay on a  (SourceError / torn frame)
                        fresh exchange connection

Each site calls :func:`inject` (optionally passing the key/payload being
written).  With no plan armed ``inject`` is a single attribute check and an
immediate return — sites sit at per-fetch / per-snapshot granularity, never
per-row, so an unarmed plan costs nothing measurable (pinned by
``tests/test_faults.py`` and the ingest_scale acceptance run).

## Plan grammar

A plan is JSON (or the equivalent dict through :func:`arm`)::

    {"seed": 1234,
     "rules": [
       {"site": "kafka.fetch", "kind": "error", "prob": 0.02, "times": 6,
        "message": "recv: injected broker flap"},
       {"site": "kafka.fetch", "kind": "error", "after": 200, "times": 1,
        "message": "injected worker crash"},
       {"site": "lsm.put", "kind": "torn", "key_substr": "@", "times": 2},
       {"site": "checkpoint.commit", "kind": "error", "times": 2},
       {"site": "*", "kind": "latency", "ms": 5, "prob": 0.01}
     ]}

Rule fields:

- ``site``: exact site name, a ``prefix.*`` glob, or ``*`` (all sites).
- ``kind``: ``error`` (raise), ``latency`` (sleep ``ms`` milliseconds), or
  ``torn`` (truncate the payload bytes at a seeded cut point — only
  meaningful at sites that pass a payload, i.e. ``lsm.put``).
- ``times``: fire at most N times (omitted/null = unlimited) — the
  "repeat-N-then-heal" schedule.
- ``after``: skip the first K *matching* calls before becoming eligible.
- ``prob``: per-call firing probability (default 1.0), drawn from the
  rule's own seeded RNG so the decision for matching call #k is a pure
  function of ``(seed, rule index, k)`` — deterministic regardless of
  which thread made the call.
- ``key_substr``: only match calls whose ``key`` contains this substring
  (e.g. ``"@"`` restricts ``lsm.put`` tearing to epoch-suffixed snapshot
  blobs, never the commit record).
- ``message``: error text.  The text *steers classification* downstream:
  transport markers (``recv:`` ...) route a ``kafka.fetch`` error into the
  reader's reconnect path, ``fetch error 1`` into the OFFSET_OUT_OF_RANGE
  reset, anything else escapes the reader and exercises the prefetch
  supervisor.  The default message carries no markers.
- ``error``: ``"source"`` or ``"state"`` to override the error class the
  site would pick by its name prefix.

The first rule that fires wins the call (rules are evaluated in plan
order); a rule that matches but does not fire still advances its ``after``
counter.  Every decision is appended to the plan's event log
(:meth:`FaultPlan.event_log`), which is what the chaos soak compares across
two same-seed simulations to prove determinism.

Arming: :func:`arm` (API) or the ``DENORMALIZED_FAULT_PLAN`` environment
variable — either inline JSON or ``@/path/to/plan.json`` — read once at
module import, which is how the soak's child processes receive the plan.
"""

from __future__ import annotations

import json
import os
import random
import threading
import time

from denormalized_tpu.common.errors import SourceError, StateError

#: known sites, and the error class each raises by default
SITES = {
    "kafka.fetch": SourceError,
    "kafka.produce": SourceError,
    "decode": SourceError,
    "sink.write": SourceError,
    "lsm.put": StateError,
    "lsm.get": StateError,
    "lsm.flush": StateError,
    "checkpoint.commit": StateError,
    "lsm.spill_put": StateError,
    "lsm.spill_get": StateError,
    "spill.manifest": StateError,
    "exchange.connect": SourceError,
    "exchange.send": SourceError,
    "exchange.recv": SourceError,
    "exchange.reconnect": SourceError,
    "cluster.rejoin": StateError,
    "cluster.replay": SourceError,
}

#: where each site's ``inject`` call lives (module relative to this
#: package) and what the boundary is.  Machine-checked both ways by
#: dnzlint (DNZ-F002): a site registered here with no inject call in its
#: declared module — or renamed at the call site — fails the lint gate
#: instead of arming vacuous chaos plans.  The fault-site table in
#: ``docs/fault_tolerance.md`` is generated from this registry
#: (``python -m tools.dnzlint --fault-site-table``).
SITE_MODULES = {
    "kafka.fetch": ("sources/kafka.py", "`KafkaClient` fetch (every wire fetch)"),
    "kafka.produce": ("sources/kafka.py", "`KafkaClient.produce`"),
    "decode": ("sources/kafka.py", "decoder output, once per rowful batch, both decode paths"),
    "sink.write": ("sources/kafka.py", "`KafkaSinkWriter.write`"),
    "lsm.put": ("state/lsm.py", "`LsmStore.put` (supports torn values)"),
    "lsm.get": ("state/lsm.py", "`LsmStore.get`"),
    "lsm.flush": ("state/lsm.py", "`LsmStore.flush`"),
    "checkpoint.commit": ("state/checkpoint.py", "`CheckpointCoordinator.commit`"),
    "lsm.spill_put": (
        "state/tiering.py",
        "`SpillController.put_block` — cold-state block eviction to the "
        "LSM tier (supports torn values)",
    ),
    "lsm.spill_get": (
        "state/tiering.py",
        "`SpillController.get_block` — reload-on-touch of a spilled block",
    ),
    "spill.manifest": (
        "state/tiering.py",
        "`SpillController.write_manifest` — per-node live-block manifest "
        "write (supports torn values)",
    ),
    "exchange.connect": (
        "cluster/exchange.py",
        "`ExchangeClient.connect` — worker-to-worker exchange socket "
        "establishment (cluster runtime)",
    ),
    "exchange.send": (
        "cluster/exchange.py",
        "`ExchangeClient.send` — one framed exchange message on the "
        "wire (supports torn frames: the truncated frame is written, "
        "the receiver's CRC/length check detects the tear)",
    ),
    "exchange.recv": (
        "cluster/exchange.py",
        "exchange server receive loop, once per inbound frame",
    ),
    "exchange.reconnect": (
        "cluster/exchange.py",
        "`ExchangeClient` redial of a down edge during partial "
        "recovery, once per backoff attempt",
    ),
    "cluster.rejoin": (
        "cluster/worker.py",
        "respawned worker's rejoin handshake (generation > 0), before "
        "it reports ready to the coordinator",
    ),
    "cluster.replay": (
        "cluster/exchange.py",
        "replay of sender-buffered frames on a freshly resumed "
        "exchange connection (supports torn frames: the receiver's "
        "CRC check detects the tear and the edge redials)",
    ),
}

_KINDS = ("error", "latency", "torn")


class FaultRule:
    """One rule's match predicate + seeded decision state (thread-safe
    under the owning plan's lock)."""

    def __init__(self, spec: dict, index: int, seed: int):
        self.index = index
        self.name = spec.get("name")  # optional label, echoed in events
        self.site = spec.get("site", "*")
        # a typo'd site ("lsm.putt", "kafk.*") would arm fine, match
        # nothing, and let a chaos run report green without ever
        # injecting the fault — reject at arm time instead
        if self.site != "*":
            if self.site.endswith(".*"):
                prefix = self.site[:-1]
                known = any(s.startswith(prefix) for s in SITES)
            else:
                known = self.site in SITES
            if not known:
                raise ValueError(
                    f"fault rule {index}: site {self.site!r} matches no "
                    f"known site (expected '*' or one of {sorted(SITES)})"
                )
        self.kind = spec.get("kind", "error")
        if self.kind not in _KINDS:
            raise ValueError(
                f"fault rule {index}: unknown kind {self.kind!r} "
                f"(expected one of {_KINDS})"
            )
        times = spec.get("times")
        self.times = None if times is None else int(times)
        self.after = int(spec.get("after", 0))
        self.prob = float(spec.get("prob", 1.0))
        self.key_substr = spec.get("key_substr")
        self.message = spec.get("message")
        self.error = spec.get("error")
        self.ms = float(spec.get("ms", 0.0))
        # decision RNG: a pure function of (seed, rule index) — the k-th
        # matching call's draw is identical across runs and across the
        # thread interleavings that produced it
        self._rng = random.Random(int(seed) * 1_000_003 + index)
        self.hits = 0  # matching calls seen
        self.fired = 0  # times this rule actually fired

    def matches(self, site: str, key: str | None) -> bool:
        if self.site != "*" and self.site != site:
            if not (self.site.endswith(".*")
                    and site.startswith(self.site[:-1])):
                return False
        if self.key_substr is not None:
            if key is None or self.key_substr not in key:
                return False
        return True

    def decide(self) -> bool:
        """Advance this rule's deterministic counters for one matching
        call; True when the rule fires."""
        self.hits += 1
        if self.times is not None and self.fired >= self.times:
            return False
        if self.hits <= self.after:
            return False
        if self.prob < 1.0 and self._rng.random() >= self.prob:
            return False
        self.fired += 1
        return True

    def error_class(self, site: str):
        if self.error == "source":
            return SourceError
        if self.error == "state":
            return StateError
        cls = SITES.get(site)
        if cls is not None:
            return cls
        head = site.split(".", 1)[0]
        return StateError if head in ("lsm", "checkpoint", "state") \
            else SourceError


class FaultPlan:
    """A seeded set of rules plus the log of everything they did."""

    def __init__(self, spec: dict | str):
        if isinstance(spec, str):
            spec = json.loads(spec)
        self.seed = int(spec.get("seed", 0))
        self.rules = [
            FaultRule(r, i, self.seed)
            for i, r in enumerate(spec.get("rules", []))
        ]
        self.events: list[dict] = []
        self._lock = threading.Lock()
        # per-site registry counters, bound lazily on first firing (a
        # plan can be armed before the obs registry is configured)
        self._obs_counters: dict[str, object] = {}

    # -- the one entry point every site goes through ---------------------
    def on(self, site: str, key: str | None = None, payload=None):
        """Apply the plan to one call at ``site``; returns the (possibly
        torn) payload, raises the rule's error class, or sleeps."""
        sleep_s = 0.0
        raise_exc = None
        fired_event = None
        with self._lock:
            for rule in self.rules:
                if not rule.matches(site, key):
                    continue
                if rule.kind == "torn" and not payload:
                    # nothing to tear at a payload-less call: leave the
                    # rule's budget (times/after/RNG) untouched for a
                    # call that carries bytes — consuming it here would
                    # log a vacuous "fired" while the planned tear
                    # silently never happens
                    continue
                if not rule.decide():
                    continue
                event = {
                    "site": site,
                    "rule": rule.index,
                    "kind": rule.kind,
                    "hit": rule.hits,
                    "fire": rule.fired,
                }
                if rule.name:
                    event["name"] = rule.name
                if rule.kind == "latency":
                    sleep_s = rule.ms / 1000.0
                    event["ms"] = rule.ms
                elif rule.kind == "torn":
                    # payload is non-empty: payload-less calls were
                    # filtered before decide()
                    keep = rule._rng.randrange(0, len(payload))
                    event["torn_to"] = keep
                    event["torn_from"] = len(payload)
                    if key is not None:
                        event["key"] = key
                    payload = payload[:keep]
                else:  # error
                    msg = rule.message or f"injected fault at {site}"
                    event["message"] = msg
                    raise_exc = rule.error_class(site)(msg)
                self.events.append(event)
                fired_event = event
                break  # first firing rule wins the call
        if fired_event is not None:
            # outside the plan lock: fault events ride the SAME metric +
            # span streams as everything else (counter per site for the
            # Prometheus/JSONL timeline, an instant event in the trace)
            self._record_obs(site, fired_event)
        if sleep_s > 0.0:
            time.sleep(sleep_s)
        if raise_exc is not None:
            raise raise_exc
        return payload

    def _record_obs(self, site: str, event: dict) -> None:
        from denormalized_tpu import obs

        c = self._obs_counters.get(site)
        if c is None:
            c = obs.counter("dnz_fault_injections_total", site=site)
            self._obs_counters[site] = c
        c.add(1)
        rec = obs.spans.recorder()
        if rec is not None:
            rec.instant(f"fault.{site}", dict(event))

    def event_log(self) -> list[dict]:
        with self._lock:
            return [dict(e) for e in self.events]

    def fired_sites(self) -> dict[str, int]:
        """Per-site count of fired injections (observability/asserts)."""
        out: dict[str, int] = {}
        with self._lock:
            for e in self.events:
                out[e["site"]] = out.get(e["site"], 0) + 1
        return out


# -- process-global plan --------------------------------------------------

_PLAN: FaultPlan | None = None


def arm(plan: FaultPlan | dict | str) -> FaultPlan:
    """Install a process-global plan (replacing any previous one)."""
    global _PLAN
    if not isinstance(plan, FaultPlan):
        plan = FaultPlan(plan)
    _PLAN = plan
    return plan


def disarm() -> None:
    global _PLAN
    _PLAN = None


def plan() -> FaultPlan | None:
    return _PLAN


def armed() -> bool:
    return _PLAN is not None


def inject(site: str, key: str | None = None, payload=None):
    """Site hook: no-op (returns ``payload`` unchanged) unless a plan is
    armed.  Sites sit at I/O-operation granularity — one call per fetch,
    produce, state op, or commit — never per row."""
    p = _PLAN
    if p is None:
        return payload
    return p.on(site, key=key, payload=payload)


# env arming at import: how child processes (soak, SIGKILL harnesses)
# receive the plan without API plumbing
_env_plan = os.environ.get("DENORMALIZED_FAULT_PLAN")
if _env_plan:
    try:
        if _env_plan.startswith("@"):
            with open(_env_plan[1:]) as _f:
                _env_plan = _f.read()
        arm(_env_plan)
    except Exception as _e:
        # this runs at engine import — a stale/malformed value must name
        # its source, not surface as a bare JSONDecodeError deep inside
        # an unrelated import chain
        raise RuntimeError(
            f"DENORMALIZED_FAULT_PLAN is set but unusable "
            f"({_env_plan[:80]!r}): {_e}"
        ) from _e
del _env_plan
