"""Execution driver: plan → physical tree → drive to completion.

Counterpart of the reference's execution entry (df.execute_stream +
with_orchestrator lifecycle, datastream.rs:244-343): builds the physical
plan, wires the checkpoint orchestrator into every source when checkpointing
is enabled, installs SIGINT/SIGTERM graceful shutdown (the reference's
start_shutdown_listener, datastream.rs:53-72), and pumps the stream.
"""

from __future__ import annotations

import signal
import threading
from typing import Iterator

from denormalized_tpu.common.record_batch import RecordBatch
from denormalized_tpu.logical import plan as lp
from denormalized_tpu.physical.base import EndOfStream, ExecOperator
from denormalized_tpu.planner.planner import Planner


class ShutdownFlag:
    """Cooperative shutdown shared with sources (tokio::watch analog)."""

    def __init__(self) -> None:
        self._event = threading.Event()

    def set(self) -> None:
        self._event.set()

    def is_set(self) -> bool:
        return self._event.is_set()


def _install_signal_handlers(flag: ShutdownFlag):
    """Install SIGINT/SIGTERM → graceful stop; returns a restore fn.  Only
    possible on the main thread (same constraint tokio::signal has)."""
    if threading.current_thread() is not threading.main_thread():
        return lambda: None
    prev_int = signal.getsignal(signal.SIGINT)
    prev_term = signal.getsignal(signal.SIGTERM)

    def handler(signum, frame):
        flag.set()

    signal.signal(signal.SIGINT, handler)
    signal.signal(signal.SIGTERM, handler)

    def restore():
        signal.signal(signal.SIGINT, prev_int)
        signal.signal(signal.SIGTERM, prev_term)

    return restore


def _attach_checkpointing(root: ExecOperator, ctx, checkpoint=None):
    """When checkpoint=true, start the barrier orchestrator and register
    every source + stateful operator (with_orchestrator,
    datastream.rs:244-307).  Returns (orchestrator, coordinator).

    ``checkpoint`` is a per-execution override: explain(analyze=True)
    passes False so an introspection run never commits epochs under the
    real pipeline's node-id keys — without mutating the Context's shared
    EngineConfig, which a concurrent stream on the same Context reads."""
    enabled = (
        checkpoint if checkpoint is not None
        else getattr(ctx.config, "checkpoint", False)
    )
    if not enabled:
        return None, None
    from denormalized_tpu.state.orchestrator import Orchestrator
    from denormalized_tpu.state.checkpoint import wire_checkpointing

    orch = Orchestrator(interval_s=ctx.config.checkpoint_interval_s)
    coord = wire_checkpointing(root, ctx, orch)
    orch.start()
    return orch, coord


def _resolve_registry(ctx):
    """The metrics registry THIS execution binds against: the thread's
    current registry when the config enables metrics, the shared
    always-disabled registry otherwise.  Resolution is per query, so two
    concurrent executions with different ``metrics_enabled`` settings in
    one process no longer fight over a global flag (the PR-6 documented
    limitation) — each query's operators bind live handles or nulls
    according to ITS OWN config."""
    from denormalized_tpu import obs

    if getattr(ctx.config, "metrics_enabled", True):
        return obs.current_registry()
    return obs.disabled_registry()


def build_physical(plan: lp.LogicalPlan, ctx) -> ExecOperator:
    from denormalized_tpu import obs
    from denormalized_tpu.logical.optimizer import optimize

    # metrics enablement resolves from the EXECUTING context's config,
    # immediately before operator construction (handles bind once — live
    # or null — and the hot path never re-checks): construction runs
    # under the query-resolved registry binding, so a concurrent build
    # with a different setting binds into ITS registry, not ours
    plan = optimize(plan, getattr(ctx.config, "optimizer", True))
    with obs.bound_registry(_resolve_registry(ctx)):
        return Planner(ctx.config).create_physical_plan(plan)


def execute_plan(plan: lp.LogicalPlan, ctx, checkpoint=None) -> None:
    from denormalized_tpu.physical.base import Marker

    from denormalized_tpu import obs
    from denormalized_tpu.obs import doctor

    reg = _resolve_registry(ctx)
    with obs.bound_registry(reg):
        root = build_physical(plan, ctx)
        ctx._last_physical = root  # post-run metrics access (DataStream.metrics)
        # EVERYTHING that starts a per-query service runs inside the
        # try: a failure while wiring the next service (bad lineage
        # config, port clash) must still tear down the ones already
        # started — not leak a bound HTTP port and live threads
        orch = coord = exporters = handle = spill = None
        restore = lambda: None  # noqa: E731
        flag = ShutdownFlag()
        try:
            # cold tier BEFORE checkpoint wiring: restore rebuilds each
            # operator's tier map through the adapter installed here
            from denormalized_tpu.state.tiering import attach_spill

            spill = attach_spill(root, ctx)
            ctx._last_spill = spill
            orch, coord = _attach_checkpointing(root, ctx, checkpoint)
            ctx._last_coord = coord  # transactional sinks read committed_epoch
            # opt-in exporters: Prometheus endpoint / JSONL snapshots /
            # Perfetto trace dump, per EngineConfig (None when nothing
            # opted in), scoped to THIS query's resolved registry
            exporters = obs.start_exporters(ctx.config, registry=reg)
            ctx._last_exporters = exporters
            # pipeline doctor: register the plan for live introspection
            # (/queries/<id>/plan, bottleneck attribution, record lineage)
            handle = doctor.register_query(
                root, config=ctx.config, registry=reg
            )
            ctx._last_doctor = handle
            restore = _install_signal_handlers(flag)
            for item in root.run():
                if isinstance(item, Marker) and coord is not None:
                    # marker drained at the root: every operator
                    # snapshotted this epoch → make it the durable
                    # recovery point
                    coord.commit(item.epoch)
                if flag.is_set():
                    break
                if isinstance(item, EndOfStream):
                    break
        finally:
            restore()
            if orch is not None:
                orch.stop()
            if spill is not None:
                spill.close()
            if handle is not None:
                # freeze the final snapshot (and drop the operator-tree
                # reference) BEFORE exporters stop, so the last JSONL
                # snapshot / trace dump and the doctor agree on end state
                handle.finish()
            if exporters is not None:
                exporters.stop()
            from denormalized_tpu.runtime.tracing import log_metrics

            log_metrics(root)


def stream_plan(plan: lp.LogicalPlan, ctx) -> Iterator[RecordBatch]:
    from denormalized_tpu import obs
    from denormalized_tpu.obs import doctor
    from denormalized_tpu.physical.base import Marker

    reg = _resolve_registry(ctx)
    orch = coord = exporters = handle = it = spill = None
    try:
        with obs.bound_registry(reg):
            root = build_physical(plan, ctx)
            ctx._last_physical = root  # post-run metrics (DataStream.metrics)
            from denormalized_tpu.state.tiering import attach_spill

            spill = attach_spill(root, ctx)
            ctx._last_spill = spill
            orch, coord = _attach_checkpointing(root, ctx)
            # exactly-once sinks tag output with the in-flight epoch and
            # a recovery reader discards the uncommitted suffix (the
            # transactional truncate-on-restore protocol);
            # committed_epoch is their boundary
            ctx._last_coord = coord
            exporters = obs.start_exporters(ctx.config, registry=reg)
            ctx._last_exporters = exporters
            handle = doctor.register_query(
                root, config=ctx.config, registry=reg
            )
            ctx._last_doctor = handle
        # drive loop: re-enter the binding around each RESUMPTION, never
        # across a yield — a paused stream must not leave its registry
        # on the consumer thread's binding stack (a sibling query built
        # between pulls would bind into the wrong registry).  Binds from
        # worker threads ride the captures in SourceExec /
        # PrefetchWorker instead.
        it = root.run()
        while True:
            with obs.bound_registry(reg):
                try:
                    item = next(it)
                except StopIteration:
                    break
            if isinstance(item, RecordBatch):
                yield item
            elif isinstance(item, Marker) and coord is not None:
                coord.commit(item.epoch)
            elif isinstance(item, EndOfStream):
                break
    finally:
        with obs.bound_registry(reg):
            # close the operator chain FIRST (deterministically runs the
            # operators' own finally blocks — pump shutdown, worker
            # joins — instead of waiting for GC), then tear down the
            # per-query services; all slots default to None so a setup
            # failure (bad lineage config, port clash) still stops
            # whatever had already started
            if it is not None:
                it.close()
            if orch is not None:
                orch.stop()
            if spill is not None:
                spill.close()
            if handle is not None:
                handle.finish()
            if exporters is not None:
                exporters.stop()
