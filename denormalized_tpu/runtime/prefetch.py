"""Pipelined multi-core ingest: one prefetch worker per partition.

Each :class:`PrefetchWorker` thread owns one :class:`PartitionReader`
(and therefore that reader's own native client connection — the native
Kafka client is single-threaded per object, so per-worker ownership is
what makes the fetch loops independent) and runs the full
fetch → native decode → ``RecordBatch`` assembly loop off the consumer
thread.  The ctypes foreign calls (``kc_fetch``, the native JSON/Avro
parse) drop the GIL for their native portion, so N workers overlap
network wait and decode across cores; ``tests/test_prefetch_pipeline.py``
pins that property with a concurrency test.

Completed batches land in one shared ready queue that the consumer
(:class:`~denormalized_tpu.physical.simple_execs.SourceExec`) drains —
each item already carries the reader's offset snapshot (taken right
after the read, so barrier persistence reflects only yielded batches)
and its canonical timestamps.  The queue itself is unbounded; the bound
is a per-worker ``Semaphore(depth)`` released only after the consumer
has fully processed the item downstream.  That makes backpressure the
bounded per-partition buffer (a double buffer at ``depth=2``: one batch
being consumed, one being assembled) rather than the reader's poll
cadence, and it means one partition's catch-up burst can never occupy
another partition's budget the way a single shared bounded queue could.

Reader-side activity is tracked on the worker (single-writer slots) so
watermark idleness judgments never depend on when the consumer got
around to processing a partition's batches:

- ``pending``         — enqueued-but-unconsumed rowful batches exist;
- ``enq_wall``        — wall clock of the last rowful enqueue;
- ``first_read_done`` — the first ``read()`` has RETURNED (before that,
  the partition's backlog is unknown, not absent);
- ``caught_up``       — the reader's own backlog report
  (``PartitionReader.caught_up()``): ``False`` means the source KNOWS
  more data is already at the broker, so the partition must never be
  idle-excluded even while a fetch/decode is in flight (the soak-found
  hole behind SOAK_KAFKA's short first window: a partition mid-way
  through a large catch-up fetch looked idle to every consumer-side
  clock).  ``None`` (reader has no backlog knowledge) falls back to the
  wall-clock judgment.
"""

from __future__ import annotations

import queue as queue_mod
import threading
import time
from typing import Callable, Iterator


class PrefetchWorker:
    """One partition's fetch+decode loop on its own thread."""

    def __init__(
        self,
        idx: int,
        reader,
        out_q: queue_mod.Queue,
        done: threading.Event,
        *,
        depth: int = 2,
        read_timeout_s: float = 0.1,
    ) -> None:
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        self.idx = idx
        self.reader = reader
        self._q = out_q
        self._done = done
        self._slots = threading.Semaphore(depth)
        self._read_timeout_s = read_timeout_s
        # single-writer activity slots (worker writes enq_*, consumer
        # writes deq_) — see module docstring
        self.enq_rowful = 0
        self.deq_rowful = 0
        self.enq_wall = time.monotonic()
        self.first_read_done = False
        self.caught_up: bool | None = None
        self.finished = False
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run,
            daemon=True,
            name=f"prefetch-{self.idx}",
        )
        self._thread.start()

    def join(self, timeout: float | None = None) -> None:
        if self._thread is not None:
            self._thread.join(timeout)

    # -- consumer side ----------------------------------------------------
    def consumed(self, rowful: bool) -> None:
        """Release the item's buffer slot AFTER downstream processed it —
        the slot is the backpressure unit, so it must cover the full
        consume, not just the dequeue."""
        if rowful:
            self.deq_rowful += 1
        self._slots.release()

    def activity(self) -> tuple[bool, float, bool, bool]:
        """(pending, last_rowful_enqueue_wall, first_read_done,
        may_judge_idle) for the partition-watermark tracker."""
        return (
            self.enq_rowful > self.deq_rowful,
            self.enq_wall,
            self.first_read_done,
            self.caught_up is not False,
        )

    def reader_quiet(self) -> bool:
        """True when the READER side shows no sign of data in flight:
        first read returned, nothing enqueued-but-unconsumed, and the
        reader does not report known backlog.  A finished partition is
        quiet permanently."""
        if self.finished:
            return True
        return (
            self.first_read_done
            and self.enq_rowful <= self.deq_rowful
            and self.caught_up is not False
        )

    # -- worker side ------------------------------------------------------
    def _acquire_slot(self) -> bool:
        while not self._done.is_set():
            if self._slots.acquire(timeout=0.1):
                return True
        return False

    def _run(self) -> None:
        reader = self.reader
        probe = getattr(reader, "caught_up", None)
        if not callable(probe):
            probe = None
        try:
            while not self._done.is_set():
                b = reader.read(timeout_s=self._read_timeout_s)
                self.first_read_done = True
                if b is None:
                    break  # partition exhausted (or reader died cleanly)
                if probe is not None:
                    self.caught_up = probe()
                if b.num_rows:
                    # stamp BEFORE the (possibly blocking) slot acquire:
                    # while waiting for the consumer the partition has
                    # pending work and must read as active
                    self.enq_wall = time.monotonic()
                    self.enq_rowful += 1
                snap = reader.offset_snapshot()
                if not self._acquire_slot():
                    return  # shutdown won
                self._q.put((self.idx, snap, b))
        except BaseException as e:  # surfaced by the consumer
            self._q.put(e)
        finally:
            self.finished = True
            self._q.put((self.idx, None, None))


class PrefetchPump:
    """N prefetch workers merged into one ready queue."""

    def __init__(
        self,
        readers,
        *,
        queue_budget: int = 64,
        depth: int | None = None,
        read_timeout_s: float = 0.1,
    ) -> None:
        if depth is None:
            # split the aggregate budget across partitions; never below a
            # double buffer, never absurdly deep (in-flight batches widen
            # the watermark skew the consumer must reconcile)
            depth = max(2, min(16, queue_budget // max(1, len(readers))))
        self._q: queue_mod.Queue = queue_mod.Queue()
        self._done = threading.Event()
        self.workers = [
            PrefetchWorker(
                i, r, self._q, self._done,
                depth=depth, read_timeout_s=read_timeout_s,
            )
            for i, r in enumerate(readers)
        ]
        self.depth = depth

    def start(self) -> "PrefetchPump":
        for w in self.workers:
            w.start()
        return self

    def stop(self) -> None:
        self._done.set()

    def get(self):
        return self._q.get()

    def consumed(self, idx: int, rowful: bool) -> None:
        self.workers[idx].consumed(rowful)

    def activity(self, idx: int) -> tuple[bool, float, bool, bool]:
        return self.workers[idx].activity()

    def quiet(self) -> bool:
        """True when EVERY partition is reader-side quiet — the gate for
        the source-level idle hint, so a consumer stall (compile, GC)
        followed by an empty heartbeat can never declare idleness over
        rows that are already fetched or known to be at the broker."""
        return all(w.reader_quiet() for w in self.workers)

    def drain(
        self,
        total_rows: int | None = None,
        deadline: float | None = None,
    ) -> Iterator:
        """Utility consumer loop (bench / tests): yield (idx, snap,
        batch) for every rowful batch, releasing slots as it goes, until
        ``total_rows`` rows were seen or every worker finished.  Raises
        the first worker exception; raises TimeoutError once
        ``time.monotonic()`` passes ``deadline`` — checked on every
        dequeued item (empty heartbeats included) AND while waiting, so
        a wedged stream fails visibly instead of blocking forever."""
        finished = 0
        seen = 0
        n = len(self.workers)
        while finished < n:
            if deadline is None:
                item = self.get()
            else:
                while True:
                    if time.monotonic() > deadline:
                        raise TimeoutError(
                            f"prefetch drain stalled at {seen} rows"
                        )
                    try:
                        item = self._q.get(timeout=1.0)
                        break
                    except queue_mod.Empty:
                        continue
            if isinstance(item, BaseException):
                raise item
            idx, snap, batch = item
            if batch is None:
                finished += 1
                continue
            rowful = bool(batch.num_rows)
            try:
                if rowful:
                    seen += batch.num_rows
                    yield idx, snap, batch
            finally:
                self.consumed(idx, rowful)
            if total_rows is not None and seen >= total_rows:
                return
