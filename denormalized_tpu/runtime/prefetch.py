"""Pipelined multi-core ingest: one prefetch worker per partition.

Each :class:`PrefetchWorker` thread owns one :class:`PartitionReader`
(and therefore that reader's own native client connection — the native
Kafka client is single-threaded per object, so per-worker ownership is
what makes the fetch loops independent) and runs the full
fetch → native decode → ``RecordBatch`` assembly loop off the consumer
thread.  The ctypes foreign calls (``kc_fetch``, the native JSON/Avro
parse) drop the GIL for their native portion, so N workers overlap
network wait and decode across cores; ``tests/test_prefetch_pipeline.py``
pins that property with a concurrency test.

Completed batches land in one shared ready queue that the consumer
(:class:`~denormalized_tpu.physical.simple_execs.SourceExec`) drains —
each item already carries the reader's offset snapshot (taken right
after the read, so barrier persistence reflects only yielded batches)
and its canonical timestamps.  The queue itself is unbounded; the bound
is a per-worker ``Semaphore(depth)`` released only after the consumer
has fully processed the item downstream.  That makes backpressure the
bounded per-partition buffer (a double buffer at ``depth=2``: one batch
being consumed, one being assembled) rather than the reader's poll
cadence, and it means one partition's catch-up burst can never occupy
another partition's budget the way a single shared bounded queue could.

Reader-side activity is tracked on the worker (single-writer slots) so
watermark idleness judgments never depend on when the consumer got
around to processing a partition's batches:

- ``pending``         — enqueued-but-unconsumed rowful batches exist;
- ``enq_wall``        — wall clock of the last rowful enqueue;
- ``first_read_done`` — the first ``read()`` has RETURNED (before that,
  the partition's backlog is unknown, not absent);
- ``caught_up``       — the reader's own backlog report
  (``PartitionReader.caught_up()``): ``False`` means the source KNOWS
  more data is already at the broker, so the partition must never be
  idle-excluded even while a fetch/decode is in flight (the soak-found
  hole behind SOAK_KAFKA's short first window: a partition mid-way
  through a large catch-up fetch looked idle to every consumer-side
  clock).  ``None`` (reader has no backlog knowledge) falls back to the
  wall-clock judgment.

Supervision: a worker whose reader dies with a transient error
(``SourceError``/``StateError``) does not kill the query.  The supervisor
restarts it with exponential backoff + jitter, rebuilding the reader via
the source's per-partition factory and seeking it to the snapshot of the
LAST batch this worker successfully ENQUEUED — everything at or before
that offset is already in the ready queue or consumed, everything after
it was lost with the crash and gets re-read, so a restart can neither
replay rows the consumer saw nor drop rows it never will (the same
offset-snapshot contract checkpoint restore uses).  A bounded restart
budget (per-worker and pump-global) escalates to a structured
:class:`PrefetchRestartExhausted` carrying partition, attempt count, and
last error; restart counts surface in ``SourceExec.metrics()`` and each
restart emits a ``tracing.span`` event.
"""

from __future__ import annotations

import queue as queue_mod
import random
import threading
import time
from typing import Callable, Iterator

from denormalized_tpu.common.errors import SourceError, StateError
from denormalized_tpu.runtime.tracing import logger, span
from denormalized_tpu.state.tiering import (
    backpressure_pause as _backpressure_pause,
    pressure_engaged as _pressure_engaged,
)


class PrefetchRestartExhausted(SourceError):
    """A partition's worker failed past its restart budget: the structured
    query failure the supervisor escalates to."""

    def __init__(self, partition: int, attempts: int, last_error):
        super().__init__(
            f"partition {partition}: prefetch worker failed permanently "
            f"after {attempts} restart(s): {last_error}"
        )
        self.partition = partition
        self.attempts = attempts
        self.last_error = last_error


class _RestartBudget:
    """Shared cap on restarts across all of one pump's workers.  Tokens
    are refunded when a worker's restart streak heals (sustained healthy
    operation), so the budget bounds failure RATE, not lifetime count —
    a long-lived stream with occasional healed hiccups must not converge
    to guaranteed death."""

    def __init__(self, n: int):
        self._n = n
        self._cap = n
        self._lock = threading.Lock()

    def take(self) -> bool:
        with self._lock:
            if self._n <= 0:
                return False
            self._n -= 1
            return True

    def refund(self, n: int) -> None:
        with self._lock:
            self._n = min(self._cap, self._n + n)

    def remaining(self) -> int:
        with self._lock:
            return self._n


class PrefetchWorker:
    """One partition's fetch+decode loop on its own thread."""

    def __init__(
        self,
        idx: int,
        reader,
        out_q: queue_mod.Queue,
        done: threading.Event,
        *,
        depth: int = 2,
        read_timeout_s: float = 0.1,
        reader_factory: Callable[[], object] | None = None,
        restart_budget: int = 5,
        global_budget: _RestartBudget | None = None,
        backoff_base_s: float = 0.05,
        backoff_max_s: float = 2.0,
        heal_after_s: float = 60.0,
        source_name: str = "default",
    ) -> None:
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        self.idx = idx
        self.reader = reader
        self._q = out_q
        self._done = done
        self._depth = depth
        self._slots = threading.Semaphore(depth)
        self._read_timeout_s = read_timeout_s
        # -- supervision ---------------------------------------------------
        self._reader_factory = reader_factory
        self._restart_budget = restart_budget
        self._global_budget = global_budget or _RestartBudget(restart_budget)
        self._backoff_base_s = backoff_base_s
        self._backoff_max_s = backoff_max_s
        self._heal_after_s = heal_after_s
        # jitter RNG seeded per partition: restart timing never depends on
        # a shared global RNG another thread may be draining
        self._jitter = random.Random(0x5EED ^ (idx * 7919))
        #: lifetime restart count (observability) — budget decisions use
        #: the CURRENT STREAK, which heals after heal_after_s of crash-
        #: free operation (with the global tokens refunded): the budget
        #: bounds systemic failure, not total uptime
        self.restarts = 0
        self._streak = 0
        self._restart_wall = 0.0
        self.last_error: str | None = None
        self.backoff_total_s = 0.0
        #: offset snapshot of the last batch successfully ENQUEUED — the
        #: rebuild-on-restart seek point (everything <= it is in the queue
        #: or consumed; everything past it died with the old reader)
        self._last_snap: dict | None = None
        #: decode-fallback rows accumulated by readers this worker has
        #: RETIRED across restarts — the replacement reader's counter
        #: starts at 0, and the perf-cliff metric must not reset with it.
        #: Folded under _swap_lock so a metrics read can never observe
        #: the count doubled or dropped mid-swap.
        self.retired_decode_fallback_rows = 0
        self.retired_salvaged_rows = 0
        self._swap_lock = threading.Lock()
        # single-writer activity slots (worker writes enq_*, consumer
        # writes deq_) — see module docstring
        self.enq_rowful = 0
        self.deq_rowful = 0
        self.enq_wall = time.monotonic()
        self.first_read_done = False
        self.caught_up: bool | None = None
        self.finished = False
        self._thread: threading.Thread | None = None
        # registry instruments: queue depth (enq - deq rowful batches; at
        # the depth limit the worker is backpressure-blocked in
        # _acquire_slot) and the supervised-restart counter.  The gauge
        # value is a single store, so the worker (enqueue) and consumer
        # (dequeue) updating it without a lock can only be one batch
        # stale, never torn.  Labels carry the SOURCE too: a join runs
        # two pumps whose partition indexes collide, and sharing a
        # series across them would break the single-writer contract.
        from denormalized_tpu import obs

        # captured binding: instruments bound FROM THE WORKER THREAD
        # (a supervised rebuild constructing a fresh kafka reader binds
        # its consumer-lag gauge there) must land in the same query-
        # scoped registry this pump was built under
        self._obs_reg = obs.current_registry()
        self._obs_depth = obs.gauge(
            "dnz_prefetch_queue_depth",
            source=source_name, partition=str(idx),
        )
        self._obs_restarts = obs.counter(
            "dnz_prefetch_restarts_total",
            source=source_name, partition=str(idx),
        )
        # handoff dwell: observed by the CONSUMER at dequeue (see
        # PrefetchPump._strip) from the enqueue stamp riding each item —
        # the doctor's "is the consumer thread the bottleneck" signal
        self._obs_dwell = obs.histogram(
            "dnz_prefetch_queue_dwell_ms",
            source=source_name, partition=str(idx),
        )

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run,
            daemon=True,
            name=f"prefetch-{self.idx}",
        )
        self._thread.start()

    def join(self, timeout: float | None = None) -> None:
        if self._thread is not None:
            self._thread.join(timeout)

    # -- consumer side ----------------------------------------------------
    def consumed(self, rowful: bool) -> None:
        """Release the item's buffer slot AFTER downstream processed it —
        the slot is the backpressure unit, so it must cover the full
        consume, not just the dequeue."""
        if rowful:
            self.deq_rowful += 1
            self._obs_depth.set(self.enq_rowful - self.deq_rowful)
        self._slots.release()

    def activity(self) -> tuple[bool, float, bool, bool]:
        """(pending, last_rowful_enqueue_wall, first_read_done,
        may_judge_idle) for the partition-watermark tracker."""
        return (
            self.enq_rowful > self.deq_rowful,
            self.enq_wall,
            self.first_read_done,
            self.caught_up is not False,
        )

    def reader_quiet(self) -> bool:
        """True when the READER side shows no sign of data in flight:
        first read returned, nothing enqueued-but-unconsumed, and the
        reader does not report known backlog.  A finished partition is
        quiet permanently."""
        if self.finished:
            return True
        return (
            self.first_read_done
            and self.enq_rowful <= self.deq_rowful
            and self.caught_up is not False
        )

    # -- worker side ------------------------------------------------------
    def _acquire_slot(self) -> bool:
        while not self._done.is_set():
            if self._slots.acquire(timeout=0.1):
                return True
        return False

    def _restartable(self, err: BaseException) -> bool:
        """Transient engine errors restart; anything else (programming
        errors, interpreter shutdown) surfaces to the consumer verbatim.
        Without a factory there is nothing to rebuild from."""
        return (
            self._reader_factory is not None
            and isinstance(err, (SourceError, StateError))
        )

    def _rebuild_reader(self) -> None:
        new = self._reader_factory()
        if self._last_snap is not None:
            new.offset_restore(self._last_snap)
        # dnzlint: allow(unguarded) single-writer field: only the supervisor thread (this method's caller) ever rebinds self.reader; _swap_lock exists to keep the metric fold + swap glitch-free for concurrent *_total() readers
        old = self.reader
        with self._swap_lock:
            # fold + swap atomically w.r.t. decode_fallback_total(): no
            # ordering of the two writes alone is glitch-free (one gives
            # a transient drop, the other a transient double count)
            fallback = getattr(old, "decode_fallback_rows", None)
            if callable(fallback):
                try:
                    self.retired_decode_fallback_rows += int(fallback())
                except Exception:  # dnzlint: allow(broad-except) best-effort metrics fold off a CRASHED reader — its counter is worth carrying over, never worth failing the restart for
                    pass
            # same carry for salvage-skipped rows: a restart must not
            # RESET the silent-data-loss counter
            self.retired_salvaged_rows += int(
                getattr(old, "salvaged_rows", 0) or 0
            )
            self.reader = new
        # caught_up stays False (set when the crash was detected) until
        # the rebuilt reader's first fetch reports real backlog state
        close = getattr(old, "close", None)
        if callable(close):
            # free the crashed reader's native client now, not at GC —
            # a flapping partition would otherwise hold one dead broker
            # connection per restart
            try:
                close()
            except Exception:  # dnzlint: allow(broad-except) best-effort release of a connection that already died — the crash error, not the close error, is the story
                pass

    def decode_fallback_total(self) -> int:
        """Current + retired decode-fallback rows, glitch-free across a
        supervised reader swap."""
        with self._swap_lock:
            return (
                self.reader.decode_fallback_rows()
                + self.retired_decode_fallback_rows
            )

    def salvaged_total(self) -> int:
        """Current + retired salvage-skipped (undecodable, dropped)
        rows, glitch-free across a supervised reader swap."""
        with self._swap_lock:
            return (
                int(getattr(self.reader, "salvaged_rows", 0) or 0)
                + self.retired_salvaged_rows
            )

    def _run(self) -> None:
        # the end-of-stream sentinel is the consumer's ONLY liveness
        # signal from this worker: it must be guaranteed by the
        # outermost frame, so nothing that runs before the supervised
        # loop (the registry re-entry below, a failed import) can kill
        # the thread sentinel-less and wedge the consumer in get()
        try:
            from denormalized_tpu import obs

            with obs.bound_registry(self._obs_reg):
                self._run_supervised()
        finally:
            self.finished = True
            self._q.put((self.idx, None, None, 0.0))

    def _run_supervised(self) -> None:
        err: BaseException | None = None
        while True:
            if err is not None:
                if self._done.is_set():
                    return  # shutting down: swallow, nobody is reading
                if not self._restartable(err):
                    self._q.put(err)  # surfaced by the consumer
                    return
                if (
                    self._streak >= self._restart_budget
                    or not self._global_budget.take()
                ):
                    self._q.put(PrefetchRestartExhausted(
                        self.idx, self.restarts, err
                    ))
                    return
                self.restarts += 1
                self._obs_restarts.add(1)
                self._streak += 1
                self._restart_wall = time.monotonic()
                # jitter INSIDE the clamp: backoff_max_s is a hard cap
                # a caller can tune against watermark/idle timeouts
                delay = min(
                    self._backoff_max_s,
                    self._backoff_base_s * (2 ** (self._streak - 1))
                    * (1.0 + 0.25 * self._jitter.random()),
                )
                self.backoff_total_s += delay
                logger.warning(
                    "prefetch worker %d: %s — restart %d/%d in %.2fs "
                    "(resume from %s)",
                    self.idx, err, self._streak, self._restart_budget,
                    delay, self._last_snap,
                )
                if self._done.wait(delay):
                    return
                err = None
                try:
                    with span(
                        "prefetch.restart",
                        partition=self.idx, attempt=self.restarts,
                    ):
                        self._rebuild_reader()
                except BaseException as e:  # dnzlint: allow(broad-except) not swallowed — the supervisor re-dispatches: restartable errors re-enter the budgeted backoff, the rest surface via the queue on the next loop pass
                    # rebuild failed (e.g. broker still down): another
                    # crash — loops back into the budgeted backoff
                    err = e
                    self.last_error = f"{type(e).__name__}: {e}"
                    continue
            try:
                self._run_reader()
                return  # clean EOS (or shutdown)
            except BaseException as e:  # dnzlint: allow(broad-except) not swallowed — the supervisor loop classifies err: non-restartable errors are enqueued for the consumer to re-raise, restartable ones restart
                err = e
                self.last_error = f"{type(e).__name__}: {e}"
                # rows past _last_snap died with the reader and WILL
                # be re-read: the partition must read as known-backlog
                # (never idle-judgeable) for the whole backoff/rebuild
                # window, or the watermark advances over the lost rows
                # and the re-read arrives "late" — silent loss by the
                # very mechanism meant to prevent it
                self.caught_up = False

    def _run_reader(self) -> None:
        # dnzlint: allow(unguarded) single-writer field: the supervisor thread running this loop is the only writer of self.reader (rebound in _rebuild_reader between _run_reader calls, never during one)
        reader = self.reader
        probe = getattr(reader, "caught_up", None)
        if not callable(probe):
            probe = None
        if self._last_snap is None:
            self._last_snap = reader.offset_snapshot()
        while not self._done.is_set():
            if self._streak and (
                time.monotonic() - self._restart_wall >= self._heal_after_s
            ):
                # crash-free for the heal interval: the streak resets and
                # its global tokens come back — the next independent
                # hiccup gets a full budget instead of inheriting debt
                # from hours-old healed failures
                self._global_budget.refund(self._streak)
                self._streak = 0
            if _pressure_engaged():
                # end-of-line backpressure from the state tier: spill
                # could not keep accounted state under the hard ceiling,
                # so the PUMP slows down — one bounded pause per read (a
                # throttle, never a halt: rows must keep trickling or the
                # watermark stalls and the pressure can never clear).
                # Broker-side backlog absorbs what we stop fetching.
                _backpressure_pause()
            b = reader.read(timeout_s=self._read_timeout_s)
            self.first_read_done = True
            if b is None:
                return  # partition exhausted (or reader died cleanly)
            if probe is not None:
                cu = probe()
                if cu is not None or self.caught_up is not False:
                    # a None probe result (no fetch yet / mid-reconnect)
                    # must NOT release a crash-time known-backlog pin —
                    # only REAL backlog knowledge may
                    self.caught_up = cu
            elif self.caught_up is False and b.num_rows:
                # probe-less reader delivered rows again: the crash-time
                # pin is served (the re-read reached the consumer path);
                # fall back to wall-clock idleness judgment
                self.caught_up = None
            if b.num_rows:
                # stamp BEFORE the (possibly blocking) slot acquire:
                # while waiting for the consumer the partition has
                # pending work and must read as active
                self.enq_wall = time.monotonic()
                self.enq_rowful += 1
                self._obs_depth.set(self.enq_rowful - self.deq_rowful)
            snap = reader.offset_snapshot()
            if not self._acquire_slot():
                return  # shutdown won
            # the enqueue stamp rides the item: the consumer observes
            # queue dwell (enqueue → dequeue) at _strip time
            self._q.put((self.idx, snap, b, time.perf_counter()))
            self._last_snap = snap


class PrefetchPump:
    """N prefetch workers merged into one ready queue."""

    def __init__(
        self,
        readers,
        *,
        queue_budget: int = 64,
        depth: int | None = None,
        read_timeout_s: float = 0.1,
        reader_factories: list | None = None,
        restart_budget: int = 5,
        global_restart_budget: int | None = None,
        restart_heal_s: float = 60.0,
        source_name: str = "default",
    ) -> None:
        if depth is None:
            # split the aggregate budget across partitions; never below a
            # double buffer, never absurdly deep (in-flight batches widen
            # the watermark skew the consumer must reconcile)
            depth = max(2, min(16, queue_budget // max(1, len(readers))))
        self._q: queue_mod.Queue = queue_mod.Queue()
        self._done = threading.Event()
        if global_restart_budget is None:
            # generous enough for independent per-partition hiccups, small
            # enough that a systemic failure (broker gone for good) cannot
            # retry forever across N partitions
            global_restart_budget = max(8, 2 * len(readers))
        self._global_budget = _RestartBudget(global_restart_budget)
        # None (the documented sentinel) disables supervision; an empty
        # LIST from a buggy partition_factories() must hit the length
        # guard below, not silently disable restarts for every partition
        factories = (
            [None] * len(readers) if reader_factories is None
            else reader_factories
        )
        if len(factories) != len(readers):
            raise ValueError(
                f"{len(factories)} reader factories for "
                f"{len(readers)} readers"
            )
        self.workers = [
            PrefetchWorker(
                i, r, self._q, self._done,
                depth=depth, read_timeout_s=read_timeout_s,
                reader_factory=factories[i],
                restart_budget=restart_budget,
                global_budget=self._global_budget,
                heal_after_s=restart_heal_s,
                source_name=source_name,
            )
            for i, r in enumerate(readers)
        ]
        self.depth = depth

    def start(self) -> "PrefetchPump":
        for w in self.workers:
            w.start()
        return self

    def stop(self, join_timeout_s: float | None = 5.0) -> list[int]:
        """Shut the pump down for real: signal done, release every
        worker's buffer slots (a worker blocked in ``_acquire_slot`` wakes
        immediately instead of on its next 0.1s poll), join each worker,
        and drain the ready queue so buffered batches/exceptions don't
        outlive the query.  Returns the indexes of stragglers — workers
        still alive after the join timeout (wedged in a native call) —
        after logging them."""
        self._done.set()
        for w in self.workers:
            # over-releasing is harmless: the done flag gates the loop
            w._slots.release(w._depth)
        deadline = (
            None if join_timeout_s is None
            else time.monotonic() + join_timeout_s
        )
        stragglers = []
        for w in self.workers:
            t = (
                None if deadline is None
                else max(0.0, deadline - time.monotonic())
            )
            w.join(t)
            if w._thread is not None and w._thread.is_alive():
                stragglers.append(w.idx)
        try:
            while True:
                self._q.get_nowait()
        except queue_mod.Empty:
            pass
        if stragglers:
            logger.warning(
                "prefetch stop: %d worker(s) still alive after %.1fs "
                "join timeout: %s",
                len(stragglers), join_timeout_s or 0.0, stragglers,
            )
        return stragglers

    def restart_stats(self) -> dict:
        """Supervisor observability, aggregated into SourceExec.metrics()."""
        per = {w.idx: w.restarts for w in self.workers if w.restarts}
        return {
            "restarts": sum(per.values()),
            "restarted_partitions": len(per),
            "per_partition": per,
            "last_errors": {
                w.idx: w.last_error
                for w in self.workers if w.last_error
            },
            "global_budget_remaining": self._global_budget.remaining(),
        }

    def _strip(self, item):
        """Normalize a queue item for consumers: observe the handoff
        dwell (enqueue stamp → now) for rowful batches and strip the
        stamp, so every caller keeps seeing ``(idx, snap, batch)``.
        Exceptions and legacy 3-tuples (tests enqueue them directly)
        pass through untouched."""
        if isinstance(item, tuple) and len(item) == 4:
            idx, snap, b, t_enq = item
            if b is not None and b.num_rows and t_enq:
                w = self.workers[idx]
                if w._obs_dwell:
                    w._obs_dwell.observe(
                        (time.perf_counter() - t_enq) * 1e3
                    )
            return idx, snap, b
        return item

    def get(self):
        return self._strip(self._q.get())

    def get_live(self, timeout_s: float = 30.0):
        """Blocking get with a liveness backstop.  A live worker
        guarantees an item at least every read-timeout (even a quiet
        topic enqueues empty heartbeats), so a queue starved past
        ``timeout_s`` while some worker thread has DIED without its
        end-of-stream sentinel can never heal — raise a structured
        SourceError naming the partitions instead of blocking the
        consumer forever.  Workers that are alive but slow (a 30s
        native-recv stall against a sick broker) just log and keep
        waiting."""
        while True:
            try:
                return self._strip(self._q.get(timeout=timeout_s))
            except queue_mod.Empty:
                dead = [
                    w.idx for w in self.workers
                    if not w.finished
                    and w._thread is not None
                    and not w._thread.is_alive()
                ]
                if dead:
                    raise SourceError(
                        f"prefetch worker(s) {dead} died without an "
                        f"end-of-stream sentinel (ready queue starved "
                        f"for {timeout_s:.0f}s)"
                    ) from None
                logger.warning(
                    "prefetch ready queue starved for %.0fs — still "
                    "waiting on live worker(s) for partition(s) %s",
                    timeout_s,
                    [w.idx for w in self.workers if not w.finished],
                )

    def consumed(self, idx: int, rowful: bool) -> None:
        self.workers[idx].consumed(rowful)

    def activity(self, idx: int) -> tuple[bool, float, bool, bool]:
        return self.workers[idx].activity()

    def quiet(self) -> bool:
        """True when EVERY partition is reader-side quiet — the gate for
        the source-level idle hint, so a consumer stall (compile, GC)
        followed by an empty heartbeat can never declare idleness over
        rows that are already fetched or known to be at the broker."""
        return all(w.reader_quiet() for w in self.workers)

    def drain(
        self,
        total_rows: int | None = None,
        deadline: float | None = None,
    ) -> Iterator:
        """Utility consumer loop (bench / tests): yield (idx, snap,
        batch) for every rowful batch, releasing slots as it goes, until
        ``total_rows`` rows were seen or every worker finished.  Raises
        the first worker exception; raises TimeoutError once
        ``time.monotonic()`` passes ``deadline`` — checked on every
        dequeued item (empty heartbeats included) AND while waiting, so
        a wedged stream fails visibly instead of blocking forever."""
        finished = 0
        seen = 0
        n = len(self.workers)
        while finished < n:
            if deadline is None:
                item = self.get()
            else:
                while True:
                    if time.monotonic() > deadline:
                        raise TimeoutError(
                            f"prefetch drain stalled at {seen} rows"
                        )
                    try:
                        item = self._strip(self._q.get(timeout=1.0))
                        break
                    except queue_mod.Empty:
                        continue
            if isinstance(item, BaseException):
                raise item
            idx, snap, batch = item
            if batch is None:
                finished += 1
                continue
            rowful = bool(batch.num_rows)
            try:
                if rowful:
                    seen += batch.num_rows
                    yield idx, snap, batch
            finally:
                self.consumed(idx, rowful)
            if total_rows is not None and seen >= total_rows:
                return
