"""In-process mock Kafka broker.

The reference's de-facto integration test is running examples against a
Kafka docker image (SURVEY.md §4) — no broker, no test.  This embedded
broker speaks the exact wire subset the native client uses (Metadata v1,
ListOffsets v1, Produce v3, Fetch v4, magic-2 record batches) over a real
TCP socket, so Kafka sources/sinks get true end-to-end coverage (framing,
CRC32C batches, offset semantics) hermetically.

Also usable outside tests as a lightweight local topic bus.
"""

from __future__ import annotations

import bisect
import socket
import struct
import threading
import time


def _zz_enc(n: int) -> bytes:
    z = ((n << 1) ^ (n >> 63)) & ((1 << 70) - 1)
    out = bytearray()
    while z >= 0x80:
        out.append((z & 0x7F) | 0x80)
        z >>= 7
    out.append(z)
    return bytes(out)


def _zz_dec(buf: memoryview, pos: int) -> tuple[int, int]:
    acc = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        acc |= (b & 0x7F) << shift
        if not (b & 0x80):
            break
        shift += 7
    return (acc >> 1) ^ -(acc & 1), pos


_CRC32C_TABLE = []


def _crc32c(data: bytes) -> int:
    global _CRC32C_TABLE
    if not _CRC32C_TABLE:
        t = []
        for i in range(256):
            c = i
            for _ in range(8):
                c = (0x82F63B78 ^ (c >> 1)) if c & 1 else c >> 1
            t.append(c)
        _CRC32C_TABLE = t
    c = 0xFFFFFFFF
    for b in data:
        c = _CRC32C_TABLE[(c ^ b) & 0xFF] ^ (c >> 8)
    return c ^ 0xFFFFFFFF


def encode_records(records: list[tuple[int, bytes]]) -> bytes:
    """The uncompressed records section of a magic-2 batch — exposed so
    codec tests can craft hand-compressed variants of a known section."""
    first_ts = records[0][0] if records else 0
    recs = bytearray()
    for i, (ts, payload) in enumerate(records):
        rec = bytearray()
        rec += b"\x00"  # attributes
        rec += _zz_enc(ts - first_ts)
        rec += _zz_enc(i)
        rec += _zz_enc(-1)  # null key
        rec += _zz_enc(len(payload))
        rec += payload
        rec += _zz_enc(0)  # headers
        recs += _zz_enc(len(rec))
        recs += rec
    return bytes(recs)


def snappy_compress(data: bytes) -> bytes:
    """Minimal raw-snappy encoder: uvarint length + literal elements only
    (valid snappy — real encoders add copy elements, which the decoder
    tests exercise with hand-crafted streams)."""
    out = bytearray()
    n = len(data)
    while True:  # uvarint uncompressed length
        b = n & 0x7F
        n >>= 7
        out.append(b | (0x80 if n else 0))
        if not n:
            break
    pos = 0
    while pos < len(data):
        chunk = data[pos : pos + 60]
        out.append((len(chunk) - 1) << 2)  # literal, length ≤ 60 inline
        out += chunk
        pos += len(chunk)
    return bytes(out)


def xerial_snappy_compress(data: bytes) -> bytes:
    """Legacy Java-producer framing: magic header + [len BE][raw block]*."""
    block = snappy_compress(data)
    return (
        b"\x82SNAPPY\x00"
        + struct.pack(">ii", 1, 1)
        + struct.pack(">i", len(block))
        + block
    )


def lz4_frame_compress(data: bytes) -> bytes:
    """Minimal LZ4 frame: v1 header, literal-only compressed blocks, EndMark.
    Valid LZ4 (all-literals sequences), no xxhash checksums."""
    out = bytearray()
    out += struct.pack("<I", 0x184D2204)  # magic
    out += bytes([0x40, 0x40, 0x00])  # FLG(v1), BD(64KB), header checksum*
    # *our decoder (and this encoder's consumers) skip the HC byte
    pos = 0
    while pos < len(data):
        lit = data[pos : pos + 65536 - 16]
        pos += len(lit)
        block = bytearray()
        llen = len(lit)
        token_lit = min(llen, 15)
        block.append(token_lit << 4)
        if token_lit == 15:
            rest = llen - 15
            while rest >= 255:
                block.append(255)
                rest -= 255
            block.append(rest)
        block += lit
        out += struct.pack("<I", len(block))
        out += block
    out += struct.pack("<I", 0)  # EndMark
    return bytes(out)


def _zstd_compress(data: bytes) -> bytes:
    import zstandard  # optional: only needed when a test produces codec=4

    return zstandard.ZstdCompressor().compress(data)


# Kafka compression attribute values → encoder
_CODEC_COMPRESS = {
    1: lambda d: __import__("gzip").compress(d),
    2: snappy_compress,
    3: lz4_frame_compress,
    4: _zstd_compress,
}


def build_record_batch(
    base_offset: int,
    records: list[tuple[int, bytes]],
    compute_crc: bool = True,
    gzip_codec: bool = False,
    codec: int = 0,
    compressed_records: bytes | None = None,
) -> bytes:
    """magic-2 batch from [(timestamp_ms, payload)].

    ``compute_crc=False`` writes a zero CRC — the embedded broker serves
    high-volume benchmark fetches this way (our native client, like the
    brokers themselves on read, trusts the TCP transport); codec tests use
    the real CRC32C.  ``codec`` is the Kafka compression attribute
    (0=none 1=gzip 2=snappy 3=lz4 4=zstd); ``gzip_codec=True`` is the
    legacy alias for codec=1.  ``compressed_records`` overrides the records
    section verbatim (for hand-crafted compressed streams)."""
    if gzip_codec:
        codec = 1
    first_ts = records[0][0] if records else 0
    recs = bytearray(encode_records(records))
    if compressed_records is not None:
        recs = bytearray(compressed_records)
    elif codec:
        recs = bytearray(_CODEC_COMPRESS[codec](bytes(recs)))
    max_ts = max((ts for ts, _ in records), default=0)
    body = bytearray()
    body += struct.pack(
        ">hiqqqhii", codec, len(records) - 1, first_ts,
        max_ts, -1, -1, -1, len(records),
    )
    body += recs
    crc = _crc32c(bytes(body)) if compute_crc else 0
    out = bytearray()
    out += struct.pack(">qiib", base_offset, len(body) + 9, -1, 2)
    out += struct.pack(">I", crc)
    out += body
    return bytes(out)


def parse_record_batches(blob: bytes) -> list[tuple[int, bytes]]:
    """magic-2 batches → [(timestamp_ms, payload)]."""
    out = []
    mv = memoryview(blob)
    pos = 0
    while pos + 61 <= len(blob):
        base_offset, batch_len, _leader_epoch, magic = struct.unpack_from(
            ">qiib", mv, pos
        )
        batch_end = pos + 12 + batch_len
        p = pos + 21  # past crc
        if magic != 2:
            pos = batch_end
            continue
        (_attrs, _lod, first_ts, _max_ts, _pid, _pep, _bseq, nrec) = (
            struct.unpack_from(">hiqqqhii", mv, p)
        )
        p += 40
        for _ in range(nrec):
            rec_len, p = _zz_dec(mv, p)
            rec_end = p + rec_len
            p += 1  # attributes
            ts_delta, p = _zz_dec(mv, p)
            _off_delta, p = _zz_dec(mv, p)
            klen, p = _zz_dec(mv, p)
            if klen > 0:
                p += klen
            vlen, p = _zz_dec(mv, p)
            payload = bytes(mv[p : p + vlen]) if vlen > 0 else b""
            out.append((first_ts + ts_delta, payload))
            p = rec_end
        pos = batch_end
    return out


class MockKafkaBroker:
    """TCP server; topics are created on first produce or via create_topic.

    ``tls_context`` (a server-side ``ssl.SSLContext``) wraps every accepted
    connection — the listener side of security.protocol=SSL/SASL_SSL.
    ``sasl_plain`` ({username: password}) makes the broker REQUIRE a
    SaslHandshake v1 + SaslAuthenticate PLAIN exchange before serving any
    data API; unauthenticated requests drop the connection, like a real
    broker's sasl listener."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        tls_context=None,
        sasl_plain: dict | None = None,
    ):
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(16)
        self._tls_context = tls_context
        self._sasl_plain = sasl_plain
        self.host, self.port = self._sock.getsockname()
        # (topic, partition) -> list[(offset, ts, payload)]
        self._logs: dict[tuple[str, int], list] = {}
        # batch-head blob index per partition: (head_offset, enc) for every
        # non-empty pre-encoded record batch, so _fetch slices by bisect
        # instead of walking the log (O(log n) vs O(n) per fetch)
        self._blobs: dict[tuple[str, int], list] = {}
        self._npartitions: dict[str, int] = {}
        # per-(topic, partition) artificial fetch latency (seconds),
        # applied before serving a Fetch that covers the partition — lets
        # tests stagger partition service times deterministically (each
        # client connection has its own serve thread, so delaying one
        # partition's consumer never slows the others)
        self.fetch_delay_s: dict[tuple[str, int], float] = {}
        # test knob: serve at most this many bytes per fetch regardless
        # of the client's max_bytes — small fetches on demand (the shape
        # a slow link or a tiny-batch producer creates), for exercising
        # fetch coalescing deterministically
        self.fetch_max_bytes_clamp: int | None = None
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._conns: list[socket.socket] = []
        self.requests_served = 0

    @property
    def bootstrap(self) -> str:
        return f"{self.host}:{self.port}"

    def create_topic(self, name: str, partitions: int = 1) -> None:
        with self._lock:
            self._npartitions[name] = partitions
            for p in range(partitions):
                self._logs.setdefault((name, p), [])

    def produce(
        self, topic: str, partition: int, payloads, ts_ms=None,
        gzip_codec: bool = False, codec: int = 0,
        compressed_records: bytes | None = None,
    ):
        """Direct (no-wire) produce, handy for tests.  ``codec`` stores
        compressed batches (clients must decompress on fetch);
        ``compressed_records`` plants a verbatim records section (paired
        with the single payload expected to decode from it)."""
        ts = ts_ms if ts_ms is not None else int(time.time() * 1000)
        with self._lock:
            self._npartitions.setdefault(topic, max(partition + 1, 1))
            log = self._logs.setdefault((topic, partition), [])
            blobs = self._blobs.setdefault((topic, partition), [])
            for p in payloads:
                o = len(log)
                enc = build_record_batch(
                    o, [(ts, p)], compute_crc=False, gzip_codec=gzip_codec,
                    codec=codec, compressed_records=compressed_records,
                )
                log.append((o, ts, p, enc))
                blobs.append((o, enc))

    def produce_batched(
        self, topic: str, partition: int, payloads, ts_ms=None,
        records_per_batch: int = 512,
    ):
        """Produce MULTI-record batches (the wire shape real producers /
        librdkafka send): one encoded record batch per ``records_per_batch``
        payloads instead of one per payload — ~3× less framing overhead on
        fetch, and the realistic decode path for throughput benchmarks.

        Follower offsets store an empty ``enc`` (their bytes live in the
        head entry); the fetch path backs up to the batch head when a
        requested offset lands mid-batch — clients skip records below the
        fetch offset, as the protocol requires."""
        ts = ts_ms if ts_ms is not None else int(time.time() * 1000)
        with self._lock:
            self._npartitions.setdefault(topic, max(partition + 1, 1))
            log = self._logs.setdefault((topic, partition), [])
            blobs = self._blobs.setdefault((topic, partition), [])
            i = 0
            n = len(payloads)
            while i < n:
                chunk = payloads[i : i + records_per_batch]
                o = len(log)
                enc = build_record_batch(
                    o, [(ts, p) for p in chunk], compute_crc=False
                )
                log.append((o, ts, chunk[0], enc))
                blobs.append((o, enc))
                for j in range(1, len(chunk)):
                    log.append((o + j, ts, chunk[j], b""))
                i += len(chunk)

    @staticmethod
    def stage_batched(
        payloads, ts_ms: int, records_per_batch: int = 512,
        base_offset: int = 0,
    ) -> list:
        """Pre-encode log entries (batched, like produce_batched) WITHOUT
        appending them — for paced producers whose feed loop must not pay
        Python encode costs.  Append slices later with append_staged; the
        partition log must be empty (or exactly base_offset long) when the
        first slice lands."""
        entries = []
        i = 0
        n = len(payloads)
        while i < n:
            chunk = payloads[i : i + records_per_batch]
            o = base_offset + i
            enc = build_record_batch(
                o, [(ts_ms, p) for p in chunk], compute_crc=False
            )
            entries.append((o, ts_ms, chunk[0], enc))
            for j in range(1, len(chunk)):
                entries.append((o + j, ts_ms, chunk[j], b""))
            i += len(chunk)
        return entries

    def append_staged(self, topic: str, partition: int, entries) -> None:
        with self._lock:
            self._npartitions.setdefault(topic, max(partition + 1, 1))
            log = self._logs.setdefault((topic, partition), [])
            expect = len(log)
            if entries and entries[0][0] != expect:
                raise ValueError(
                    f"staged entries start at offset {entries[0][0]}, "
                    f"log is at {expect}"
                )
            log.extend(entries)
            blobs = self._blobs.setdefault((topic, partition), [])
            blobs.extend((o, enc) for o, _ts, _pl, enc in entries if enc)

    @staticmethod
    def _pre_encode(offset: int, ts: int, payload: bytes) -> bytes:
        """Encode each record as its own single-record batch at produce
        time, so fetches are a byte-join instead of per-fetch re-encoding
        (brokers serve stored batches verbatim too)."""
        return build_record_batch(offset, [(ts, payload)], compute_crc=False)

    def log(self, topic: str, partition: int = 0):
        with self._lock:
            return [
                (o, ts, p)
                for (o, ts, p, _enc) in self._logs.get((topic, partition), [])
            ]

    # -- server loop -----------------------------------------------------
    def start(self) -> "MockKafkaBroker":
        t = threading.Thread(target=self._accept_loop, daemon=True)
        t.start()
        self._threads.append(t)
        return self

    def stop(self) -> None:
        self._stop.set()
        # shutdown BEFORE close: close() alone does not unblock a thread
        # parked inside accept(), and the in-flight syscall would keep the
        # kernel listen socket alive (port stays bound forever)
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        # also close per-connection sockets: serve threads block in recv and
        # their ESTABLISHED sockets would keep the local port bound,
        # preventing a restart on the same port
        with self._lock:
            conns, self._conns = self._conns, []
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass

    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            with self._lock:
                self._conns.append(conn)
            t = threading.Thread(target=self._serve, args=(conn,), daemon=True)
            t.start()
            self._threads.append(t)

    def _serve(self, conn: socket.socket):
        # OSError (Bad file descriptor / ECONNRESET) is the normal outcome
        # when stop() shuts the socket down under a blocked recv/sendall —
        # treat it as end-of-connection, not a thread crash
        try:
            if self._tls_context is not None:
                # a plaintext client against the TLS listener fails the
                # handshake here — connection drops, like a real broker
                conn = self._tls_context.wrap_socket(conn, server_side=True)
            # per-connection auth state (real brokers authenticate each
            # connection independently)
            authed = self._sasl_plain is None
            while not self._stop.is_set():
                hdr = self._recv_all(conn, 4)
                if hdr is None:
                    return
                (size,) = struct.unpack(">i", hdr)
                body = self._recv_all(conn, size)
                if body is None:
                    return
                resp, authed = self._handle(body, authed)
                if resp is None:
                    return  # protocol violation (e.g. unauthed data API)
                conn.sendall(struct.pack(">i", len(resp)) + resp)
                self.requests_served += 1
        except OSError:
            return
        except Exception:  # dnzlint: allow(broad-except) test broker: ssl.SSLError on a failed handshake (and kin) ends the connection, exactly like a real broker dropping a bad client
            # ssl.SSLError on a failed handshake ends the connection too
            return
        finally:
            try:
                conn.close()
            except OSError:
                pass

    @staticmethod
    def _recv_all(conn, n):
        buf = b""
        while len(buf) < n:
            chunk = conn.recv(n - len(buf))
            if not chunk:
                return None
            buf += chunk
        return buf

    # -- request dispatch ------------------------------------------------
    def _handle(self, body: bytes, authed: bool) -> tuple[bytes | None, bool]:
        api_key, api_version, corr = struct.unpack_from(">hhi", body, 0)
        pos = 8
        (client_len,) = struct.unpack_from(">h", body, pos)
        pos += 2 + max(client_len, 0)
        payload = body[pos:]
        out = struct.pack(">i", corr)
        if api_key == 17:  # SaslHandshake v1
            resp, authed = self._sasl_handshake(payload)
            return out + resp, authed
        if api_key == 36:  # SaslAuthenticate v0
            resp, authed = self._sasl_authenticate(payload)
            return out + resp, authed
        if not authed:
            # data API before authentication: drop the connection (real
            # sasl listeners treat this as an illegal state)
            return None, authed
        if api_key == 3:
            out += self._metadata(payload, api_version)
        elif api_key == 2:
            out += self._list_offsets(payload)
        elif api_key == 0:
            out += self._produce(payload)
        elif api_key == 1:
            out += self._fetch(payload)
        else:
            out += struct.pack(">h", 35)  # UNSUPPORTED_VERSION
        return out, authed

    def _sasl_handshake(self, payload: bytes) -> tuple[bytes, bool]:
        (ln,) = struct.unpack_from(">h", payload, 0)
        mech = payload[2 : 2 + ln].decode()
        if self._sasl_plain is None or mech != "PLAIN":
            # 33 = UNSUPPORTED_SASL_MECHANISM, advertise what we speak
            out = struct.pack(">h", 33) + struct.pack(">i", 1)
            m = b"PLAIN"
            out += struct.pack(">h", len(m)) + m
            return out, False
        return struct.pack(">hi", 0, 1) + struct.pack(">h", 5) + b"PLAIN", (
            False  # handshake ok, but authentication is the next step
        )

    def _sasl_authenticate(self, payload: bytes) -> tuple[bytes, bool]:
        (blen,) = struct.unpack_from(">i", payload, 0)
        token = payload[4 : 4 + max(blen, 0)]
        parts = token.split(b"\x00")
        ok = False
        if self._sasl_plain is not None and len(parts) == 3:
            user = parts[1].decode()
            ok = self._sasl_plain.get(user) == parts[2].decode()
        if not ok:
            msg = b"Authentication failed: Invalid username or password"
            # 58 = SASL_AUTHENTICATION_FAILED
            return (
                struct.pack(">h", 58)
                + struct.pack(">h", len(msg)) + msg
                + struct.pack(">i", 0),
                False,
            )
        return struct.pack(">h", 0) + struct.pack(">h", -1) + struct.pack(
            ">i", 0
        ), True

    def _metadata(self, payload: bytes, version: int) -> bytes:
        (ntopics,) = struct.unpack_from(">i", payload, 0)
        pos = 4
        names = []
        for _ in range(max(ntopics, 0)):
            (ln,) = struct.unpack_from(">h", payload, pos)
            pos += 2
            names.append(payload[pos : pos + ln].decode())
            pos += ln
        with self._lock:
            if ntopics <= 0:
                names = list(self._npartitions)
            out = bytearray()
            # brokers
            out += struct.pack(">i", 1)
            out += struct.pack(">i", 0)  # node id
            host = self.host.encode()
            out += struct.pack(">h", len(host)) + host
            out += struct.pack(">i", self.port)
            out += struct.pack(">h", -1)  # rack null
            out += struct.pack(">i", 0)  # controller
            out += struct.pack(">i", len(names))
            for name in names:
                nparts = self._npartitions.get(name)
                err = 0 if nparts else 3  # UNKNOWN_TOPIC_OR_PARTITION
                out += struct.pack(">h", err)
                nb = name.encode()
                out += struct.pack(">h", len(nb)) + nb
                out += struct.pack(">b", 0)  # is_internal
                out += struct.pack(">i", nparts or 0)
                for p in range(nparts or 0):
                    out += struct.pack(">hiii", 0, p, 0, 1)  # err,idx,leader,nreplicas
                    out += struct.pack(">i", 0)  # replica 0
                    out += struct.pack(">i", 1)  # isr count
                    out += struct.pack(">i", 0)
            return bytes(out)

    def _list_offsets(self, payload: bytes) -> bytes:
        pos = 4  # skip replica id
        (ntopics,) = struct.unpack_from(">i", payload, pos)
        pos += 4
        out = bytearray()
        out += struct.pack(">i", ntopics)
        for _ in range(ntopics):
            (ln,) = struct.unpack_from(">h", payload, pos)
            pos += 2
            name = payload[pos : pos + ln].decode()
            pos += ln
            (nparts,) = struct.unpack_from(">i", payload, pos)
            pos += 4
            nb = name.encode()
            out += struct.pack(">h", len(nb)) + nb
            out += struct.pack(">i", nparts)
            for _ in range(nparts):
                part, ts = struct.unpack_from(">iq", payload, pos)
                pos += 12
                with self._lock:
                    log = self._logs.get((name, part), [])
                    if ts == -2:  # earliest
                        off = log[0][0] if log else 0
                    else:  # latest
                        off = (log[-1][0] + 1) if log else 0
                out += struct.pack(">ihqq", part, 0, ts, off)
        return bytes(out)

    def _produce(self, payload: bytes) -> bytes:
        pos = 0
        (tid_len,) = struct.unpack_from(">h", payload, pos)
        pos += 2 + max(tid_len, 0)
        pos += 2 + 4  # acks + timeout
        (ntopics,) = struct.unpack_from(">i", payload, pos)
        pos += 4
        out = bytearray()
        out += struct.pack(">i", ntopics)
        for _ in range(ntopics):
            (ln,) = struct.unpack_from(">h", payload, pos)
            pos += 2
            name = payload[pos : pos + ln].decode()
            pos += ln
            (nparts,) = struct.unpack_from(">i", payload, pos)
            pos += 4
            nb = name.encode()
            out += struct.pack(">h", len(nb)) + nb
            out += struct.pack(">i", nparts)
            for _ in range(nparts):
                (part, blob_len) = struct.unpack_from(">ii", payload, pos)
                pos += 8
                blob = payload[pos : pos + blob_len]
                pos += blob_len
                records = parse_record_batches(blob)
                with self._lock:
                    self._npartitions.setdefault(name, part + 1)
                    self._npartitions[name] = max(
                        self._npartitions[name], part + 1
                    )
                    log = self._logs.setdefault((name, part), [])
                    blobs = self._blobs.setdefault((name, part), [])
                    base = log[-1][0] + 1 if log else 0
                    for i, (ts, pl) in enumerate(records):
                        o = base + i
                        enc = self._pre_encode(o, ts, pl)
                        log.append((o, ts, pl, enc))
                        blobs.append((o, enc))
                out += struct.pack(">ihqq", part, 0, base, -1)
        out += struct.pack(">i", 0)  # throttle
        return bytes(out)

    def _fetch(self, payload: bytes) -> bytes:
        pos = 4 + 4 + 4 + 4 + 1  # replica, max_wait, min_bytes, max_bytes, isolation
        max_wait = struct.unpack_from(">i", payload, 4)[0]
        (ntopics,) = struct.unpack_from(">i", payload, pos)
        pos += 4
        reqs = []
        for _ in range(ntopics):
            (ln,) = struct.unpack_from(">h", payload, pos)
            pos += 2
            name = payload[pos : pos + ln].decode()
            pos += ln
            (nparts,) = struct.unpack_from(">i", payload, pos)
            pos += 4
            parts = []
            for _ in range(nparts):
                part, off, maxb = struct.unpack_from(">iqi", payload, pos)
                pos += 16
                parts.append((part, off, maxb))
            reqs.append((name, parts))

        if self.fetch_delay_s:
            delay = max(
                (
                    self.fetch_delay_s.get((name, part), 0.0)
                    for name, parts in reqs
                    for part, _off, _maxb in parts
                ),
                default=0.0,
            )
            if delay:
                time.sleep(delay)

        # honor max_wait when no data is available
        deadline = time.time() + max_wait / 1000.0
        while time.time() < deadline:
            with self._lock:
                # offsets are dense from 0: data available iff the high
                # watermark passed the requested offset — O(1) per
                # partition (the old per-record any() walked the whole
                # log prefix on every fetch poll)
                have_data = any(
                    len(self._logs.get((name, part), ())) > off
                    for name, parts in reqs
                    for part, off, _maxb in parts
                )
            if have_data:
                break
            time.sleep(0.01)

        out = bytearray()
        out += struct.pack(">i", 0)  # throttle
        out += struct.pack(">i", len(reqs))
        for name, parts in reqs:
            nb = name.encode()
            out += struct.pack(">h", len(nb)) + nb
            out += struct.pack(">i", len(parts))
            for part, off, maxb in parts:
                with self._lock:
                    log = self._logs.get((name, part), [])
                    hw = (log[-1][0] + 1) if log else 0
                    # batch-head blob index: bisect to the batch covering
                    # ``off`` (a mid-batch offset serves its head — clients
                    # skip records below the requested offset, per
                    # protocol), then take whole batches up to the
                    # request's max_bytes.  O(log n + batches served) vs
                    # the old O(n) log walk.  A caught-up consumer
                    # (off >= hw) gets an EMPTY record set, not a replay
                    # of the final batch.
                    if int(off) >= hw:
                        blob = b""
                    else:
                        blobs = self._blobs.get((name, part), [])
                        bi = bisect.bisect_right(
                            blobs, (int(off), b"\xff")
                        ) - 1
                        bi = max(0, bi)
                        picked = []
                        size = 0
                        budget = max(maxb, 1)
                        if self.fetch_max_bytes_clamp is not None:
                            budget = min(budget, self.fetch_max_bytes_clamp)
                        for o, enc in blobs[bi : bi + 50_000]:
                            picked.append(enc)
                            size += len(enc)
                            if size >= budget:
                                break
                        blob = b"".join(picked)
                out += struct.pack(">ihqq", part, 0, hw, hw)
                out += struct.pack(">i", 0)  # aborted txns: empty array
                out += struct.pack(">i", len(blob))
                out += blob
        return bytes(out)
