"""Built-in scalar function registry.

The reference exposes datafusion's scalar function library to Python users
(py-denormalized/python/denormalized/datafusion/functions.py — string, math,
date and conditional functions re-exported wholesale).  This module is the
TPU build's equivalent: every function has a vectorized numpy implementation
(host projections/filters) and, where it makes sense on device, a jax
implementation so post-aggregation filters fuse into the jitted step.

Numeric null semantics follow NaN propagation; string functions map
``None`` → ``None`` elementwise (object arrays are the host string
representation, mirroring arrow's null slots).
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Callable

import numpy as np

from denormalized_tpu.common.errors import PlanError
from denormalized_tpu.common.schema import DataType

# out_type codes: a DataType, or "same" (argument 0's type)
_F64 = DataType.FLOAT64
_I64 = DataType.INT64
_STR = DataType.STRING
_BOOL = DataType.BOOL
_TS = DataType.TIMESTAMP_MS


@dataclass(frozen=True)
class ScalarFn:
    np_fn: Callable  # (*numpy arrays/scalars) -> numpy array
    # DataType | "same" (argument 0's type) | callable(arg_fields)->Field
    # (computed — LIST/STRUCT functions derive element types from args)
    out_type: object
    jax_fn: Callable | None = None  # (*jax arrays) -> jax array
    min_args: int = 1
    max_args: int | None = None  # None = same as min
    # zero-arg functions that draw PER ROW (random, uuid): np_fn receives
    # the batch row count instead of being broadcast from one scalar
    rowwise_nullary: bool = False


def _map1(fn):
    """Elementwise over an object array, None-preserving."""

    def run(a):
        a = np.asarray(a, dtype=object)
        out = np.empty(len(a), dtype=object)
        for i, x in enumerate(a):
            out[i] = None if x is None else fn(x)
        return out

    return run


def _map_n(fn):
    """Elementwise over N object arrays; None in any arg → None (SQL-ish)."""

    def run(*arrays):
        n = max(len(np.atleast_1d(a)) for a in arrays)
        cols = [np.asarray(a, dtype=object) for a in arrays]
        out = np.empty(n, dtype=object)
        for i in range(n):
            vals = [c[i] if len(c) > 1 else c[0] for c in cols]
            out[i] = None if any(v is None for v in vals) else fn(*vals)
        return out

    return run


def _str_of(x):
    return x if isinstance(x, str) else str(x)


# -- string functions ----------------------------------------------------


def _substr(s, start, length=None):
    start = int(start)
    # SQL 1-based; start<1 extends the window leftward like datafusion
    begin = max(start - 1, 0)
    if length is None:
        return s[begin:]
    end = start - 1 + int(length)
    return s[begin:max(end, begin)]


def _pad(s, n, p, left):
    """Postgres lpad/rpad: the pad string CYCLES; result truncated to n."""
    if len(s) >= n:
        return s[:n]
    fill = (p * (n - len(s)))[: n - len(s)] if p else ""
    if not fill:
        return s
    return fill + s if left else s + fill


def _split_part(s, delim, idx):
    parts = s.split(delim)
    i = int(idx)
    return parts[i - 1] if 1 <= i <= len(parts) else ""


def _strpos(s, sub):
    return s.find(sub) + 1


def _initcap(s):
    return "".join(
        c.upper() if (i == 0 or not s[i - 1].isalnum()) else c.lower()
        for i, c in enumerate(s)
    )


_STRING_FNS = {
    "upper": ScalarFn(_map1(lambda s: _str_of(s).upper()), _STR),
    "lower": ScalarFn(_map1(lambda s: _str_of(s).lower()), _STR),
    "length": ScalarFn(_map1(len), _I64),
    "char_length": ScalarFn(_map1(len), _I64),
    "character_length": ScalarFn(_map1(len), _I64),
    "octet_length": ScalarFn(_map1(lambda s: len(s.encode())), _I64),
    "reverse": ScalarFn(_map1(lambda s: s[::-1]), _STR),
    "initcap": ScalarFn(_map1(_initcap), _STR),
    "ascii": ScalarFn(_map1(lambda s: ord(s[0]) if s else 0), _I64),
    "chr": ScalarFn(_map1(lambda n: chr(int(n))), _STR),
    "md5": ScalarFn(
        _map1(
            lambda s: __import__("hashlib").md5(
                _str_of(s).encode()
            ).hexdigest()
        ),
        _STR,
    ),
    "concat": ScalarFn(
        # datafusion concat skips nulls rather than nulling out
        lambda *a: _concat_skip_nulls(*a),
        _STR,
        min_args=1,
        max_args=64,
    ),
    "concat_ws": ScalarFn(
        lambda sep, *a: _concat_ws(sep, *a), _STR, min_args=2, max_args=64
    ),
    "trim": ScalarFn(
        _map_n(lambda s, chars=None: s.strip(chars)), _STR, min_args=1,
        max_args=2,
    ),
    "btrim": ScalarFn(
        _map_n(lambda s, chars=None: s.strip(chars)), _STR, min_args=1,
        max_args=2,
    ),
    "ltrim": ScalarFn(
        _map_n(lambda s, chars=None: s.lstrip(chars)), _STR, min_args=1,
        max_args=2,
    ),
    "rtrim": ScalarFn(
        _map_n(lambda s, chars=None: s.rstrip(chars)), _STR, min_args=1,
        max_args=2,
    ),
    "substr": ScalarFn(_map_n(_substr), _STR, min_args=2, max_args=3),
    "substring": ScalarFn(_map_n(_substr), _STR, min_args=2, max_args=3),
    "replace": ScalarFn(
        _map_n(lambda s, f, t: s.replace(f, t)), _STR, min_args=3
    ),
    "translate": ScalarFn(
        # postgres semantics: chars beyond the 'to' string are DELETED
        _map_n(
            lambda s, f, t: s.translate(
                str.maketrans(f[: len(t)], t[: len(f)], f[len(t):])
            )
        ),
        _STR,
        min_args=3,
    ),
    "starts_with": ScalarFn(
        _map_n(lambda s, p: s.startswith(p)), _BOOL, min_args=2
    ),
    "ends_with": ScalarFn(
        _map_n(lambda s, p: s.endswith(p)), _BOOL, min_args=2
    ),
    "contains": ScalarFn(_map_n(lambda s, p: p in s), _BOOL, min_args=2),
    "strpos": ScalarFn(_map_n(_strpos), _I64, min_args=2),
    "instr": ScalarFn(_map_n(_strpos), _I64, min_args=2),
    "left": ScalarFn(_map_n(lambda s, n: s[: int(n)]), _STR, min_args=2),
    "right": ScalarFn(
        _map_n(lambda s, n: s[-int(n):] if int(n) else ""), _STR, min_args=2
    ),
    "lpad": ScalarFn(
        _map_n(lambda s, n, p=" ": _pad(s, int(n), p, left=True)),
        _STR,
        min_args=2,
        max_args=3,
    ),
    "rpad": ScalarFn(
        _map_n(lambda s, n, p=" ": _pad(s, int(n), p, left=False)),
        _STR,
        min_args=2,
        max_args=3,
    ),
    "repeat": ScalarFn(_map_n(lambda s, n: s * int(n)), _STR, min_args=2),
    "split_part": ScalarFn(_map_n(_split_part), _STR, min_args=3),
    "to_hex": ScalarFn(_map1(lambda n: format(int(n), "x")), _STR),
    # regex family (postgres/datafusion semantics; patterns compile once
    # per distinct (pattern, flags) via _regex)
    "regexp_like": ScalarFn(
        _map_n(lambda s, p, f="": bool(_regex(p, f).search(s))),
        _BOOL,
        min_args=2,
        max_args=3,
    ),
    "regexp_replace": ScalarFn(
        _map_n(
            lambda s, p, r, f="": _regex(p, f).sub(
                _pg_replacement(r), s, count=0 if "g" in f else 1
            )
        ),
        _STR,
        min_args=3,
        max_args=4,
    ),
    "regexp_count": ScalarFn(
        _map_n(lambda s, p, f="": len(_regex(p, f).findall(s))),
        _I64,
        min_args=2,
        max_args=3,
    ),
    "like": ScalarFn(
        _map_n(lambda s, p: bool(_like_regex(p, False).fullmatch(s))),
        _BOOL,
        min_args=2,
    ),
    "ilike": ScalarFn(
        _map_n(lambda s, p: bool(_like_regex(p, True).fullmatch(s))),
        _BOOL,
        min_args=2,
    ),
}


# compiled-pattern caches are lru-BOUNDED: patterns can come from a data
# column, and an unbounded dict would grow for the stream's lifetime
import functools as _functools


@_functools.lru_cache(maxsize=4096)
def _regex(pattern: str, flags: str = ""):
    import re

    f = 0
    if "i" in flags:
        f |= re.IGNORECASE
    if "s" in flags:
        f |= re.DOTALL
    if "m" in flags:
        f |= re.MULTILINE
    return re.compile(pattern, f)


@_functools.lru_cache(maxsize=4096)
def _pg_replacement(r: str) -> str:
    """Postgres replacement escapes → python re escapes: ``\\&`` is the
    whole match (python ``\\g<0>``); ``\\1``..``\\9`` pass through; an
    escaped backslash stays literal; ANY other escaped character is that
    literal character (python re.sub would raise 'bad escape' on it)."""
    out = []
    i = 0
    while i < len(r):
        c = r[i]
        if c == "\\":
            if i + 1 >= len(r):
                out.append("\\\\")  # trailing lone backslash: literal
                i += 1
                continue
            nxt = r[i + 1]
            if nxt == "&":
                out.append("\\g<0>")
            elif nxt == "\\":
                out.append("\\\\")
            elif nxt.isdigit() and nxt != "0":
                # \g<N> form: a following literal digit must not extend
                # the group number (\10 means group 1 then literal '0')
                out.append(f"\\g<{nxt}>")
            else:
                # any other escaped char (incl. \0) is that literal char
                out.append(nxt)
            i += 2
            continue
        out.append(c)
        i += 1
    return "".join(out)


@_functools.lru_cache(maxsize=4096)
def _like_regex(pattern: str, case_insensitive: bool):
    import re

    out = []
    i = 0
    while i < len(pattern):
        ch = pattern[i]
        if ch == "\\" and i + 1 < len(pattern):
            # escaped wildcard (\% or \_) or backslash: literal character
            out.append(re.escape(pattern[i + 1]))
            i += 2
            continue
        if ch == "%":
            out.append(".*")
        elif ch == "_":
            out.append(".")
        else:
            out.append(re.escape(ch))
        i += 1
    # DOTALL: SQL LIKE wildcards match newlines too
    flags = re.DOTALL | (re.IGNORECASE if case_insensitive else 0)
    return re.compile("".join(out), flags)


def _concat_skip_nulls(*arrays):
    n = max(len(np.atleast_1d(a)) for a in arrays)
    cols = [np.asarray(a, dtype=object) for a in arrays]
    out = np.empty(n, dtype=object)
    for i in range(n):
        out[i] = "".join(
            _str_of(c[i] if len(c) > 1 else c[0])
            for c in cols
            if (c[i] if len(c) > 1 else c[0]) is not None
        )
    return out


def _concat_ws(sep, *arrays):
    n = max(len(np.atleast_1d(a)) for a in ((sep,) + arrays))
    sep_arr = np.asarray(sep, dtype=object)
    cols = [np.asarray(a, dtype=object) for a in arrays]
    out = np.empty(n, dtype=object)
    for i in range(n):
        s = sep_arr[i] if sep_arr.ndim and len(sep_arr) > 1 else sep_arr.item() if sep_arr.ndim == 0 else sep_arr[0]
        if s is None:
            out[i] = None
            continue
        vals = [
            _str_of(c[i] if len(c) > 1 else c[0])
            for c in cols
            if (c[i] if len(c) > 1 else c[0]) is not None
        ]
        out[i] = s.join(vals)
    return out


# -- string additions: edit distance, hashes, encodings ------------------


def _levenshtein(a: str, b: str) -> int:
    """Classic two-row DP (the sizes here are projection cells, not bulk
    data — a C implementation would be noise next to the object-array
    iteration around it)."""
    if a == b:
        return 0
    if not a:
        return len(b)
    if not b:
        return len(a)
    prev = list(range(len(b) + 1))
    for i, ca in enumerate(a, 1):
        cur = [i]
        for j, cb in enumerate(b, 1):
            cur.append(min(
                prev[j] + 1, cur[j - 1] + 1, prev[j - 1] + (ca != cb)
            ))
        prev = cur
    return prev[-1]


def _find_in_set(s: str, str_list: str) -> int:
    """MySQL find_in_set: 1-based index of s in a comma-separated list;
    0 when absent."""
    parts = str_list.split(",")
    try:
        return parts.index(s) + 1
    except ValueError:
        return 0


def _overlay(s: str, repl: str, pos, length=None) -> str:
    """Postgres overlay(string PLACING repl FROM pos [FOR length])."""
    p = int(pos)
    ln = len(repl) if length is None else int(length)
    return s[: p - 1] + repl + s[p - 1 + ln :]


def _substr_index(s: str, delim: str, count) -> str:
    """MySQL substring_index: everything before (count>0) / after
    (count<0) the count-th delimiter occurrence."""
    n = int(count)
    if n == 0 or not delim:
        return ""
    parts = s.split(delim)
    if n > 0:
        return delim.join(parts[:n])
    return delim.join(parts[n:])


def _hash_fn(algo: str):
    import hashlib

    def one(s):
        h = hashlib.new(algo)
        h.update(s.encode() if isinstance(s, str) else bytes(s))
        return h.hexdigest()

    return _map1(one)


def _encode(s, enc):
    import base64

    data = s.encode() if isinstance(s, str) else bytes(s)
    enc = str(enc).lower()
    if enc == "hex":
        return data.hex()
    if enc == "base64":
        # datafusion uses unpadded url-safe-less base64? standard with
        # padding stripped matches arrow's base64 for round-trips here
        return base64.b64encode(data).decode().rstrip("=")
    raise PlanError(f"encode: unknown encoding {enc!r} (hex|base64)")


def _decode(s, enc):
    import base64

    enc = str(enc).lower()
    if enc == "hex":
        return bytes.fromhex(s).decode(errors="replace")
    if enc == "base64":
        pad = "=" * (-len(s) % 4)
        return base64.b64decode(s + pad).decode(errors="replace")
    raise PlanError(f"decode: unknown encoding {enc!r} (hex|base64)")


def _digest(s, method):
    import hashlib

    h = hashlib.new(str(method).lower())
    h.update(s.encode() if isinstance(s, str) else bytes(s))
    return h.hexdigest()


def _arrow_typeof(x):
    a = np.asarray(x)
    if a.dtype == object:
        probe = next((v for v in a.tolist() if v is not None), None)
        if isinstance(probe, str) or probe is None:
            name = "Utf8"
        elif isinstance(probe, dict):
            name = "Struct"
        elif isinstance(probe, (list, tuple)):
            name = "List"
        else:
            name = type(probe).__name__
    else:
        name = {
            "int32": "Int32", "int64": "Int64", "float32": "Float32",
            "float64": "Float64", "bool": "Boolean",
        }.get(a.dtype.name, a.dtype.name)
    out = np.empty(max(a.size, 1), dtype=object)
    out[:] = name
    return out


def _in_list(v, *candidates):
    """Membership against a candidate tuple (the ``in_list`` function,
    reference functions.py:323); NULL value → NULL."""
    vals = np.atleast_1d(np.asarray(v, dtype=object))
    cands = [
        (np.atleast_1d(np.asarray(c, dtype=object))) for c in candidates
    ]
    out = np.empty(len(vals), dtype=object)
    for i, x in enumerate(vals):
        if x is None:
            out[i] = None
            continue
        out[i] = any(
            _eq_scalar(x, (c[i] if len(c) > 1 else c[0])) for c in cands
        )
    return out


def _eq_scalar(a, b):
    if b is None:
        return False
    try:
        return bool(a == b)
    except Exception:  # dnzlint: allow(broad-except) SQL comparison semantics: incomparable operand types compare unequal, they don't error the query
        return False


_STRING_FNS2 = {
    "levenshtein": ScalarFn(_map_n(_levenshtein), _I64, None, 2),
    "find_in_set": ScalarFn(_map_n(_find_in_set), _I64, None, 2),
    "overlay": ScalarFn(_map_n(_overlay), _STR, None, 3, 4),
    "substr_index": ScalarFn(_map_n(_substr_index), _STR, None, 3),
    "bit_length": ScalarFn(
        _map1(lambda s: len(s.encode()) * 8 if isinstance(s, str) else 64),
        _I64,
    ),
    "sha224": ScalarFn(_hash_fn("sha224"), _STR),
    "sha256": ScalarFn(_hash_fn("sha256"), _STR),
    "sha384": ScalarFn(_hash_fn("sha384"), _STR),
    "sha512": ScalarFn(_hash_fn("sha512"), _STR),
    "encode": ScalarFn(_map_n(_encode), _STR, None, 2),
    "decode": ScalarFn(_map_n(_decode), _STR, None, 2),
    "digest": ScalarFn(_map_n(_digest), _STR, None, 2),
    "uuid": ScalarFn(
        lambda n: np.array(
            [str(__import__("uuid").uuid4()) for _ in range(n)], object
        ),
        _STR, None, 0, 0, rowwise_nullary=True,
    ),
    "arrow_typeof": ScalarFn(_arrow_typeof, _STR),
    "in_list": ScalarFn(_in_list, _BOOL, None, 2, 64),
}


# -- math functions ------------------------------------------------------


def _np_round(x, d=0):
    # SQL/DataFusion semantics: half away from zero (numpy rounds half to
    # even — round(-2.5) must be -3, not -2)
    x = np.asarray(x, dtype=np.float64)
    scale = 10.0 ** int(np.atleast_1d(d)[0])
    return np.copysign(np.floor(np.abs(x) * scale + 0.5) / scale, x)


def _jax(fname):
    import jax.numpy as jnp

    return getattr(jnp, fname)


def _jax_fn(name):
    def run(*a):
        import jax.numpy as jnp

        return getattr(jnp, name)(*a)

    return run


def _jax_round(x, d=0):
    import jax.numpy as jnp

    scale = 10.0 ** int(d) if not hasattr(d, "shape") else 10.0 ** d
    return jnp.copysign(jnp.floor(jnp.abs(x) * scale + 0.5) / scale, x)


_MATH_FNS = {
    "abs": ScalarFn(np.abs, "same", _jax_fn("abs")),
    # device lowering must match the host's half-away-from-zero, NOT
    # jnp.round's half-to-even — the same expression fused into a device
    # filter has to agree with the host evaluator
    "round": ScalarFn(_np_round, _F64, lambda x, d=0: _jax_round(x, d), 1, 2),
    "floor": ScalarFn(np.floor, _F64, _jax_fn("floor")),
    "ceil": ScalarFn(np.ceil, _F64, _jax_fn("ceil")),
    "trunc": ScalarFn(np.trunc, _F64, _jax_fn("trunc")),
    "sqrt": ScalarFn(np.sqrt, _F64, _jax_fn("sqrt")),
    "cbrt": ScalarFn(np.cbrt, _F64, _jax_fn("cbrt")),
    "exp": ScalarFn(np.exp, _F64, _jax_fn("exp")),
    "ln": ScalarFn(np.log, _F64, _jax_fn("log")),
    "log10": ScalarFn(np.log10, _F64, _jax_fn("log10")),
    "log2": ScalarFn(np.log2, _F64, _jax_fn("log2")),
    "power": ScalarFn(np.power, _F64, _jax_fn("power"), 2),
    "pow": ScalarFn(np.power, _F64, _jax_fn("power"), 2),
    "signum": ScalarFn(np.sign, _F64, _jax_fn("sign")),
    "sin": ScalarFn(np.sin, _F64, _jax_fn("sin")),
    "cos": ScalarFn(np.cos, _F64, _jax_fn("cos")),
    "tan": ScalarFn(np.tan, _F64, _jax_fn("tan")),
    "asin": ScalarFn(np.arcsin, _F64, _jax_fn("arcsin")),
    "acos": ScalarFn(np.arccos, _F64, _jax_fn("arccos")),
    "atan": ScalarFn(np.arctan, _F64, _jax_fn("arctan")),
    "atan2": ScalarFn(np.arctan2, _F64, _jax_fn("arctan2"), 2),
    "sinh": ScalarFn(np.sinh, _F64, _jax_fn("sinh")),
    "cosh": ScalarFn(np.cosh, _F64, _jax_fn("cosh")),
    "tanh": ScalarFn(np.tanh, _F64, _jax_fn("tanh")),
    "degrees": ScalarFn(np.degrees, _F64, _jax_fn("degrees")),
    "radians": ScalarFn(np.radians, _F64, _jax_fn("radians")),
    "isnan": ScalarFn(
        lambda x: np.isnan(np.asarray(x, dtype=np.float64)),
        _BOOL,
        _jax_fn("isnan"),
    ),
    "nanvl": ScalarFn(
        lambda x, y: np.where(np.isnan(np.asarray(x, np.float64)), y, x),
        _F64,
        lambda x, y: __import__("jax.numpy", fromlist=["where"]).where(
            __import__("jax.numpy", fromlist=["isnan"]).isnan(x), y, x
        ),
        2,
    ),
    "pi": ScalarFn(lambda: np.float64(math.pi), _F64, None, 0, 0),
    "log": ScalarFn(  # log(x) = base 10 (datafusion); log(base, x) two-arg
        lambda *a: (
            np.log10(a[0])
            if len(a) == 1
            else np.log(np.asarray(a[1], np.float64))
            / np.log(np.asarray(a[0], np.float64))
        ),
        _F64,
        None,
        1,
        2,
    ),
    "asinh": ScalarFn(np.arcsinh, _F64, _jax_fn("arcsinh")),
    "acosh": ScalarFn(np.arccosh, _F64, _jax_fn("arccosh")),
    "atanh": ScalarFn(np.arctanh, _F64, _jax_fn("arctanh")),
    "cot": ScalarFn(
        lambda x: 1.0 / np.tan(np.asarray(x, np.float64)),
        _F64,
        lambda x: 1.0 / _jax("tan")(x),
    ),
    "factorial": ScalarFn(
        _map1(lambda n: math.factorial(int(n))), _I64
    ),
    "gcd": ScalarFn(
        lambda a, b: np.gcd(
            np.asarray(a, np.int64), np.asarray(b, np.int64)
        ),
        _I64, _jax_fn("gcd"), 2,
    ),
    "lcm": ScalarFn(
        lambda a, b: np.lcm(
            np.asarray(a, np.int64), np.asarray(b, np.int64)
        ),
        _I64, _jax_fn("lcm"), 2,
    ),
    "iszero": ScalarFn(
        lambda x: np.asarray(x, np.float64) == 0.0,
        _BOOL,
        lambda x: x == 0.0,
    ),
    "random": ScalarFn(
        lambda n: np.random.default_rng().random(n), _F64, None, 0, 0,
        rowwise_nullary=True,
    ),
}

# -- date/time functions (int64 epoch-millis timestamps) -----------------

_TRUNC_UNITS = ("second", "minute", "hour", "day", "week", "month", "year")


def _date_trunc(unit, ts):
    unit = str(np.atleast_1d(unit)[0]).lower()
    t = np.asarray(ts, dtype=np.int64)
    if unit == "second":
        return (t // 1000) * 1000
    if unit == "minute":
        return (t // 60_000) * 60_000
    if unit == "hour":
        return (t // 3_600_000) * 3_600_000
    if unit == "day":
        return (t // 86_400_000) * 86_400_000
    if unit == "week":
        # epoch day 0 = Thursday; ISO weeks start Monday (epoch day 4)
        days = t // 86_400_000
        return ((days - 4) // 7 * 7 + 4) * 86_400_000
    d = t.astype("datetime64[ms]")
    if unit == "month":
        return d.astype("datetime64[M]").astype("datetime64[ms]").astype(np.int64)
    if unit == "year":
        return d.astype("datetime64[Y]").astype("datetime64[ms]").astype(np.int64)
    raise PlanError(f"date_trunc: unknown unit {unit!r}")


def _date_part(unit, ts):
    unit = str(np.atleast_1d(unit)[0]).lower()
    t = np.asarray(ts, dtype=np.int64)
    if unit in ("epoch",):
        return t.astype(np.float64) / 1000.0
    if unit in ("millisecond", "milliseconds"):
        return (t % 1000).astype(np.int64)
    d = t.astype("datetime64[ms]")
    if unit == "second":
        return (t // 1000 % 60).astype(np.int64)
    if unit == "minute":
        return (t // 60_000 % 60).astype(np.int64)
    if unit == "hour":
        return (t // 3_600_000 % 24).astype(np.int64)
    if unit in ("day", "dom"):
        return (d - d.astype("datetime64[M]")).astype(
            "timedelta64[D]"
        ).astype(np.int64) + 1
    if unit in ("dow",):  # 0 = Sunday, postgres-style
        return ((t // 86_400_000 + 4) % 7).astype(np.int64)
    if unit in ("doy",):
        return (d - d.astype("datetime64[Y]")).astype(
            "timedelta64[D]"
        ).astype(np.int64) + 1
    if unit == "week":
        iso = d.astype("datetime64[D]").astype(object)
        return np.array([x.isocalendar()[1] for x in iso], dtype=np.int64)
    if unit == "month":
        return (
            d.astype("datetime64[M]").astype(np.int64) % 12 + 1
        ).astype(np.int64)
    if unit == "year":
        return (
            d.astype("datetime64[Y]").astype(np.int64) + 1970
        ).astype(np.int64)
    raise PlanError(f"date_part: unknown unit {unit!r}")


def _to_timestamp_millis(v):
    a = np.asarray(v)
    if a.dtype == object:
        out = np.empty(len(a), dtype=object)
        for i, x in enumerate(a):
            # null propagates as None (an epoch-0 stand-in would silently
            # inject 1970 events into windows)
            out[i] = (
                None
                if x is None
                else int(np.datetime64(x, "ms").astype(np.int64))
            )
        if all(x is not None for x in out):
            return out.astype(np.int64)
        return out
    return a.astype(np.int64)


def _date_bin(stride_ms, ts, origin_ms=0):
    t = np.asarray(ts, dtype=np.int64)
    s = int(np.atleast_1d(stride_ms)[0])
    o = int(np.atleast_1d(origin_ms)[0])
    return (t - o) // s * s + o


def _parse_ts_cell(x, formatters, unit_scale_ms: float):
    """One cell → epoch ms.  Strings go through the formatters (chrono-%
    style, strptime-compatible) or ISO parse; numerics scale by the
    function's unit (to_timestamp_seconds → ×1000, micros → ÷1000)."""
    if x is None:
        return None
    if isinstance(x, str):
        if formatters:
            import datetime as _dt

            for f in formatters:
                try:
                    d = _dt.datetime.strptime(x, str(f))
                    if d.tzinfo is None:
                        d = d.replace(tzinfo=_dt.timezone.utc)
                    return int(d.timestamp() * 1000)
                except ValueError:
                    continue
            raise PlanError(
                f"to_timestamp: {x!r} matches none of {formatters}"
            )
        return int(np.datetime64(x, "ms").astype(np.int64))
    return int(round(float(x) * unit_scale_ms))


def _to_timestamp_family(unit_scale_ms: float):
    def run(v, *formatters):
        fmts = [
            str(np.atleast_1d(f)[0]) for f in formatters
        ] if formatters else []
        a = np.atleast_1d(np.asarray(v))
        if a.dtype != object and a.dtype.kind in "iuf":
            return np.round(
                a.astype(np.float64) * unit_scale_ms
            ).astype(np.int64)
        out = np.empty(len(a), dtype=object)
        for i, x in enumerate(a.tolist()):
            out[i] = _parse_ts_cell(x, fmts, unit_scale_ms)
        if all(x is not None for x in out):
            return out.astype(np.int64)
        return out

    return run


def _to_unixtime(v, *formatters):
    ms = _to_timestamp_family(1.0)(v, *formatters)
    if ms.dtype == object:
        return np.array(
            [None if x is None else x // 1000 for x in ms], object
        )
    return ms // 1000


def _from_unixtime(secs):
    return np.asarray(secs, np.int64) * 1000


def _make_date(y, m, d):
    ys = np.atleast_1d(np.asarray(y, np.int64))
    ms_ = np.atleast_1d(np.asarray(m, np.int64))
    ds = np.atleast_1d(np.asarray(d, np.int64))
    n = max(len(ys), len(ms_), len(ds))

    def pick(a, i):
        return int(a[i] if len(a) > 1 else a[0])

    import datetime as _dt

    out = np.empty(n, dtype=np.int64)
    for i in range(n):
        out[i] = int(
            _dt.datetime(
                pick(ys, i), pick(ms_, i), pick(ds, i),
                tzinfo=_dt.timezone.utc,
            ).timestamp() * 1000
        )
    return out


_DATE_FNS = {
    "date_trunc": ScalarFn(_date_trunc, _TS, None, 2),
    "datetrunc": ScalarFn(_date_trunc, _TS, None, 2),
    "date_part": ScalarFn(_date_part, _F64, None, 2),
    "datepart": ScalarFn(_date_part, _F64, None, 2),
    "extract": ScalarFn(_date_part, _F64, None, 2),
    "to_timestamp_millis": ScalarFn(_to_timestamp_millis, _TS),
    # the engine's timestamp storage is epoch-millis; every to_timestamp_*
    # variant converts its input unit to ms (reference functions.py:909-955
    # — arrow precisions there; one storage precision here)
    "to_timestamp": ScalarFn(_to_timestamp_family(1000.0), _TS, None, 1, 5),
    "to_timestamp_seconds": ScalarFn(
        _to_timestamp_family(1000.0), _TS, None, 1, 5
    ),
    "to_timestamp_micros": ScalarFn(
        _to_timestamp_family(1e-3), _TS, None, 1, 5
    ),
    "to_timestamp_nanos": ScalarFn(
        _to_timestamp_family(1e-6), _TS, None, 1, 5
    ),
    "to_unixtime": ScalarFn(_to_unixtime, _I64, None, 1, 5),
    "from_unixtime": ScalarFn(_from_unixtime, _TS),
    "make_date": ScalarFn(_make_date, _TS, None, 3),
    "current_date": ScalarFn(
        lambda: np.int64(
            __import__("time").time() * 1000 // 86_400_000 * 86_400_000
        ),
        _TS, None, 0, 0,
    ),
    "current_time": ScalarFn(
        lambda: np.int64(__import__("time").time() * 1000 % 86_400_000),
        _I64, None, 0, 0,
    ),
    "date_bin": ScalarFn(_date_bin, _TS, None, 2, 3),
    "now": ScalarFn(
        lambda: np.int64(__import__("time").time() * 1000), _TS, None, 0, 0
    ),
}

# -- conditional ---------------------------------------------------------


def _coalesce(*arrays):
    cols = [np.asarray(a) for a in arrays]
    n = max(len(np.atleast_1d(c)) for c in cols)
    if any(c.dtype == object for c in cols):
        out = np.empty(n, dtype=object)
        for i in range(n):
            out[i] = None
            for c in cols:
                v = c[i] if c.ndim and len(c) > 1 else c.item() if c.ndim == 0 else c[0]
                if v is not None and not (
                    isinstance(v, float) and math.isnan(v)
                ):
                    out[i] = v
                    break
        return out
    out = np.broadcast_to(cols[0].astype(np.float64), (n,)).copy()
    for c in cols[1:]:
        out = np.where(np.isnan(out), c, out)
    return out


def _nullif(a, b):
    a = np.asarray(a)
    b = np.asarray(b)
    if a.dtype == object or b.dtype == object:
        return _map_n(lambda x, y: None if x == y else x)(a, b)
    return np.where(a == b, np.nan, a.astype(np.float64))


def _ifnull(a, b):
    return _coalesce(a, b)


_COND_FNS = {
    "coalesce": ScalarFn(_coalesce, "same", None, 1, 64),
    "nullif": ScalarFn(_nullif, "same", None, 2),
    "ifnull": ScalarFn(_ifnull, "same", None, 2),
    "nvl": ScalarFn(_ifnull, "same", None, 2),
}


def _array_fns():
    from denormalized_tpu.logical.array_functions import ARRAY_FNS

    return ARRAY_FNS


REGISTRY: dict[str, ScalarFn] = {
    **_STRING_FNS,
    **_STRING_FNS2,
    **_MATH_FNS,
    **_DATE_FNS,
    **_COND_FNS,
    **_array_fns(),
}


def lookup(fname: str) -> ScalarFn:
    fn = REGISTRY.get(fname)
    if fn is None:
        raise PlanError(
            f"unknown scalar function {fname!r} "
            f"(available: {', '.join(sorted(REGISTRY))})"
        )
    return fn
