"""Logical plan optimizer.

The reference runs a CURATED subset of DataFusion's rewrite rules — it
deliberately omits the ones that break unbounded plans
(crates/core/src/utils/default_optimizer_rules.rs:29-65).  Our plan algebra
is purpose-built, so the rule set is small and streaming-safe by
construction:

- :class:`ProjectionPruning` — compute the transitively-required column set
  top-down, NARROW every intermediate Project to the outputs actually read
  above it, and insert a narrow Project above each Scan so unused source
  columns are dropped before every downstream operator (interning, window
  state, joins).  Sources that support it (JSON readers) take the pushdown
  all the way into DECODE via ``Source.with_projection`` — the parser never
  materializes pruned columns; others get the Project fallback.
- :class:`MergeProjects` — collapse stacked projections (each
  ``with_column`` call adds one) into a single evaluation pass.  A merge is
  only taken when it cannot DUPLICATE work: an inner expression that is
  non-trivial is inlined only if the outer projection references it at most
  once, and user UDFs are never inlined (they may be expensive or
  non-deterministic).
- :class:`FilterPushdown` — evaluate filters before the projections above
  them, and fuse adjacent filters into one conjunction.  A predicate is
  only pushed when the rewrite is semantics-preserving: no IsNull nodes
  (null-mask checks on projected columns would silently become value/NaN
  checks) and no UDFs in the substituted form (duplicate / re-evaluated
  calls).

Rules run to a fixpoint (bounded); ``EngineConfig(optimizer=False)``
disables the pass wholesale.
"""

from __future__ import annotations

from typing import Callable

from denormalized_tpu.common.constants import CANONICAL_TIMESTAMP_COLUMN
from denormalized_tpu.logical import plan as lp
from denormalized_tpu.logical.expr import (
    AliasExpr,
    BinaryExpr,
    CaseExpr,
    CastExpr,
    Column,
    Expr,
    FieldAccessExpr,
    IsNullExpr,
    NotExpr,
    ScalarFunctionExpr,
    ScalarUDFExpr,
    substitute_columns,
)


def map_children(
    node: lp.LogicalPlan, fn: Callable[[lp.LogicalPlan], lp.LogicalPlan]
) -> lp.LogicalPlan:
    """Rebuild ``node`` with ``fn`` applied to each child — the ONE place
    that knows how to reconstruct every plan node (all rules traverse
    through it, so a new node type only needs adding here)."""
    if isinstance(node, lp.Sink):
        return lp.Sink(fn(node.input), node.sink)
    if isinstance(node, lp.Project):
        return lp.Project(fn(node.input), node.exprs)
    if isinstance(node, lp.Filter):
        return lp.Filter(fn(node.input), node.predicate)
    if isinstance(node, lp.StreamingWindow):
        return lp.StreamingWindow(
            fn(node.input),
            node.group_exprs,
            node.aggr_exprs,
            node.window_type,
            node.length_ms,
            node.slide_ms,
        )
    if isinstance(node, lp.Join):
        return lp.Join(
            fn(node.left),
            fn(node.right),
            node.kind,
            node.left_keys,
            node.right_keys,
            node.filter,
            node.band,
        )
    return node


def _expr_nodes(e: Expr):
    """Yield every node of an expression tree."""
    yield e
    if isinstance(e, BinaryExpr):
        yield from _expr_nodes(e.left)
        yield from _expr_nodes(e.right)
    elif isinstance(e, (NotExpr, IsNullExpr, AliasExpr, CastExpr)):
        yield from _expr_nodes(e.inner)
    elif isinstance(e, FieldAccessExpr):
        yield from _expr_nodes(e.inner)
    elif isinstance(e, (ScalarFunctionExpr, ScalarUDFExpr)):
        for a in e.args:
            yield from _expr_nodes(a)
    elif isinstance(e, CaseExpr):
        if e.base is not None:
            yield from _expr_nodes(e.base)
        for c, r in e.branches:
            yield from _expr_nodes(c)
            yield from _expr_nodes(r)
        if e.otherwise is not None:
            yield from _expr_nodes(e.otherwise)


def _contains(e: Expr, cls) -> bool:
    return any(isinstance(n, cls) for n in _expr_nodes(e))


def _is_trivial(e: Expr) -> bool:
    """Inlining this duplicates no meaningful work."""
    while isinstance(e, AliasExpr):
        e = e.inner
    from denormalized_tpu.logical.expr import Literal

    return isinstance(e, (Column, Literal))


class ProjectionPruning:
    """Narrow every projection to the columns the plan actually reads:
    intermediate Projects lose outputs nobody above consumes, and each Scan
    gets a narrow Project directly above it."""

    def rewrite(self, plan: lp.LogicalPlan) -> lp.LogicalPlan:
        return self._walk(plan, None)

    def _walk(
        self, node: lp.LogicalPlan, required: set[str] | None
    ) -> lp.LogicalPlan:
        # required=None means "every column" (top of plan / sinks)
        if isinstance(node, lp.Sink):
            return lp.Sink(self._walk(node.input, None), node.sink)
        if isinstance(node, lp.Project):
            exprs = node.exprs
            if required is not None:
                # narrow the projection itself: outputs nobody above reads
                # are dropped (with_column chains otherwise carry every
                # passthrough column to the top)
                kept = [
                    e
                    for e in exprs
                    if e.name in required
                    or e.name == CANONICAL_TIMESTAMP_COLUMN
                ]
                if kept:
                    exprs = kept
            need: set[str] = set()
            for e in exprs:
                need |= e.columns_referenced()
            return lp.Project(self._walk(node.input, need), exprs)
        if isinstance(node, lp.Filter):
            need = set(node.predicate.columns_referenced())
            if required is None:
                return lp.Filter(self._walk(node.input, None), node.predicate)
            return lp.Filter(
                self._walk(node.input, need | required), node.predicate
            )
        if isinstance(node, lp.StreamingWindow):
            need = set()
            for g in node.group_exprs:
                need |= g.columns_referenced()
            for a in node.aggr_exprs:
                if a.kind == "udaf" and a.udaf is not None:
                    for arg in a.udaf.args:
                        need |= arg.columns_referenced()
                elif a.arg is not None:
                    need |= a.arg.columns_referenced()
            return lp.StreamingWindow(
                self._walk(node.input, need),
                node.group_exprs,
                node.aggr_exprs,
                node.window_type,
                node.length_ms,
                node.slide_ms,
            )
        if isinstance(node, lp.Join):
            lnames = set(node.left.schema.names)
            rnames = set(node.right.schema.names)
            if required is None:
                lneed = rneed = None
            else:
                base = set(required)
                base |= set(node.left_keys) | set(node.right_keys)
                lneed = {n for n in base if n in lnames}
                rneed = {n for n in base if n in rnames}
                if node.filter is not None:
                    for n in node.filter.columns_referenced():
                        (lneed if n in lnames else rneed).add(n)
                if node.band is not None:
                    # band expressions evaluate against their own
                    # side's input — pruning must keep those columns
                    # even though they may never reach the output
                    for n in node.band.left_expr.columns_referenced():
                        lneed.add(n)
                    for n in node.band.right_expr.columns_referenced():
                        rneed.add(n)
            return lp.Join(
                self._walk(node.left, lneed),
                self._walk(node.right, rneed),
                node.kind,
                node.left_keys,
                node.right_keys,
                node.filter,
                node.band,
            )
        if isinstance(node, lp.Scan):
            if required is None:
                return node
            keep = [
                f.name
                for f in node.schema
                if f.name in required or f.name == CANONICAL_TIMESTAMP_COLUMN
            ]
            if len(keep) == len(node.schema):
                return node  # nothing to prune
            # best case: the reader itself declines to DECODE the pruned
            # columns (JSON sources); otherwise project them away above it.
            # A pushed source may still carry extra columns (its timestamp
            # column) — narrow those with a Project HERE rather than relying
            # on a later fixpoint pass.
            pushed = node.source.with_projection(set(keep))
            if pushed is not None:
                scan = lp.Scan(node.table_name, pushed, pushed.schema)
                extra = set(pushed.schema.names) - set(keep)
                if extra - {CANONICAL_TIMESTAMP_COLUMN}:
                    return lp.Project(
                        scan,
                        [
                            Column(n)
                            for n in pushed.schema.names
                            if n in keep
                        ],
                    )
                return scan
            return lp.Project(node, [Column(n) for n in keep])
        return map_children(node, lambda c: self._walk(c, None))


class MergeProjects:
    """Project(Project(x)) → Project(x), gated so no work is duplicated."""

    # a merged projection may be at most this factor larger (in expression
    # nodes) than the two it replaces — cheap recomputation is a win over an
    # extra column-materialization pass, exponential reference chains are not
    _GROWTH_BOUND = 2.0

    def rewrite(self, plan: lp.LogicalPlan) -> lp.LogicalPlan:
        node = map_children(plan, self.rewrite)
        if isinstance(node, lp.Project) and isinstance(node.input, lp.Project):
            inner = node.input
            mapping = self._mapping(inner)
            if self._udf_inlined(node, mapping):
                return node  # UDFs may be expensive or non-deterministic
            merged = [
                self._realias(substitute_columns(e, mapping), e)
                for e in node.exprs
            ]
            before = self._size(node.exprs) + self._size(inner.exprs)
            if self._size(merged) > self._GROWTH_BOUND * before:
                return node
            return self.rewrite(lp.Project(inner.input, merged))
        return node

    @staticmethod
    def _mapping(p: lp.Project) -> dict[str, Expr]:
        return {f.name: e for f, e in zip(p.schema, p.exprs)}

    @staticmethod
    def _size(exprs) -> int:
        return sum(sum(1 for _ in _expr_nodes(e)) for e in exprs)

    @staticmethod
    def _udf_inlined(outer: lp.Project, mapping: dict[str, Expr]) -> bool:
        for e in outer.exprs:
            for n in _expr_nodes(e):
                if isinstance(n, Column):
                    inner_e = mapping.get(n.name)
                    if inner_e is not None and not _is_trivial(inner_e) and (
                        _contains(inner_e, ScalarUDFExpr)
                    ):
                        return True
        return False

    @staticmethod
    def _realias(sub: Expr, original: Expr) -> Expr:
        # keep the outer projection's output names stable
        want = original.name
        return sub if sub.name == want else AliasExpr(sub, want)


class FilterPushdown:
    """Filter(Project(x)) → Project(Filter'(x)); Filter(Filter(x)) → one
    conjunctive Filter.  Pushes only semantics-preserving rewrites."""

    def rewrite(self, plan: lp.LogicalPlan) -> lp.LogicalPlan:
        node = map_children(plan, self.rewrite)
        if isinstance(node, lp.Filter):
            child = node.input
            if isinstance(child, lp.Filter):
                return self.rewrite(
                    lp.Filter(
                        child.input,
                        BinaryExpr("and", child.predicate, node.predicate),
                    )
                )
            if isinstance(child, lp.Project):
                mapping = MergeProjects._mapping(child)
                refs = node.predicate.columns_referenced()
                if not all(
                    n in mapping or child.input.schema.has(n) for n in refs
                ):
                    return node
                # IsNull on a projected column checks the VALIDITY MASK
                # when the expression stays a bare Column; substituting a
                # computed expression would silently turn it into a
                # value/NaN check — don't push those
                if _contains(node.predicate, IsNullExpr):
                    return node
                pred = substitute_columns(node.predicate, mapping)
                # never duplicate UDF evaluation into the filter
                if _contains(pred, ScalarUDFExpr):
                    return node
                return self.rewrite(
                    lp.Project(lp.Filter(child.input, pred), child.exprs)
                )
        return node


# order matters: MergeProjects runs LAST so each pass ENDS with stacked
# projections collapsed — ProjectionPruning re-wraps scans every pass (its
# walk is stateless), and ending a pass on the wrap would let the fixpoint
# terminate on a shape with redundant Project(Project(Scan)) stacks
DEFAULT_RULES = (ProjectionPruning(), FilterPushdown(), MergeProjects())
_MAX_PASSES = 5


def optimize(plan: lp.LogicalPlan, enabled: bool = True) -> lp.LogicalPlan:
    """Run the curated rule list to a (bounded) fixpoint — FilterPushdown
    can re-stack projections that MergeProjects then collapses."""
    if not enabled:
        return plan
    prev = None
    for _ in range(_MAX_PASSES):
        for rule in DEFAULT_RULES:
            plan = rule.rewrite(plan)
        shape = plan.display()
        if shape == prev:
            break
        prev = shape
    return plan
