from denormalized_tpu.logical.expr import Expr, col, lit
from denormalized_tpu.logical import plan

__all__ = ["Expr", "col", "lit", "plan"]
