"""Expression tree: columns, literals, arithmetic/comparison/boolean ops,
struct-field access, casts, aliases, and aggregate calls.

Capability mirror of the DataFusion ``Expr`` surface the reference exposes
through its fluent API (datastream.rs select/filter/with_column; nested field
access used in examples/examples/kafka_rideshare.rs:40-57; aggregates built in
examples via ``min``/``max``/``avg``/``count``).  Two evaluators exist:

- :meth:`Expr.eval` — host-side vectorized numpy over a ``RecordBatch``
  (projections, filters, join keys, string work).
- :meth:`Expr.eval_jax` — the same tree traced over ``jax`` arrays; used for
  numeric post-aggregation filters and scalar compute fused into the jitted
  device step, so XLA fuses them into the aggregation kernel.
"""

from __future__ import annotations

import operator
from dataclasses import dataclass
from typing import Any, Callable

import numpy as np

from denormalized_tpu.common.errors import PlanError, SchemaError
from denormalized_tpu.common.record_batch import RecordBatch
from denormalized_tpu.common.schema import DataType, Field, Schema

_BIN_NUMPY: dict[str, Callable] = {
    "+": operator.add,
    "-": operator.sub,
    "*": operator.mul,
    "/": operator.truediv,
    "%": operator.mod,
    "==": operator.eq,
    "!=": operator.ne,
    "<": operator.lt,
    "<=": operator.le,
    ">": operator.gt,
    ">=": operator.ge,
    "and": np.logical_and,
    "or": np.logical_or,
}

_CMP = {"==", "!=", "<", "<=", ">", ">="}
_BOOL = {"and", "or"}


class Expr:
    """Base expression node; builder methods mirror datafusion-python's Expr
    (reference py-denormalized/python/denormalized/datafusion/expr.py)."""

    # -- builder sugar ---------------------------------------------------
    def __add__(self, other):
        return BinaryExpr("+", self, _wrap(other))

    def __radd__(self, other):
        return BinaryExpr("+", _wrap(other), self)

    def __sub__(self, other):
        return BinaryExpr("-", self, _wrap(other))

    def __rsub__(self, other):
        return BinaryExpr("-", _wrap(other), self)

    def __mul__(self, other):
        return BinaryExpr("*", self, _wrap(other))

    def __rmul__(self, other):
        return BinaryExpr("*", _wrap(other), self)

    def __truediv__(self, other):
        return BinaryExpr("/", self, _wrap(other))

    def __rtruediv__(self, other):
        return BinaryExpr("/", _wrap(other), self)

    def __mod__(self, other):
        return BinaryExpr("%", self, _wrap(other))

    def __eq__(self, other):  # type: ignore[override]
        return BinaryExpr("==", self, _wrap(other))

    def __ne__(self, other):  # type: ignore[override]
        return BinaryExpr("!=", self, _wrap(other))

    def __lt__(self, other):
        return BinaryExpr("<", self, _wrap(other))

    def __le__(self, other):
        return BinaryExpr("<=", self, _wrap(other))

    def __gt__(self, other):
        return BinaryExpr(">", self, _wrap(other))

    def __ge__(self, other):
        return BinaryExpr(">=", self, _wrap(other))

    def __and__(self, other):
        return BinaryExpr("and", self, _wrap(other))

    def __or__(self, other):
        return BinaryExpr("or", self, _wrap(other))

    def __invert__(self):
        return NotExpr(self)

    def __hash__(self):
        return hash(repr(self))

    def alias(self, name: str) -> "Expr":
        return AliasExpr(self, name)

    def field(self, name: str) -> "Expr":
        """Struct-field access: ``col('gps').field('speed')`` (reference
        kafka_rideshare.rs:40)."""
        return FieldAccessExpr(self, name)

    def cast(self, dtype: DataType) -> "Expr":
        return CastExpr(self, dtype)

    def is_null(self) -> "Expr":
        return IsNullExpr(self, negate=False)

    def is_not_null(self) -> "Expr":
        return IsNullExpr(self, negate=True)

    # -- interface -------------------------------------------------------
    @property
    def name(self) -> str:
        """Output column name."""
        raise NotImplementedError

    def out_field(self, schema: Schema) -> Field:
        raise NotImplementedError

    def eval(self, batch: RecordBatch) -> np.ndarray:
        """Vectorized host evaluation → one array of batch.num_rows."""
        raise NotImplementedError

    def eval_jax(self, cols: dict[str, Any]):
        """Trace over a dict of column -> jax array (device evaluation)."""
        raise NotImplementedError

    def columns_referenced(self) -> set[str]:
        raise NotImplementedError


def _wrap(v) -> Expr:
    return v if isinstance(v, Expr) else Literal(v)


@dataclass(frozen=True, eq=False)
class Column(Expr):
    _name: str

    @property
    def name(self) -> str:
        return self._name

    def out_field(self, schema: Schema) -> Field:
        return schema.field(self._name)

    def eval(self, batch: RecordBatch) -> np.ndarray:
        return batch.column(self._name)

    def eval_jax(self, cols: dict[str, Any]):
        if self._name not in cols:
            raise SchemaError(f"column {self._name!r} not on device")
        return cols[self._name]

    def columns_referenced(self) -> set[str]:
        return {self._name}

    def __repr__(self):
        return f"col({self._name!r})"


@dataclass(frozen=True, eq=False)
class Literal(Expr):
    value: Any

    @property
    def name(self) -> str:
        return f"lit({self.value})"

    def out_field(self, schema: Schema) -> Field:
        return Field(self.name, _literal_dtype(self.value), nullable=False)

    def eval(self, batch: RecordBatch) -> np.ndarray:
        dt = _literal_dtype(self.value).to_numpy()
        return np.full(batch.num_rows, self.value, dtype=dt)

    def eval_jax(self, cols: dict[str, Any]):
        return self.value

    def columns_referenced(self) -> set[str]:
        return set()

    def __repr__(self):
        return f"lit({self.value!r})"


def _literal_dtype(v) -> DataType:
    if isinstance(v, bool):
        return DataType.BOOL
    if isinstance(v, (int, np.integer)):
        return DataType.INT64
    if isinstance(v, (float, np.floating)):
        return DataType.FLOAT64
    if isinstance(v, str):
        return DataType.STRING
    raise PlanError(f"unsupported literal {v!r}")


@dataclass(frozen=True, eq=False)
class BinaryExpr(Expr):
    op: str
    left: Expr
    right: Expr

    @property
    def name(self) -> str:
        return f"{self.left.name} {self.op} {self.right.name}"

    def out_field(self, schema: Schema) -> Field:
        if self.op in _CMP or self.op in _BOOL:
            return Field(self.name, DataType.BOOL)
        lf = self.left.out_field(schema)
        rf = self.right.out_field(schema)
        return Field(self.name, _promote(lf.dtype, rf.dtype, self.op))

    def eval(self, batch: RecordBatch) -> np.ndarray:
        from denormalized_tpu.common.columns import as_numpy

        l = as_numpy(self.left.eval(batch))
        r = as_numpy(self.right.eval(batch))
        l_obj = getattr(l, "dtype", None) == object
        r_obj = getattr(r, "dtype", None) == object
        if self.op in _CMP and (l_obj or r_obj):
            # object lanes carry strings and/or nullable cells.  Any
            # comparison against a null (None) cell is FALSE — SQL
            # three-valued logic collapsed to the filter's keep/drop
            # decision, and the precondition the subsumption-sharing
            # containment argument rests on (planner/predicates.py:
            # constrained conjuncts must reject null rows on BOTH
            # sides of an implication)
            valid = None
            for side, is_obj in ((l, l_obj), (r, r_obj)):
                if not is_obj:
                    continue
                m = np.not_equal(side, None).astype(bool)
                valid = m if valid is None else (valid & m)
            if bool(valid.all()):
                return _BIN_NUMPY[self.op](l, r).astype(bool)
            lv = l[valid] if np.shape(l) == valid.shape else l
            rv = r[valid] if np.shape(r) == valid.shape else r
            out = np.zeros(valid.shape, dtype=bool)
            out[valid] = _BIN_NUMPY[self.op](lv, rv).astype(bool)
            return out
        return _BIN_NUMPY[self.op](l, r)

    def eval_jax(self, cols: dict[str, Any]):
        import jax.numpy as jnp

        l = self.left.eval_jax(cols)
        r = self.right.eval_jax(cols)
        fn = {
            "+": jnp.add, "-": jnp.subtract, "*": jnp.multiply,
            "/": jnp.divide, "%": jnp.mod,
            "==": jnp.equal, "!=": jnp.not_equal,
            "<": jnp.less, "<=": jnp.less_equal,
            ">": jnp.greater, ">=": jnp.greater_equal,
            "and": jnp.logical_and, "or": jnp.logical_or,
        }[self.op]
        return fn(l, r)

    def columns_referenced(self) -> set[str]:
        return self.left.columns_referenced() | self.right.columns_referenced()

    def __repr__(self):
        return f"({self.left!r} {self.op} {self.right!r})"


def _promote(a: DataType, b: DataType, op: str) -> DataType:
    if op == "/":
        return DataType.FLOAT64
    order = [
        DataType.BOOL,
        DataType.INT32,
        DataType.INT64,
        DataType.TIMESTAMP_MS,
        DataType.FLOAT32,
        DataType.FLOAT64,
    ]
    if a in order and b in order:
        return order[max(order.index(a), order.index(b))]
    if DataType.STRING in (a, b):
        return DataType.STRING
    raise SchemaError(f"cannot promote {a} and {b}")


@dataclass(frozen=True, eq=False)
class NotExpr(Expr):
    inner: Expr

    @property
    def name(self) -> str:
        return f"NOT {self.inner.name}"

    def out_field(self, schema: Schema) -> Field:
        return Field(self.name, DataType.BOOL)

    def eval(self, batch: RecordBatch) -> np.ndarray:
        return np.logical_not(self.inner.eval(batch))

    def eval_jax(self, cols):
        import jax.numpy as jnp

        return jnp.logical_not(self.inner.eval_jax(cols))

    def columns_referenced(self) -> set[str]:
        return self.inner.columns_referenced()

    def __repr__(self):
        return f"(~{self.inner!r})"


@dataclass(frozen=True, eq=False)
class IsNullExpr(Expr):
    inner: Expr
    negate: bool

    @property
    def name(self) -> str:
        return f"{self.inner.name} IS {'NOT ' if self.negate else ''}NULL"

    def out_field(self, schema: Schema) -> Field:
        return Field(self.name, DataType.BOOL)

    def eval(self, batch: RecordBatch) -> np.ndarray:
        from denormalized_tpu.common.columns import Column as _ColData

        if isinstance(self.inner, Column):
            m = batch.mask(self.inner.name)
            null = (
                np.zeros(batch.num_rows, dtype=bool) if m is None else ~m
            )
            v = batch.column(self.inner.name)
            if isinstance(v, _ColData):
                # columnar string/nested columns carry nulls as validity
                # — read it directly, no row materialization
                validity = getattr(v, "validity", None)
                if validity is not None:
                    null = null | ~validity
            elif v.dtype == object:
                # string/derived columns carry nulls as None VALUES (scalar
                # functions propagate None without materializing a mask) —
                # both representations are null
                null = null | np.fromiter(
                    (x is None for x in v), dtype=bool, count=len(v)
                )
        else:
            v = self.inner.eval(batch)
            if isinstance(v, _ColData):
                validity = getattr(v, "validity", None)
                null = (
                    ~validity if validity is not None
                    else np.zeros(len(v), bool)
                )
            else:
                null = (
                    np.array([x is None for x in v])
                    if v.dtype == object
                    else np.isnan(v) if v.dtype.kind == "f" else np.zeros(len(v), bool)
                )
        return ~null if self.negate else null

    def columns_referenced(self) -> set[str]:
        return self.inner.columns_referenced()


@dataclass(frozen=True, eq=False)
class AliasExpr(Expr):
    inner: Expr
    _name: str

    @property
    def name(self) -> str:
        return self._name

    def out_field(self, schema: Schema) -> Field:
        f = self.inner.out_field(schema)
        return Field(self._name, f.dtype, f.nullable, f.children)

    def eval(self, batch: RecordBatch) -> np.ndarray:
        return self.inner.eval(batch)

    def eval_jax(self, cols):
        return self.inner.eval_jax(cols)

    def columns_referenced(self) -> set[str]:
        return self.inner.columns_referenced()

    def __repr__(self):
        return f"{self.inner!r}.alias({self._name!r})"


@dataclass(frozen=True)
class SortExpr:
    """Sort specification (the reference's ``order_by`` export,
    py-denormalized functions.py:356 → datafusion SortExpr): not itself a
    value expression — consumed by order-aware options (e.g. sorting a
    bounded ``collect``)."""

    expr: "Expr"
    ascending: bool = True
    nulls_first: bool = True

    def __repr__(self):
        d = "asc" if self.ascending else "desc"
        nf = "nulls_first" if self.nulls_first else "nulls_last"
        return f"{self.expr!r}.sort({d}, {nf})"


@dataclass(frozen=True, eq=False)
class WindowFunctionExpr(Expr):
    """Ranking / offset window function (the reference exports
    datafusion's lead/lag/row_number/rank/dense_rank/percent_rank/
    cume_dist/ntile, functions.py:2292-2560).

    Evaluation scope is the RecordBatch being projected: exact SQL
    semantics on bounded ``collect()`` results (which coalesce to one
    batch); on an unbounded stream the frame is each arrival batch —
    windowed aggregation is the streaming-native tool there."""

    wname: str
    args: tuple[Expr, ...] = ()
    partition_by: tuple[Expr, ...] = ()
    order_by: tuple["SortExpr", ...] = ()
    params: tuple = ()

    @property
    def name(self) -> str:
        inner = ", ".join(a.name for a in self.args)
        return f"{self.wname}({inner})"

    def out_field(self, schema: Schema) -> Field:
        if self.wname in ("lead", "lag"):
            f0 = self.args[0].out_field(schema)
            return Field(self.name, f0.dtype, True, f0.children)
        if self.wname in ("row_number", "rank", "dense_rank", "ntile"):
            return Field(self.name, DataType.INT64)
        if self.wname in ("percent_rank", "cume_dist"):
            return Field(self.name, DataType.FLOAT64)
        raise PlanError(f"unknown window function {self.wname!r}")

    def columns_referenced(self) -> set[str]:
        s: set[str] = set()
        for e in self.args + self.partition_by:
            s |= e.columns_referenced()
        for sx in self.order_by:
            s |= sx.expr.columns_referenced()
        return s

    def _order_index(self, batch: RecordBatch) -> tuple[np.ndarray, list]:
        """Row order within the batch under order_by (stable; repeated
        sorts from the least-significant key honor per-key direction and
        null placement), plus the composite order-key tuples for tie
        detection."""
        n = batch.num_rows
        idx = list(range(n))
        keycols = []
        for sx in self.order_by:
            vals = np.atleast_1d(sx.expr.eval(batch)).tolist()
            keycols.append(vals)
        for sx, vals in reversed(list(zip(self.order_by, keycols))):
            def k(i, vals=vals, sx=sx):
                v = vals[i]
                isnull = v is None or (isinstance(v, float) and v != v)
                # nulls get an extreme bucket; direction-aware so that
                # reverse=True keeps nulls where nulls_first asks
                null_rank = 0 if (sx.nulls_first != (not sx.ascending)) else 2
                return (null_rank if isnull else 1, _SortKey(v, isnull))

            idx.sort(key=k, reverse=not sx.ascending)
        keys = [
            tuple(vals[i] for vals in keycols) for i in range(n)
        ]
        return np.asarray(idx, np.int64), keys

    def _partition_ids(self, batch: RecordBatch, n: int) -> np.ndarray:
        """Dense partition ids via the group interner (the session/window
        operators' keying trick): numeric key columns dedupe through
        np.unique, string columns through the native PyObject interner —
        no per-row tuple construction.  Columns holding non-string objects
        fall back to the legacy Python path (the interner's ``str()``
        normalization could merge keys raw tuples would keep distinct)."""
        if not self.partition_by:
            return np.zeros(n, dtype=np.int32)
        from denormalized_tpu.ops.interner import GroupInterner

        pcols = []
        for e in self.partition_by:
            v = np.atleast_1d(e.eval(batch))
            if v.dtype.kind == "f" and np.isnan(v).any():
                # comparator-path semantics: NaN != NaN, so every NaN key
                # is its OWN partition — np.unique would merge them
                raise _WindowFallback
            if v.dtype.kind not in "ifbuM" and not all(
                isinstance(x, str) or x is None for x in v.tolist()
            ):
                raise _WindowFallback
            pcols.append(v)
        return GroupInterner(len(pcols)).intern(pcols)

    def _order_keys_vec(
        self, batch: RecordBatch, n: int
    ) -> tuple[list[np.ndarray], list[np.ndarray]]:
        """Per order-by column: an int64 ascending-composite sort key
        (null bucket ∘ direction-adjusted dense rank from sorted-unique)
        and a tie id.  Tie semantics preserved from the comparator path:
        None ties with None, float NaN never ties (each NaN is its own
        rank group).  Non-comparable (mixed-type) columns raise
        ``_WindowFallback``."""
        keys: list[np.ndarray] = []
        ties: list[np.ndarray] = []
        for sx in self.order_by:
            vals = np.atleast_1d(sx.expr.eval(batch))
            kind = vals.dtype.kind
            nan_rows = None
            if kind in "iub":
                null = np.zeros(n, dtype=bool)
            elif kind == "f":
                null = np.isnan(vals)
                nan_rows = np.nonzero(null)[0]
            elif kind == "M":
                null = np.isnat(vals)
            else:
                lst = vals.tolist()
                none_mask = np.fromiter(
                    (v is None for v in lst), dtype=bool, count=n
                )
                nan_mask = np.fromiter(
                    (isinstance(v, float) and v != v for v in lst),
                    dtype=bool,
                    count=n,
                )
                null = none_mask | nan_mask
                nan_rows = np.nonzero(nan_mask)[0]
            nn = ~null
            try:
                uniq, inv = np.unique(vals[nn], return_inverse=True)
            except TypeError:
                raise _WindowFallback from None
            nv = len(uniq)
            r = np.zeros(n, dtype=np.int64)
            r[nn] = inv if sx.ascending else (nv - 1) - inv
            # final null placement follows nulls_first regardless of
            # direction (matching the comparator path's null_rank logic)
            bucket = np.where(null, 0 if sx.nulls_first else 2, 1)
            keys.append(bucket.astype(np.int64) * (nv + 1) + r)
            tie = np.full(n, -1, dtype=np.int64)  # -1: the shared None tie
            tie[nn] = inv
            if nan_rows is not None and len(nan_rows):
                tie[nan_rows] = -2 - nan_rows  # NaN: unique per row
            ties.append(tie)
        return keys, ties

    def eval(self, batch: RecordBatch) -> np.ndarray:
        n = batch.num_rows
        try:
            pids = self._partition_ids(batch, n)
            okeys, oties = self._order_keys_vec(batch, n)
        except _WindowFallback:
            return self._eval_python(batch)
        # one stable lexsort: partition primary, order-by keys within —
        # ties keep arrival order, exactly like the stable comparator sort
        sidx = np.lexsort(tuple(reversed(okeys)) + (pids,))
        ps = pids[sidx]
        pstart = np.empty(n, dtype=bool)
        pstart[:1] = True
        pstart[1:] = ps[1:] != ps[:-1]
        newk = pstart.copy()  # order-key change OR partition change
        for t in oties:
            tt = t[sidx]
            newk[1:] |= tt[1:] != tt[:-1]
        pb = np.nonzero(pstart)[0]
        plens = np.diff(np.append(pb, n))
        base = np.repeat(pb, plens)  # partition start per sorted position
        karr = np.repeat(plens, plens)  # partition size per sorted position
        j = np.arange(n) - base  # 0-based position within partition
        w = self.wname
        if w == "row_number":
            res = j + 1
        elif w == "rank":
            res = (
                np.maximum.accumulate(np.where(newk, np.arange(n), 0))
                - base
                + 1
            )
        elif w == "dense_rank":
            c = np.cumsum(newk)
            res = c - np.repeat(c[pb] - 1, plens)
        elif w == "percent_rank":
            rank = (
                np.maximum.accumulate(np.where(newk, np.arange(n), 0))
                - base
                + 1
            )
            res = np.where(
                karr > 1, (rank - 1) / np.maximum(karr - 1, 1), 0.0
            )
        elif w == "cume_dist":
            tb = np.nonzero(newk)[0]
            tlens = np.diff(np.append(tb, n))
            tie_last = np.repeat(tb + tlens - 1, tlens)
            res = (tie_last - base + 1) / karr
        elif w == "ntile":
            # SQL NTILE: the first (k mod n) buckets hold ceil(k/n) rows,
            # the rest floor(k/n) — consecutive bucket ids even when
            # rows < buckets
            nb = int(self.params[0])
            big = karr // nb + 1
            r_big = karr % nb
            small = np.maximum(karr // nb, 1)  # guarded: branch unused at k<nb
            res = np.where(
                j < r_big * big,
                j // big + 1,
                r_big + (j - r_big * big) // small + 1,
            )
        elif w in ("lead", "lag"):
            offset, default = self.params
            shift = offset if w == "lead" else -offset
            vals = np.atleast_1d(self.args[0].eval(batch))
            vs = vals[sidx]
            src = j + shift
            ok = (src >= 0) & (src < karr)
            res = np.empty(n, dtype=object)
            res[:] = default
            res[ok] = vs[(np.arange(n) + shift)[ok]]
        else:
            raise PlanError(f"unknown window function {w!r}")
        out = np.empty(n, dtype=object)
        out[sidx] = res
        # densify numeric results
        try:
            tight = np.asarray(out.tolist())
            if tight.dtype.kind in "ifb":
                return tight
        except (ValueError, TypeError):
            pass
        return out

    def _eval_python(self, batch: RecordBatch) -> np.ndarray:
        """Comparator-based fallback for order/partition columns numpy
        cannot sort (mixed non-comparable objects) — the pre-vectorization
        implementation, kept verbatim."""
        n = batch.num_rows
        # partition ids
        if self.partition_by:
            pcols = [
                np.atleast_1d(e.eval(batch)).tolist()
                for e in self.partition_by
            ]
            pkeys = [tuple(c[i] for c in pcols) for i in range(n)]
        else:
            pkeys = [()] * n
        order_idx, okeys = (
            self._order_index(batch)
            if self.order_by
            else (np.arange(n, dtype=np.int64), [()] * n)
        )
        # group ordered rows by partition
        parts: dict = {}
        for pos in order_idx.tolist():
            parts.setdefault(pkeys[pos], []).append(pos)
        out = np.empty(n, dtype=object)
        for rows in parts.values():
            self._eval_partition(rows, okeys, batch, out)
        # densify numeric results
        try:
            tight = np.asarray(out.tolist())
            if tight.dtype.kind in "ifb":
                return tight
        except (ValueError, TypeError):
            pass
        return out

    def _eval_partition(self, rows, okeys, batch, out) -> None:
        k = len(rows)
        w = self.wname
        if w == "row_number":
            for j, r in enumerate(rows):
                out[r] = j + 1
            return
        if w in ("rank", "dense_rank", "percent_rank", "cume_dist"):
            rank = 0
            dense = 0
            ranks = np.empty(k, np.int64)
            for j, r in enumerate(rows):
                if j == 0 or okeys[r] != okeys[rows[j - 1]]:
                    rank = j + 1
                    dense += 1
                ranks[j] = dense if w == "dense_rank" else rank
            if w in ("rank", "dense_rank"):
                for j, r in enumerate(rows):
                    out[r] = int(ranks[j])
                return
            if w == "percent_rank":
                for j, r in enumerate(rows):
                    out[r] = 0.0 if k <= 1 else (ranks[j] - 1) / (k - 1)
                return
            # cume_dist: fraction of rows with key <= current
            last_of_key = {}
            for j, r in enumerate(rows):
                last_of_key[okeys[r]] = j
            for j, r in enumerate(rows):
                out[r] = (last_of_key[okeys[r]] + 1) / k
            return
        if w == "ntile":
            # SQL NTILE: the first (k mod n) buckets hold ceil(k/n) rows,
            # the rest floor(k/n) — consecutive bucket ids even when
            # rows < buckets
            n_buckets = int(self.params[0])
            big = k // n_buckets + 1
            r_big = k % n_buckets
            for j, r in enumerate(rows):
                if j < r_big * big:
                    out[r] = j // big + 1
                else:
                    out[r] = r_big + (j - r_big * big) // (k // n_buckets) + 1
            return
        if w in ("lead", "lag"):
            offset, default = self.params
            vals = np.atleast_1d(self.args[0].eval(batch))
            shift = offset if w == "lead" else -offset
            for j, r in enumerate(rows):
                src = j + shift
                out[r] = (
                    _scalarize(vals[rows[src]])
                    if 0 <= src < k
                    else default
                )
            return
        raise PlanError(f"unknown window function {w!r}")

    def __repr__(self):
        return self.name


class _WindowFallback(Exception):
    """Signal: this batch's keys need the comparator-based Python path."""


class _SortKey:
    """Total-order wrapper: mixed / non-comparable values fall back to
    string comparison instead of raising mid-projection."""

    __slots__ = ("v", "isnull")

    def __init__(self, v, isnull):
        self.v = v
        self.isnull = isnull

    def __lt__(self, other):
        if self.isnull or other.isnull:
            return False  # null bucket already separated by the tuple
        try:
            return self.v < other.v
        except TypeError:
            return str(self.v) < str(other.v)

    def __eq__(self, other):
        return self.v == other.v


def _scalarize(v):
    return v.item() if isinstance(v, np.generic) else v


@dataclass(frozen=True, eq=False)
class FieldAccessExpr(Expr):
    inner: Expr
    field_name: str

    @property
    def name(self) -> str:
        return f"{self.inner.name}.{self.field_name}"

    def out_field(self, schema: Schema) -> Field:
        f = self.inner.out_field(schema)
        if f.dtype is not DataType.STRUCT:
            raise SchemaError(f"{f.name!r} is not a struct")
        for c in f.children:
            if c.name == self.field_name:
                return Field(self.name, c.dtype, c.nullable, c.children)
        raise SchemaError(f"struct {f.name!r} has no field {self.field_name!r}")

    def eval(self, batch: RecordBatch) -> np.ndarray:
        from denormalized_tpu.common.columns import (
            NestedColumn,
            PrimitiveColumn,
            as_numpy,
        )

        structs = self.inner.eval(batch)
        if (
            isinstance(structs, NestedColumn)
            and structs.kind == "struct"
            and structs.validity is None
        ):
            # shredded access: the child column IS the answer — no row
            # materialization.  (A null parent struct must surface None
            # for every child, which only the row path models; the
            # all-present case — the normal one — stays columnar.)
            for f, child in zip(structs.field.children, structs.children):
                if f.name == self.field_name:
                    if isinstance(child, PrimitiveColumn):
                        if child.validity is not None:
                            return child.as_object()
                        # densified exactly like the legacy tight path
                        return (
                            child.values.view(np.bool_)
                            if child.kind == "bool" else child.values
                        )
                    return child
        structs = as_numpy(structs)  # object array of dicts
        out = np.empty(len(structs), dtype=object)
        for i, s in enumerate(structs):
            out[i] = None if s is None else s.get(self.field_name)
        # densify numerics
        try:
            tight = np.asarray(out.tolist())
            if tight.dtype.kind in "ifb":
                return tight
        except (ValueError, TypeError):
            pass
        return out

    def columns_referenced(self) -> set[str]:
        return self.inner.columns_referenced()

    def __repr__(self):
        return f"{self.inner!r}.field({self.field_name!r})"


@dataclass(frozen=True, eq=False)
class CastExpr(Expr):
    inner: Expr
    dtype: DataType

    @property
    def name(self) -> str:
        return self.inner.name

    def out_field(self, schema: Schema) -> Field:
        f = self.inner.out_field(schema)
        return Field(f.name, self.dtype, f.nullable)

    def eval(self, batch: RecordBatch) -> np.ndarray:
        from denormalized_tpu.common.columns import StringColumn, as_numpy

        v = self.inner.eval(batch)
        if self.dtype is DataType.STRING:
            if isinstance(v, StringColumn) and v.validity is None:
                # already columnar strings with no nulls: identity cast
                # (null slots legacy-cast to the string 'None', so they
                # take the materializing path below)
                return v
            return np.array([str(x) for x in as_numpy(v)], dtype=object)
        return np.asarray(v).astype(self.dtype.to_numpy())

    def eval_jax(self, cols):
        import jax.numpy as jnp

        jdt = {
            DataType.INT32: jnp.int32,
            DataType.INT64: jnp.int32,  # device stays 32-bit unless x64 on
            DataType.FLOAT32: jnp.float32,
            DataType.FLOAT64: jnp.float32,
            DataType.BOOL: jnp.bool_,
        }.get(self.dtype)
        if jdt is None:
            raise PlanError(f"cannot cast to {self.dtype} on device")
        return self.inner.eval_jax(cols).astype(jdt)

    def columns_referenced(self) -> set[str]:
        return self.inner.columns_referenced()


@dataclass(frozen=True, eq=False)
class ScalarFunctionExpr(Expr):
    """Built-in scalar function call (registry:
    :mod:`denormalized_tpu.logical.scalar_functions` — the equivalent of the
    datafusion function library the reference re-exports,
    py-denormalized/python/denormalized/datafusion/functions.py)."""

    fname: str
    args: tuple[Expr, ...]

    def _fn(self):
        from denormalized_tpu.logical import scalar_functions as sf

        return sf.lookup(self.fname)

    @property
    def name(self) -> str:
        return f"{self.fname}({', '.join(a.name for a in self.args)})"

    def out_field(self, schema: Schema) -> Field:
        ot = self._fn().out_type
        if ot == "same":
            if not self.args:
                raise PlanError(f"{self.fname} needs arguments")
            f0 = self.args[0].out_field(schema)
            return Field(self.name, f0.dtype)
        if callable(ot) and not isinstance(ot, DataType):
            # computed output type: LIST/STRUCT functions derive element /
            # child fields from their argument fields
            f = ot(tuple(a.out_field(schema) for a in self.args))
            return Field(self.name, f.dtype, f.nullable, f.children)
        return Field(self.name, ot)

    def eval(self, batch: RecordBatch) -> np.ndarray:
        fn = self._fn()
        # domain errors (sqrt(-x), log(0)) follow SQL NaN/NULL semantics —
        # no warnings
        with np.errstate(invalid="ignore", divide="ignore"):
            if fn.rowwise_nullary:
                # per-row zero-arg functions (random, uuid) need the row
                # count — a broadcast scalar would repeat one draw
                out = fn.np_fn(batch.num_rows)
            else:
                from denormalized_tpu.common.columns import as_numpy

                # scalar functions are a user-facing value boundary:
                # columnar string/nested args materialize (cached) here
                out = fn.np_fn(
                    *[as_numpy(a.eval(batch)) for a in self.args]
                )
        if not isinstance(out, np.ndarray):
            out = np.asarray(out)
        if out.ndim == 0:  # zero-arg / scalar result → broadcast
            out = np.full(batch.num_rows, out.item())
        return out

    def eval_jax(self, cols: dict[str, Any]):
        fn = self._fn()
        if fn.jax_fn is None:
            raise PlanError(f"{self.fname} is host-only (no device lowering)")
        return fn.jax_fn(*[a.eval_jax(cols) for a in self.args])

    def columns_referenced(self) -> set[str]:
        s: set[str] = set()
        for a in self.args:
            s |= a.columns_referenced()
        return s

    def __repr__(self):
        return f"{self.fname}({', '.join(map(repr, self.args))})"


@dataclass(frozen=True, eq=False)
class CaseExpr(Expr):
    """SQL CASE.  ``base`` None → searched form (WHEN <bool-cond> THEN r);
    otherwise the simple form (WHEN base == value THEN r)."""

    base: Expr | None
    branches: tuple[tuple[Expr, Expr], ...]
    otherwise: Expr | None

    @property
    def name(self) -> str:
        return "case(" + ", ".join(
            f"{c.name}->{r.name}" for c, r in self.branches
        ) + ")"

    def out_field(self, schema: Schema) -> Field:
        dt = self.branches[0][1].out_field(schema).dtype
        for _, r in self.branches[1:]:
            dt = _promote(dt, r.out_field(schema).dtype, "case")
        if self.otherwise is not None:
            dt = _promote(dt, self.otherwise.out_field(schema).dtype, "case")
        return Field(self.name, dt)

    def _conds(self, batch):
        for c, _ in self.branches:
            if self.base is not None:
                yield BinaryExpr("==", self.base, c).eval(batch)
            else:
                yield np.asarray(c.eval(batch), dtype=bool)

    def eval(self, batch: RecordBatch) -> np.ndarray:
        conds = list(self._conds(batch))
        results = [np.asarray(r.eval(batch)) for _, r in self.branches]
        is_obj = any(r.dtype == object for r in results)
        if self.otherwise is not None:
            default = np.asarray(self.otherwise.eval(batch))
            is_obj = is_obj or default.dtype == object
        else:
            default = None
        n = batch.num_rows
        if is_obj:
            out = np.empty(n, dtype=object)
            out[:] = None
            taken = np.zeros(n, dtype=bool)
            for cond, res in zip(conds, results):
                pick = cond & ~taken
                out[pick] = res[pick] if res.ndim else res.item()
                taken |= cond
            if default is not None:
                rest = ~taken
                out[rest] = (
                    default[rest] if default.ndim else default.item()
                )
            return out
        if default is None:
            default = np.full(n, np.nan)
        return np.select(conds, results, default)

    def eval_jax(self, cols: dict[str, Any]):
        import jax.numpy as jnp

        if self.otherwise is not None:
            out = self.otherwise.eval_jax(cols)
        else:
            out = jnp.nan
        for c, r in reversed(self.branches):
            if self.base is not None:
                cond = BinaryExpr("==", self.base, c).eval_jax(cols)
            else:
                cond = c.eval_jax(cols)
            out = jnp.where(cond, r.eval_jax(cols), out)
        return out

    def columns_referenced(self) -> set[str]:
        s: set[str] = set()
        if self.base is not None:
            s |= self.base.columns_referenced()
        for c, r in self.branches:
            s |= c.columns_referenced() | r.columns_referenced()
        if self.otherwise is not None:
            s |= self.otherwise.columns_referenced()
        return s

    def __repr__(self):
        return self.name


class CaseBuilder:
    """Fluent CASE builder (datafusion-python `case(...)`/`when(...)`)."""

    def __init__(self, base: Expr | None = None):
        self._base = base
        self._branches: list[tuple[Expr, Expr]] = []

    def when(self, cond, result) -> "CaseBuilder":
        self._branches.append((_wrap(cond), _wrap(result)))
        return self

    def otherwise(self, value) -> CaseExpr:
        if not self._branches:
            raise PlanError("CASE needs at least one WHEN branch")
        return CaseExpr(self._base, tuple(self._branches), _wrap(value))

    def end(self) -> CaseExpr:
        if not self._branches:
            raise PlanError("CASE needs at least one WHEN branch")
        return CaseExpr(self._base, tuple(self._branches), None)


@dataclass(frozen=True, eq=False)
class ScalarUDFExpr(Expr):
    """User-defined scalar function over numpy columns (reference:
    udf_example.rs + py udf.py)."""

    fn: Callable
    args: tuple[Expr, ...]
    _name: str
    dtype: DataType

    @property
    def name(self) -> str:
        return self._name

    def out_field(self, schema: Schema) -> Field:
        return Field(self._name, self.dtype)

    def eval(self, batch: RecordBatch) -> np.ndarray:
        from denormalized_tpu.common.columns import as_numpy

        # the UDF boundary: user code sees plain numpy columns
        return np.asarray(
            self.fn(*[as_numpy(a.eval(batch)) for a in self.args])
        )

    def eval_jax(self, cols):
        return self.fn(*[a.eval_jax(cols) for a in self.args])

    def columns_referenced(self) -> set[str]:
        s: set[str] = set()
        for a in self.args:
            s |= a.columns_referenced()
        return s

    def __repr__(self):
        return f"{self._name}({', '.join(map(repr, self.args))})"


# -- aggregates ---------------------------------------------------------

AGG_KINDS = (
    "count", "sum", "min", "max", "avg",
    # variance family: decomposes into sum/count/sum-of-squares components
    # on device (DataFusion exposes these through the reference's vendored
    # functions module)
    "stddev", "stddev_pop", "var", "var_pop",
)
VAR_KINDS = ("stddev", "stddev_pop", "var", "var_pop")

#: sketch-backed approximate aggregates — first-class mergeable kinds
#: that plan onto the slice store (ops/sketches.py planes) when the
#: multi-query slice path is on, and lower to their exact/UDAF fallback
#: accumulators otherwise (see planner._lower_sketch_aggs)
SKETCH_AGG_KINDS = (
    "approx_distinct", "approx_top_k",
    "approx_percentile_cont", "approx_median",
)


@dataclass(frozen=True, eq=False)
class AggregateExpr(Expr):
    """An aggregate call inside window(): count/sum/min/max/avg or a UDAF."""

    kind: str  # one of AGG_KINDS, SKETCH_AGG_KINDS, or "udaf"
    arg: Expr | None  # None for count(*)
    _alias: str | None = None
    udaf: Any = None  # api.udaf.UDAF instance when kind == "udaf";
    # for SKETCH_AGG_KINDS: the exact/UDAF fallback accumulator the
    # planner lowers to off the slice path
    params: tuple = ()  # sketch kind parameters (k, quantile q, ...)

    @property
    def name(self) -> str:
        if self._alias:
            return self._alias
        argname = self.arg.name if self.arg is not None else "*"
        if self.kind == "approx_percentile_cont" and self.params:
            return f"{self.kind}({argname}, {self.params[0]})"
        if self.kind == "approx_top_k" and self.params:
            return f"{self.kind}({argname}, {self.params[0]})"
        return f"{self.kind}({argname})"

    def alias(self, name: str) -> "AggregateExpr":
        return AggregateExpr(self.kind, self.arg, name, self.udaf, self.params)

    def out_field(self, schema: Schema) -> Field:
        if self.kind == "count":
            return Field(self.name, DataType.INT64, nullable=False)
        if self.kind == "approx_distinct":
            return Field(self.name, DataType.INT64, nullable=False)
        if self.kind == "approx_top_k":
            # list of [value, count] pairs, count-descending
            return Field(self.name, DataType.LIST)
        if self.kind in ("approx_percentile_cont", "approx_median"):
            return Field(self.name, DataType.FLOAT64)
        if self.kind == "avg" or self.kind in VAR_KINDS:
            return Field(self.name, DataType.FLOAT64)
        if self.kind == "udaf":
            if self.udaf.return_type is None:  # same type as the argument
                return Field(self.name, self.arg.out_field(schema).dtype)
            return Field(self.name, self.udaf.return_type)
        f = self.arg.out_field(schema)
        if self.kind == "sum":
            if f.dtype in (DataType.INT32, DataType.INT64, DataType.BOOL):
                return Field(self.name, DataType.INT64)
            return Field(self.name, DataType.FLOAT64)
        return Field(self.name, f.dtype)

    def eval(self, batch: RecordBatch) -> np.ndarray:
        raise PlanError("aggregate expression outside window()")

    def columns_referenced(self) -> set[str]:
        return self.arg.columns_referenced() if self.arg is not None else set()

    def __repr__(self):
        return self.name


def substitute_columns(e: Expr, mapping: dict[str, Expr]) -> Expr:
    """Rewrite ``e`` with every Column reference replaced by its mapped
    expression (used by the optimizer to merge stacked projections and push
    filters beneath them).  Nodes are immutable, so untouched subtrees are
    reused as-is."""
    if isinstance(e, Column):
        return mapping.get(e.name, e)
    if isinstance(e, Literal):
        return e
    if isinstance(e, BinaryExpr):
        return BinaryExpr(
            e.op,
            substitute_columns(e.left, mapping),
            substitute_columns(e.right, mapping),
        )
    if isinstance(e, NotExpr):
        return NotExpr(substitute_columns(e.inner, mapping))
    if isinstance(e, IsNullExpr):
        return IsNullExpr(substitute_columns(e.inner, mapping), e.negate)
    if isinstance(e, AliasExpr):
        return AliasExpr(substitute_columns(e.inner, mapping), e._name)
    if isinstance(e, FieldAccessExpr):
        return FieldAccessExpr(
            substitute_columns(e.inner, mapping), e.field_name
        )
    if isinstance(e, CastExpr):
        return CastExpr(substitute_columns(e.inner, mapping), e.dtype)
    if isinstance(e, ScalarFunctionExpr):
        return ScalarFunctionExpr(
            e.fname,
            tuple(substitute_columns(a, mapping) for a in e.args),
        )
    if isinstance(e, ScalarUDFExpr):
        return ScalarUDFExpr(
            e.fn,
            tuple(substitute_columns(a, mapping) for a in e.args),
            e._name,
            e.dtype,
        )
    if isinstance(e, CaseExpr):
        return CaseExpr(
            substitute_columns(e.base, mapping) if e.base is not None else None,
            tuple(
                (
                    substitute_columns(c, mapping),
                    substitute_columns(r, mapping),
                )
                for c, r in e.branches
            ),
            substitute_columns(e.otherwise, mapping)
            if e.otherwise is not None
            else None,
        )
    raise PlanError(f"cannot substitute through {type(e).__name__}")


def column_validity(e: Expr, batch: RecordBatch) -> np.ndarray | None:
    """Row validity of an expression's output: the AND of the null masks of
    every column it reads (derived columns — e.g. variance's shifted
    moments — inherit their source columns' nulls).  None = all valid."""
    m = None
    refs = (e.name,) if isinstance(e, Column) else e.columns_referenced()
    for ref in refs:
        rm = batch.mask(ref) if batch.schema.has(ref) else None
        if rm is not None:
            m = rm if m is None else (m & rm)
    return m


# -- public constructors (mirror datafusion-python functions module) -----


def col(name: str) -> Expr:
    return Column(name)


def lit(value) -> Expr:
    return Literal(value)
