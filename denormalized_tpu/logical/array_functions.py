"""LIST / STRUCT scalar function family.

The reference re-exports datafusion's array manipulation library to Python
users (py-denormalized/python/denormalized/datafusion/functions.py:1029-1502
— ``make_array``, ``array_append`` … ``flatten``, each with a ``list_*``
alias).  This module is the host-side equivalent over first-class LIST
columns: a LIST column is an object ndarray whose slots are python lists
(or None for SQL NULL), and the element type — when known — rides in the
schema as ``Field(children=(element_field,))``.

Everything here is host-only by design: ragged per-row lists have no
static shape, so they stay off the device the same way strings do (they
are projection/emission payload, not aggregation state).  Semantics follow
DataFusion: 1-based indexing, NULL propagation on NULL list arguments,
``array_position`` returning NULL when absent.
"""

from __future__ import annotations

import numpy as np

from denormalized_tpu.common.errors import PlanError
from denormalized_tpu.common.schema import DataType, Field
from denormalized_tpu.logical.expr import _scalarize

_I64 = DataType.INT64
_STR = DataType.STRING
_BOOL = DataType.BOOL


# -- value plumbing ------------------------------------------------------


def _as_list(x):
    """Normalize one cell to a python list (None stays None)."""
    if x is None:
        return None
    if isinstance(x, np.ndarray):
        return x.tolist()
    if isinstance(x, (list, tuple)):
        return list(x)
    return [x]


def _cells(*arrays):
    """Iterate rows across argument arrays with length-1 broadcast (the
    convention literals arrive in)."""
    cols = [np.atleast_1d(np.asarray(a, dtype=object)) for a in arrays]
    n = max(len(c) for c in cols)
    for i in range(n):
        yield [_scalarize(c[i] if len(c) > 1 else c[0]) for c in cols]


def _rowwise(fn, n_out_type=object):
    """Build an np_fn applying ``fn`` per row; None list arg → None out
    is each fn's own responsibility (most want NULL propagation)."""

    def run(*arrays):
        rows = list(_cells(*arrays))
        out = np.empty(len(rows), dtype=n_out_type)
        for i, vals in enumerate(rows):
            out[i] = fn(*vals)
        return out

    return run


# -- output-type helpers (computed Field from argument fields) -----------


def _elem_field(list_field: Field) -> Field:
    if list_field.children:
        return list_field.children[0]
    return Field("item", _STR)


def _ot_list_of(element_dtype_from: int):
    """LIST whose element type is argument ``element_dtype_from``'s type."""

    def ot(arg_fields):
        if not arg_fields:
            return Field("", DataType.LIST, children=(Field("item", _STR),))
        f = arg_fields[min(element_dtype_from, len(arg_fields) - 1)]
        return Field("", DataType.LIST, children=(Field("item", f.dtype),))

    return ot


def _ot_list_i64(_arg_fields):
    """LIST<INT64> regardless of input (positions, dims)."""
    return Field("", DataType.LIST, children=(Field("item", _I64),))


def _ot_list_passthrough(idx: int = 0):
    """LIST with the same element type as the LIST argument at ``idx``."""

    def ot(arg_fields):
        if len(arg_fields) > idx and arg_fields[idx].dtype is DataType.LIST:
            return arg_fields[idx]
        return Field("", DataType.LIST, children=(Field("item", _STR),))

    return ot


def _ot_element(idx: int = 0):
    """The element type of the LIST argument at ``idx``."""

    def ot(arg_fields):
        if len(arg_fields) > idx and arg_fields[idx].dtype is DataType.LIST:
            return _elem_field(arg_fields[idx])
        return Field("", _STR)

    return ot


def _ot_struct(arg_fields):
    """STRUCT for ``struct(*cols)``: children c0..cN of the arg types."""
    return Field(
        "",
        DataType.STRUCT,
        children=tuple(
            Field(f"c{i}", f.dtype) for i, f in enumerate(arg_fields)
        ),
    )


def _ot_named_struct(arg_fields):
    """STRUCT for ``named_struct(name0, v0, ...)``: names come from the
    literal name arguments, types from the value arguments."""
    kids = []
    for i in range(0, len(arg_fields) - 1, 2):
        # the name is a literal; its *value* is not visible here, so the
        # child is named positionally and refined at eval time — schema
        # consumers see the value TYPES, which is what matters for layout
        kids.append(Field(f"f{i // 2}", arg_fields[i + 1].dtype))
    return Field("", DataType.STRUCT, children=tuple(kids))


# -- constructors --------------------------------------------------------


def _make_array(*arrays):
    rows = list(_cells(*arrays))
    out = np.empty(len(rows), dtype=object)
    for i, vals in enumerate(rows):
        out[i] = list(vals)
    return out


def _range(*arrays):
    def one(start, stop=None, step=1):
        if stop is None:
            start, stop = 0, start
        if start is None or stop is None or step in (None, 0):
            return None
        return list(range(int(start), int(stop), int(step)))

    return _rowwise(one)(*arrays)


def _struct(*arrays):
    rows = list(_cells(*arrays))
    out = np.empty(len(rows), dtype=object)
    for i, vals in enumerate(rows):
        out[i] = {f"c{j}": v for j, v in enumerate(vals)}
    return out


def _named_struct(*arrays):
    rows = list(_cells(*arrays))
    out = np.empty(len(rows), dtype=object)
    for i, vals in enumerate(rows):
        if len(vals) % 2:
            raise PlanError(
                "named_struct takes name/value pairs (odd argument count)"
            )
        out[i] = {
            str(vals[j]): vals[j + 1] for j in range(0, len(vals), 2)
        }
    return out


# -- per-row list ops ----------------------------------------------------


def _null_prop(fn):
    """First argument is the list; None → None."""

    def run(arr, *rest):
        a = _as_list(arr)
        return None if a is None else fn(a, *rest)

    return run


def _eq(a, b):
    # NaN-insensitive equality would surprise; match python/DF semantics
    return a == b


def _array_position(a, el, start=1):
    start = 1 if start is None else int(start)
    for i in range(max(start - 1, 0), len(a)):
        if _eq(a[i], el):
            return i + 1
    return None


def _array_slice(a, begin, end, stride=None):
    # DataFusion: 1-based inclusive begin..end; negative indexes from the
    # end; stride defaults to 1
    n = len(a)
    if begin is None or end is None:
        return None
    begin = int(begin)
    end = int(end)
    if begin < 0:
        begin = n + begin + 1
    if end < 0:
        end = n + end + 1
    begin = max(begin, 1)
    end = min(end, n)
    step = 1 if stride is None else int(stride)
    if step == 0:
        return None
    if step > 0:
        return a[begin - 1 : end : step]
    return a[begin - 1 : None if end <= 1 else end - 2 : step]


def _array_sort(a, descending=False, nulls_first=False):
    desc = _truthy(descending)
    nf = _truthy(nulls_first)
    nulls = [v for v in a if v is None]
    rest = sorted((v for v in a if v is not None), reverse=desc)
    return nulls + rest if nf else rest + nulls


def _truthy(v) -> bool:
    if isinstance(v, str):
        return v.lower() in ("true", "t", "1", "yes", "desc")
    return bool(v)


def _array_to_string(arr, delim, null_str=None):
    a = _as_list(arr)
    if a is None or delim is None:
        return None
    parts = []
    for v in a:
        if isinstance(v, (list, tuple)):  # nested lists flatten (DF)
            inner = _array_to_string(v, delim, null_str)
            if inner:
                parts.append(inner)
        elif v is None:
            if null_str is not None:
                parts.append(str(null_str))
        else:
            parts.append(_fmt_el(v))
    return str(delim).join(parts)


def _fmt_el(v) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, float) and v.is_integer():
        return str(int(v))
    return str(v)


def _dedup(a):
    seen = []
    for v in a:
        if not any(_eq(v, s) for s in seen):
            seen.append(v)
    return seen


def _resize(a, size, fill=None):
    if size is None:
        return None
    size = int(size)
    return a[:size] + [fill] * max(0, size - len(a))


def _remove_n(a, el, n):
    out = []
    left = int(n)
    for v in a:
        if left > 0 and _eq(v, el):
            left -= 1
            continue
        out.append(v)
    return out


def _replace_n(a, f, t, n):
    out = []
    left = int(n)
    for v in a:
        if left > 0 and _eq(v, f):
            out.append(t)
            left -= 1
        else:
            out.append(v)
    return out


def _flatten(a):
    out = []
    for v in a:
        if isinstance(v, (list, tuple, np.ndarray)):
            out.extend(_as_list(v))
        else:
            out.append(v)
    return out


def _array_concat(*arrays):
    def one(*lists):
        out = []
        for x in lists:
            a = _as_list(x)
            if a is None:
                return None
            out.extend(a)
        return out

    return _rowwise(one)(*arrays)


def _ndims(v):
    d = 0
    while isinstance(v, (list, tuple, np.ndarray)):
        d += 1
        v = v[0] if len(v) else None
    return d


def _regexp_match(*arrays):
    """Postgres/DataFusion regexp_match: capture groups of the FIRST
    match as a LIST of strings (the whole match when the pattern has no
    groups); NULL when no match."""
    from denormalized_tpu.logical.scalar_functions import _regex

    def one(s, pattern, flags=""):
        if s is None or pattern is None:
            return None
        m = _regex(pattern, flags or "").search(s)
        if m is None:
            return None
        return list(m.groups()) if m.groups() else [m.group(0)]

    return _rowwise(one)(*arrays)


def _build() -> dict:
    from denormalized_tpu.logical.scalar_functions import ScalarFn

    def F(np_fn, out_type, min_args=1, max_args=None):
        return ScalarFn(np_fn, out_type, None, min_args, max_args)

    fns: dict[str, ScalarFn] = {
        "make_array": F(_make_array, _ot_list_of(0), 0, 64),
        "array": F(_make_array, _ot_list_of(0), 0, 64),
        "range": F(_range, _ot_list_of(0), 1, 3),
        "struct": F(_struct, _ot_struct, 1, 64),
        "named_struct": F(_named_struct, _ot_named_struct, 2, 64),
        "regexp_match": F(
            _regexp_match,
            lambda _f: Field("", DataType.LIST,
                             children=(Field("item", _STR),)),
            2, 3,
        ),
        "flatten": F(
            _rowwise(_null_prop(_flatten)), _ot_list_passthrough(), 1
        ),
        "array_concat": F(_array_concat, _ot_list_passthrough(), 1, 64),
        "array_append": F(
            _rowwise(_null_prop(lambda a, el: a + [el])),
            _ot_list_passthrough(), 2,
        ),
        "array_prepend": F(
            _rowwise(lambda el, arr: (
                None if _as_list(arr) is None else [el] + _as_list(arr)
            )),
            _ot_list_passthrough(1), 2,
        ),
        "array_pop_back": F(
            _rowwise(_null_prop(lambda a: a[:-1])), _ot_list_passthrough(), 1
        ),
        "array_pop_front": F(
            _rowwise(_null_prop(lambda a: a[1:])), _ot_list_passthrough(), 1
        ),
        "array_dims": F(
            _rowwise(_null_prop(
                lambda a: _dims_of(a)
            )),
            _ot_list_i64, 1,
        ),
        "array_ndims": F(
            _rowwise(lambda arr: (
                None if _as_list(arr) is None else _ndims(_as_list(arr))
            )),
            _I64, 1,
        ),
        "array_distinct": F(
            _rowwise(_null_prop(_dedup)), _ot_list_passthrough(), 1
        ),
        "array_element": F(
            _rowwise(lambda arr, n: _element(arr, n)), _ot_element(), 2
        ),
        "array_length": F(
            _rowwise(lambda arr: (
                None if _as_list(arr) is None else len(_as_list(arr))
            )),
            _I64, 1, 2,
        ),
        "array_has": F(
            _rowwise(lambda arr, el: (
                None if _as_list(arr) is None
                else any(_eq(v, el) for v in _as_list(arr))
            )),
            _BOOL, 2,
        ),
        "array_has_all": F(
            _rowwise(lambda arr, sub: _has_all(arr, sub)), _BOOL, 2
        ),
        "array_has_any": F(
            _rowwise(lambda arr, other: _has_any(arr, other)), _BOOL, 2
        ),
        "array_position": F(
            _rowwise(_null_prop(_array_position)), _I64, 2, 3
        ),
        "array_positions": F(
            _rowwise(_null_prop(lambda a, el: [
                i + 1 for i, v in enumerate(a) if _eq(v, el)
            ])),
            _ot_list_i64, 2,
        ),
        "array_remove": F(
            _rowwise(_null_prop(lambda a, el: _remove_n(a, el, 1))),
            _ot_list_passthrough(), 2,
        ),
        "array_remove_n": F(
            _rowwise(_null_prop(_remove_n)), _ot_list_passthrough(), 3
        ),
        "array_remove_all": F(
            _rowwise(_null_prop(
                lambda a, el: [v for v in a if not _eq(v, el)]
            )),
            _ot_list_passthrough(), 2,
        ),
        "array_repeat": F(
            _rowwise(lambda el, n: (
                None if n is None else [el] * max(int(n), 0)
            )),
            _ot_list_of(0), 2,
        ),
        "array_replace": F(
            _rowwise(_null_prop(lambda a, f, t: _replace_n(a, f, t, 1))),
            _ot_list_passthrough(), 3,
        ),
        "array_replace_n": F(
            _rowwise(_null_prop(_replace_n)), _ot_list_passthrough(), 4
        ),
        "array_replace_all": F(
            _rowwise(_null_prop(
                lambda a, f, t: [t if _eq(v, f) else v for v in a]
            )),
            _ot_list_passthrough(), 3,
        ),
        "array_resize": F(
            _rowwise(_null_prop(_resize)), _ot_list_passthrough(), 2, 3
        ),
        "array_slice": F(
            _rowwise(_null_prop(_array_slice)), _ot_list_passthrough(), 3, 4
        ),
        "array_sort": F(
            _rowwise(_null_prop(_array_sort)), _ot_list_passthrough(), 1, 3
        ),
        "array_to_string": F(_rowwise(_array_to_string), _STR, 2, 3),
        "array_intersect": F(
            _rowwise(lambda a, b: _set_op(a, b, "intersect")),
            _ot_list_passthrough(), 2,
        ),
        "array_union": F(
            _rowwise(lambda a, b: _set_op(a, b, "union")),
            _ot_list_passthrough(), 2,
        ),
        "array_except": F(
            _rowwise(lambda a, b: _set_op(a, b, "except")),
            _ot_list_passthrough(), 2,
        ),
    }
    # the list_* namespace is a straight aliasing of array_* (reference
    # functions.py list_append:1096 etc.)
    aliases = {
        "list_append": "array_append",
        "list_push_back": "array_append",
        "array_push_back": "array_append",
        "list_prepend": "array_prepend",
        "list_push_front": "array_prepend",
        "array_push_front": "array_prepend",
        "array_cat": "array_concat",
        "list_cat": "array_concat",
        "list_concat": "array_concat",
        "list_dims": "array_dims",
        "list_distinct": "array_distinct",
        "list_element": "array_element",
        "array_extract": "array_element",
        "list_extract": "array_element",
        "list_indexof": "array_position",
        "array_indexof": "array_position",
        "list_position": "array_position",
        "list_positions": "array_positions",
        "list_join": "array_to_string",
        "array_join": "array_to_string",
        "list_to_string": "array_to_string",
        "list_length": "array_length",
        "list_ndims": "array_ndims",
        "list_pop_back": "array_pop_back",
        "list_pop_front": "array_pop_front",
        "list_remove": "array_remove",
        "list_remove_n": "array_remove_n",
        "list_remove_all": "array_remove_all",
        "list_replace": "array_replace",
        "list_replace_n": "array_replace_n",
        "list_replace_all": "array_replace_all",
        "list_resize": "array_resize",
        "list_slice": "array_slice",
        "list_sort": "array_sort",
        "list_intersect": "array_intersect",
        "list_union": "array_union",
        "list_except": "array_except",
        "list_has": "array_has",
        "list_has_all": "array_has_all",
        "list_has_any": "array_has_any",
    }
    for alias, target in aliases.items():
        fns[alias] = fns[target]
    return fns


def _dims_of(a):
    dims = []
    v = a
    while isinstance(v, (list, tuple, np.ndarray)):
        dims.append(len(v))
        v = v[0] if len(v) else None
    return dims


def _element(arr, n):
    a = _as_list(arr)
    if a is None or n is None:
        return None
    i = int(n)
    if i < 0:
        i = len(a) + i + 1
    if not 1 <= i <= len(a):
        return None
    return a[i - 1]


def _has_all(arr, sub):
    a, s = _as_list(arr), _as_list(sub)
    if a is None or s is None:
        return None
    return all(any(_eq(v, x) for v in a) for x in s)


def _has_any(arr, other):
    a, o = _as_list(arr), _as_list(other)
    if a is None or o is None:
        return None
    return any(any(_eq(v, x) for v in a) for x in o)


def _set_op(a, b, op):
    la, lb = _as_list(a), _as_list(b)
    if la is None or lb is None:
        return None
    if op == "intersect":
        return _dedup([v for v in la if any(_eq(v, x) for x in lb)])
    if op == "union":
        return _dedup(la + lb)
    return _dedup([v for v in la if not any(_eq(v, x) for x in lb)])


ARRAY_FNS = _build()
