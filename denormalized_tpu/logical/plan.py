"""Logical plan nodes.

The reference reuses DataFusion's ``LogicalPlan`` and adds one extension node,
``StreamingWindowPlanNode`` (crates/core/src/logical_plan/streaming_window.rs:15)
built by ``StreamingLogicalPlanBuilder::streaming_window``
(logical_plan/mod.rs:16-60).  We own the whole (much smaller) plan algebra:
Scan / Project / Filter / StreamingWindow / Join / Sink, each of which knows
its output schema eagerly — plan building touches no data (mirroring the lazy
construction at context.rs:65 / datastream.rs).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Any, Sequence

from denormalized_tpu.common.constants import (
    CANONICAL_TIMESTAMP_COLUMN,
    WINDOW_END_COLUMN,
    WINDOW_START_COLUMN,
)
from denormalized_tpu.common.errors import PlanError
from denormalized_tpu.common.schema import DataType, Field, Schema
from denormalized_tpu.logical.expr import AggregateExpr, Column, Expr


class LogicalPlan:
    schema: Schema

    @property
    def children(self) -> list["LogicalPlan"]:
        return []

    def display(self, indent: int = 0) -> str:
        line = "  " * indent + self._label()
        return "\n".join([line] + [c.display(indent + 1) for c in self.children])

    def _label(self) -> str:
        return type(self).__name__


@dataclass
class Scan(LogicalPlan):
    """Leaf: a registered streaming table (reference: TopicReader registered
    by Context::from_topic, context.rs:65-72)."""

    table_name: str
    source: Any  # sources.base.Source
    schema: Schema

    def _label(self) -> str:
        return f"Scan({self.table_name})"


@dataclass
class Project(LogicalPlan):
    input: LogicalPlan
    exprs: list[Expr]
    schema: Schema

    def __init__(self, input: LogicalPlan, exprs: Sequence[Expr]):
        self.input = input
        # internal metadata columns ride along implicitly, like the struct
        # column the reference preserves through every projection.
        self.exprs = list(exprs)
        fields = [e.out_field(input.schema) for e in self.exprs]
        names = [f.name for f in fields]
        for f in input.schema:
            if f.name == CANONICAL_TIMESTAMP_COLUMN and f.name not in names:
                fields.append(f)
                self.exprs.append(Column(f.name))
        self.schema = Schema(fields)

    @property
    def children(self):
        return [self.input]

    def _label(self):
        return f"Project({', '.join(e.name for e in self.exprs)})"


@dataclass
class Filter(LogicalPlan):
    input: LogicalPlan
    predicate: Expr
    schema: Schema = None  # type: ignore[assignment]

    def __post_init__(self):
        self.schema = self.input.schema

    @property
    def children(self):
        return [self.input]

    def _label(self):
        return f"Filter({self.predicate!r})"


class WindowType(enum.Enum):
    """Mirror of StreamingWindowType (streaming_window.rs:69-74).  Session
    windows are declared-but-unimplemented in the reference (`todo!()`); we
    implement them for real in the session-window operator."""

    TUMBLING = "tumbling"
    SLIDING = "sliding"
    SESSION = "session"


@dataclass
class StreamingWindow(LogicalPlan):
    """Windowed aggregation node (reference StreamingWindowPlanNode,
    logical_plan/streaming_window.rs:15-67; schema extension with window
    bound columns mirrors StreamingWindowSchema::try_new :83-108)."""

    input: LogicalPlan
    group_exprs: list[Expr]
    aggr_exprs: list[AggregateExpr]
    window_type: WindowType
    length_ms: int
    slide_ms: int | None  # None for tumbling; gap for session
    schema: Schema = None  # type: ignore[assignment]

    def __post_init__(self):
        if self.length_ms <= 0:
            raise PlanError("window length must be positive")
        if self.slide_ms is not None and self.slide_ms <= 0:
            raise PlanError("window slide must be positive")
        for g in self.group_exprs:
            # reference planner only supports column group-bys
            # (planner/streaming_window.rs:36-66); we allow any expr but name
            # the output column after it.
            pass
        in_schema = self.input.schema
        fields = [g.out_field(in_schema) for g in self.group_exprs]
        fields += [a.out_field(in_schema) for a in self.aggr_exprs]
        fields += [
            Field(WINDOW_START_COLUMN, DataType.TIMESTAMP_MS, nullable=False),
            Field(WINDOW_END_COLUMN, DataType.TIMESTAMP_MS, nullable=False),
            # emitted batches carry event time (= window start) so windows
            # and joins compose downstream
            Field(CANONICAL_TIMESTAMP_COLUMN, DataType.TIMESTAMP_MS, nullable=False),
        ]
        self.schema = Schema(fields)

    @property
    def children(self):
        return [self.input]

    def _label(self):
        w = f"{self.window_type.value} len={self.length_ms}ms"
        if self.slide_ms:
            w += f" slide={self.slide_ms}ms"
        return (
            f"StreamingWindow([{', '.join(g.name for g in self.group_exprs)}] "
            f"[{', '.join(a.name for a in self.aggr_exprs)}] {w})"
        )


class JoinKind(enum.Enum):
    INNER = "inner"
    LEFT = "left"
    RIGHT = "right"
    FULL = "full"
    # existence joins (DataFusion JoinType::LeftSemi/LeftAnti, exposed by
    # the reference's DataStream::join surface, datastream.rs:129): output
    # is LEFT rows only — semi emits each left row at most once when a
    # right match exists; anti emits left rows proven matchless (at
    # eviction horizon or EOS).  Right-side variants normalize to these by
    # swapping inputs at the API layer (DataStream.join).
    LEFT_SEMI = "left_semi"
    LEFT_ANTI = "left_anti"


@dataclass(frozen=True)
class JoinBand:
    """Banded (interval/range) join predicate riding alongside the equi
    keys: a pair matches iff ``left_expr - right_expr`` lands in
    ``[lower_ms, upper_ms]`` (inclusive; ``None`` = unbounded on that
    side).  ``lower_ms > upper_ms`` is a legal EMPTY band (matches
    nothing) — the degenerate case the hypothesis differential pins.
    Each expression is evaluated against its OWN input's schema, so a
    band can reference the right side's canonical timestamp even though
    that column never appears in the join output — the
    enrichment/temporal-correlation shape (``ts BETWEEN a AND b``) the
    residual pair filter cannot express.  Rows only match while
    co-retained: a band reaching beyond ``join_retention_ms`` is
    clipped by eviction (docs/joins.md)."""

    left_expr: Expr
    right_expr: Expr
    lower_ms: int | float | None
    upper_ms: int | float | None

    def _label(self) -> str:
        lo = "-inf" if self.lower_ms is None else self.lower_ms
        hi = "+inf" if self.upper_ms is None else self.upper_ms
        return (
            f"{self.left_expr.name} - {self.right_expr.name} in "
            f"[{lo}, {hi}]"
        )


@dataclass
class Join(LogicalPlan):
    """Stream-stream equi-join.  The reference lowers joins to DataFusion's
    join over two windowed streams (datastream.rs:126-177); ours is a
    symmetric streaming hash join keyed on the equi-columns."""

    left: LogicalPlan
    right: LogicalPlan
    kind: JoinKind
    left_keys: list[str]
    right_keys: list[str]
    filter: Expr | None = None
    band: JoinBand | None = None
    schema: Schema = None  # type: ignore[assignment]

    def __post_init__(self):
        if self.kind in (JoinKind.LEFT_SEMI, JoinKind.LEFT_ANTI):
            # existence joins surface no right columns, so same-named
            # columns across sides are fine in the OUTPUT — but a join
            # filter still evaluates over matched pairs, and a name both
            # sides carry would silently bind to the left column there
            if self.filter is not None:
                shared_keys = {
                    l for l, r in zip(self.left_keys, self.right_keys)
                    if l == r
                }  # equal by construction on a matched pair: unambiguous
                both = (
                    {f.name for f in self.left.schema}
                    & {f.name for f in self.right.schema}
                ) - shared_keys - {CANONICAL_TIMESTAMP_COLUMN}
                amb = self.filter.columns_referenced() & both
                if amb:
                    raise PlanError(
                        f"ambiguous column(s) {sorted(amb)} in "
                        f"{self.kind.value} join filter: present on both "
                        "sides; rename one side before joining"
                    )
            self.schema = self.left.schema
            return
        fields = list(self.left.schema.fields)
        names = {f.name for f in fields}
        for f in self.right.schema:
            if f.name == CANONICAL_TIMESTAMP_COLUMN:
                continue  # keep left's canonical timestamp
            if f.name in names:
                if f.name in self.right_keys and f.name in self.left_keys:
                    continue  # shared equi-key appears once
                raise PlanError(
                    f"ambiguous column {f.name!r} in join; rename one side "
                    "(reference renames via with_column before joining)"
                )
            fields.append(f)
        self.schema = Schema(fields)

    @property
    def children(self):
        return [self.left, self.right]

    def _label(self):
        on = ", ".join(f"{l}={r}" for l, r in zip(self.left_keys, self.right_keys))
        if self.band is not None:
            on += f", band {self.band._label()}"
        return f"Join({self.kind.value} on {on})"


@dataclass
class Sink(LogicalPlan):
    """Terminal node: stdout print / python callback / kafka topic writer
    (reference datastream.rs print_stream :311 / sink_kafka :346;
    py sink_python datastream.rs(py):229)."""

    input: LogicalPlan
    sink: Any  # physical.sinks.Sink factory
    schema: Schema = None  # type: ignore[assignment]

    def __post_init__(self):
        self.schema = self.input.schema

    @property
    def children(self):
        return [self.input]

    def _label(self):
        return f"Sink({type(self.sink).__name__})"
