"""Multi-host initialization.

The reference is single-process by design (SURVEY.md §2.3: in-process
channels, "no multi-node anything").  The TPU-native scale-out story keeps
ONE code path for both: the same ``Mesh`` + ``shard_map`` kernels run over
however many hosts participate — collectives ride ICI within a slice and DCN
across slices; nothing in the engine distinguishes the two.

``init_distributed`` wraps ``jax.distributed.initialize`` (coordinator
address + process count, the JAX-native replacement for the reference's
would-be NCCL/MPI bootstrap), and ``global_mesh`` builds the key-axis mesh
over every device in the job.  On a single host both are no-ops/equivalent
to :func:`make_mesh`.

Operational sketch (multi-host streaming job):
- every host runs the same query binary with its own Kafka partition subset
  (source parallelism stays host-local, exactly like the reference's
  per-partition readers);
- window state shards over the GLOBAL device set via
  ``EngineConfig(mesh_devices=len(jax.devices()))``;
- barriers/checkpoints coordinate per-host (each host owns its sources'
  offsets; window snapshots are sharded-state exports).
"""

from __future__ import annotations

import jax

from denormalized_tpu.parallel.mesh import make_mesh


def init_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> None:
    """Join a multi-host JAX job.  No-op only when NOTHING multi-host was
    requested (no coordinator, no process id, ≤1 process); any explicit
    argument — including a bare ``process_id`` on auto-detecting platforms —
    goes through to ``jax.distributed.initialize``."""
    if (
        coordinator_address is None
        and process_id is None
        and num_processes in (None, 1)
    ):
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def global_mesh():
    """Mesh over the ENTIRE job's device set (every host).

    Deliberately takes no device-count argument: slicing the global device
    list would hand some hosts a mesh containing none of their addressable
    devices (shard_map would fail or deadlock at the first collective).
    For single-host sub-meshes use :func:`make_mesh` directly."""
    devices = jax.devices()
    local = set(jax.local_devices())
    if local and not local & set(devices):
        raise RuntimeError(
            "global device list excludes this process's devices — was "
            "init_distributed called on every host?"
        )
    return make_mesh(devices=devices)


def local_device_count() -> int:
    return jax.local_device_count()
