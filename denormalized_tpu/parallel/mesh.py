"""Device-mesh construction.

The scale-out axis of the reference is CPU-thread partitioning: one tokio
task per Kafka partition plus a hash ``RepartitionExec`` exchange
(SURVEY.md §2.4).  The TPU-native analog is a ``jax.sharding.Mesh``: the
single mesh axis ``"keys"`` plays the role of the hash-partition axis —
group-state shards live one-per-device and rows reach the right shard via
masked scatter (no exchange needed on ICI, the batch rides the broadcast) or
via per-device partial state merged with ``psum`` (the Partial/Final analog).
Multi-host extends the same mesh over DCN (jax.distributed), not a separate
code path.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh


KEY_AXIS = "keys"

# jax moved shard_map out of jax.experimental at 0.6; the pinned image
# ships 0.4.x where only the experimental spelling exists.  One shim so
# every kernel call site works on either — without it EVERY sharded
# layout (and the multichip dryrun) dies with AttributeError on 0.4.x.
if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:  # pragma: no cover - exercised on jax < 0.6 images (like this one)
    from jax.experimental.shard_map import shard_map  # noqa: F401
# second mesh axis for the 2-D layout: data-parallel row slices (each
# slice ingests its own source partitions; ICI-local key blocks within a
# slice, cross-slice merge only at emission — the axis that rides DCN in
# a multi-slice job)
SLICE_AXIS = "slices"


def make_mesh_2d(
    n_slices: int, n_key_shards: int | None = None, devices=None
) -> Mesh:
    """2-D mesh ``(slices, keys)``: rows are data-parallel across the
    slice axis, group-state is sharded across the key axis.  Lay the key
    axis innermost so its per-batch traffic (state updates, emission
    gathers) stays on the fastest links (ICI within a slice); the slice
    axis carries traffic only at emission/export (psum of window rows) —
    the cross-slice/DCN-tolerant direction."""
    if devices is None:
        devices = jax.devices()
    if n_key_shards is None:
        if len(devices) % n_slices:
            raise ValueError(
                f"{len(devices)} devices not divisible by {n_slices} slices"
            )
        n_key_shards = len(devices) // n_slices
    need = n_slices * n_key_shards
    if len(devices) < need:
        raise ValueError(
            f"need {need} devices ({n_slices}x{n_key_shards}), have "
            f"{len(devices)}"
        )
    arr = np.array(devices[:need]).reshape(n_slices, n_key_shards)
    return Mesh(arr, (SLICE_AXIS, KEY_AXIS))


def make_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    """1-D mesh (axis "keys") over ``devices`` (default: ``jax.devices()``,
    the job-global list), truncated to the first ``n_devices``.  In
    multi-process jobs do NOT truncate — use
    :func:`denormalized_tpu.parallel.distributed.global_mesh`."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        if len(devices) < n_devices:
            raise ValueError(
                f"need {n_devices} devices, have {len(devices)} "
                f"({[d.platform for d in devices[:3]]}...)"
            )
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (KEY_AXIS,))
