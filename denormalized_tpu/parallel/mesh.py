"""Device-mesh construction.

The scale-out axis of the reference is CPU-thread partitioning: one tokio
task per Kafka partition plus a hash ``RepartitionExec`` exchange
(SURVEY.md §2.4).  The TPU-native analog is a ``jax.sharding.Mesh``: the
single mesh axis ``"keys"`` plays the role of the hash-partition axis —
group-state shards live one-per-device and rows reach the right shard via
masked scatter (no exchange needed on ICI, the batch rides the broadcast) or
via per-device partial state merged with ``psum`` (the Partial/Final analog).
Multi-host extends the same mesh over DCN (jax.distributed), not a separate
code path.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh


KEY_AXIS = "keys"


def make_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    """1-D mesh (axis "keys") over ``devices`` (default: ``jax.devices()``,
    the job-global list), truncated to the first ``n_devices``.  In
    multi-process jobs do NOT truncate — use
    :func:`denormalized_tpu.parallel.distributed.global_mesh`."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        if len(devices) < n_devices:
            raise ValueError(
                f"need {n_devices} devices, have {len(devices)} "
                f"({[d.platform for d in devices[:3]]}...)"
            )
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (KEY_AXIS,))
