"""Sharded window-state backends — TPU-native scale-out of the hot path.

The reference scales grouped window aggregation with a hash
``RepartitionExec`` exchange feeding per-partition streams, and merges
ungrouped aggregates through a Partial→Final operator pair
(SURVEY.md §2.4, coalesce_before_streaming_window_aggregate.rs:63-70,
planner/streaming_window.rs:133-153).  On a TPU mesh both strategies become
sharding layouts of the SAME device kernel (`update_state_impl`), wrapped in
``shard_map`` so XLA owns the collectives:

- :class:`KeyShardedWindowState` — the hash-partition analog.  Accumulator
  buffers are sharded over the group axis (each device owns a contiguous
  block of group ids); the batch is replicated and every device applies only
  its own block via masking.  Update needs NO collective (the "exchange"
  rides the input broadcast over ICI); emission gathers one window row
  (G-sized) per device.  Right choice for high-cardinality state that must
  not be duplicated per device.

- :class:`PartialFinalWindowState` — the Partial→Final analog.  Rows are
  sharded across devices (data parallel); every device keeps a full local
  copy of the (small) state and emission merges with ``psum`` / ``pmin`` /
  ``pmax`` at watermark triggers only.  Right choice for low-cardinality
  aggregation at extreme ingest rates: input transfer is 1/n per device and
  the merge collective runs once per window, not per batch.

- :class:`SingleDeviceWindowState` — the degenerate 1-device backend used by
  default (and on the single live chip).

All three present the same interface to the window operator, which stays
oblivious to the layout.
"""

from __future__ import annotations

import functools
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from denormalized_tpu.ops import segment_agg as sa
from denormalized_tpu.parallel.mesh import KEY_AXIS, SLICE_AXIS, shard_map


class WindowStateBackend:
    """Interface the window operator drives."""

    spec: sa.WindowKernelSpec  # device-local spec
    # True when the backend reduces rows on host and ships partial
    # aggregates (the ``partial_merge`` strategy): the operator then calls
    # ``accumulate``/``flush_pending`` instead of per-batch ``update``
    accumulates_host: bool = False
    # link-traffic accounting (numpy-payload bytes handed to/from the
    # device; the round-3 VERDICT asks the bench to prove where the
    # highcard ceiling is — these feed bytes/s and link-saturation
    # fields in the bench JSON)
    bytes_h2d: int = 0
    bytes_d2h: int = 0

    @property
    def strategy_name(self) -> str:
        """What actually executes — defined next to each backend so a
        rename or new subclass cannot silently mislabel the bench's
        ``strategy_resolved`` field."""
        return type(self).__name__

    @property
    def group_capacity(self) -> int:
        """Total group-id capacity visible to the host interner."""
        raise NotImplementedError

    def update(
        self, values, colvalid, win_rel, rem, gid, row_valid, base_mod,
        min_win_rel: int | None = None, max_win_rel: int | None = None,
    ):
        raise NotImplementedError

    def flush_pending(self) -> None:
        """Merge any host-accumulated partials into device state.  No-op
        for row-shipping backends.  MUST be called before emission,
        export, or capacity growth on host-accumulating backends."""

    def read_reset_block(self, first_slot: int, n: int) -> dict[str, "np.ndarray"]:
        """Read and reset n consecutive ring slots; default = per-slot
        loop (sharded layouts)."""
        rows = []
        for i in range(n):
            slot = (first_slot + i) % self.spec.window_slots
            rows.append(self.read_slot(slot))
            self.reset_slot(slot)
        return {
            label: np.stack([r[label] for r in rows])
            for label in rows[0]
        }

    # -- async emission pipeline: start dispatches the device work and
    # returns a handle; finish materializes it on host.  The default is
    # synchronous (start does the work); device backends override start to
    # return in-flight device arrays so the transfer overlaps ingest.
    def read_reset_block_start(
        self, first_slot: int, n: int, live_groups=None, lean=False
    ):
        return self.read_reset_block(first_slot, n)

    def read_reset_block_finish(self, handle) -> dict[str, "np.ndarray"]:
        return handle

    # -- on-device finalization (optional) -----------------------------
    def prepare_finals(self, agg_specs: tuple) -> None:
        """Announce the output aggregate specs so the backend can
        pre-compile finals-emission programs.  No-op for backends that
        don't finalize on device."""

    def read_reset_block_finals_start(
        self, first_slot: int, n: int, live_groups=None
    ):
        """Dispatch a finals emission (final output planes + active
        bitmask, see segment_agg._finals_and_reset) for n ring slots —
        or return None when this layout doesn't support it (caller falls
        back to the component-plane path)."""
        return None

    def read_slot(self, slot: int) -> dict[str, np.ndarray]:
        raise NotImplementedError

    def read_slot_compact(self, slot: int):
        """(active gids, aligned component rows) — or None when this layout
        doesn't implement device-side compaction (caller falls back to the
        full read_slot)."""
        return None

    def reset_slot(self, slot: int) -> None:
        raise NotImplementedError

    def export(self) -> dict[str, np.ndarray]:
        """(W, G_total) host snapshot for checkpoint/growth."""
        raise NotImplementedError

    # -- async export (checkpointing): start dispatches an on-device clone
    # plus its host copy and returns a handle; finish materializes it.
    # Default is synchronous.
    def export_start(self):
        return self.export()

    def export_finish(self, handle) -> dict[str, "np.ndarray"]:
        return handle

    def import_(self, host_state: dict[str, np.ndarray]) -> None:
        raise NotImplementedError


class SingleDeviceWindowState(WindowStateBackend):
    def __init__(self, spec: sa.WindowKernelSpec, device_strategy: str = "scatter"):
        self.spec = spec
        self._state = sa.init_state(spec)
        self.device_strategy = device_strategy
        # actual dispatch counts: 'pallas_dense'/'auto' fall back to the
        # scatter program per batch when the kernel doesn't support the
        # spec or the batch shape — strategy_name reports what RAN
        self.dense_updates = 0
        self.scatter_updates = 0
        self._pallas_interpret = jax.default_backend() != "tpu"
        if not self._pallas_interpret:
            # pre-compile emission gather programs for the block sizes and
            # group buckets the trigger will actually request: an unseen
            # (n, g_bucket, lean) tuple compiling mid-stream costs seconds
            # on a remote-compile TPU backend.  Running them on the
            # freshly-initialized state is a no-op (slots are already at
            # init values).  The runtime bucket is pow2(live groups),
            # floor 1024, cap G — warm the two endpoints; a pow2 crossing
            # in between pays a one-off compile (and hits the persistent
            # XLA cache on any later run).  Both layout variants are
            # warmed when they differ: a stream flips lean→full on its
            # first null, and a restored stream starts full.
            variants = {False, sa.lean_possible(spec)}
            for n in (1, 2, 4, 8):
                if n <= spec.window_slots:
                    for g_bucket in {min(1024, spec.group_capacity),
                                     spec.group_capacity}:
                        for lean in variants:
                            self._state, _ = sa._gather_and_reset(
                                spec, n, g_bucket, self._state,
                                jnp.asarray(0, jnp.int32), lean,
                            )

    @property
    def strategy_name(self) -> str:
        if self.device_strategy == "scatter":
            return "row_shipping:scatter"
        # 'pallas_dense' / 'auto': report the dispatch that actually ran
        if self.dense_updates and self.scatter_updates:
            return "row_shipping:pallas_dense+scatter"
        if self.dense_updates:
            return "row_shipping:pallas_dense"
        if self.scatter_updates:
            return "row_shipping:scatter"
        return f"row_shipping:{self.device_strategy} (no batches yet)"

    @property
    def group_capacity(self) -> int:
        return self.spec.group_capacity

    def update(
        self, values, colvalid, win_rel, rem, gid, row_valid, base_mod,
        min_win_rel: int | None = None, max_win_rel: int | None = None,
    ):
        self.bytes_h2d += sum(
            int(np.asarray(a).nbytes)
            for a in (values, colvalid, win_rel, rem, gid, row_valid)
            if a is not None
        )
        # 'auto' only engages the dense path on real TPU hardware: in
        # interpret mode (CPU) the pallas kernel is orders of magnitude
        # slower than the scatter path, so auto means scatter there.
        # Explicit 'pallas_dense' still honors interpret for parity tests.
        try_dense = self.device_strategy == "pallas_dense" or (
            self.device_strategy == "auto" and not self._pallas_interpret
        )
        if try_dense and min_win_rel is not None:
            from denormalized_tpu.ops import pallas_window as pw

            span_ok = (
                max_win_rel is not None
                and max_win_rel - max(min_win_rel - (self.spec.length_units - 1), 0)
                < pw.K_ACTIVE
            )
            tile_ok = np.shape(values)[0] % pw.TILE == 0
            if pw.dense_supported(self.spec) and span_ok and tile_ok:
                self.dense_updates += 1
                lo = max(min_win_rel - (self.spec.length_units - 1), 0)
                self._state = pw.dense_update(
                    self.spec,
                    self._state,
                    jnp.asarray(values),
                    jnp.asarray(colvalid),
                    jnp.asarray(win_rel),
                    jnp.asarray(rem),
                    jnp.asarray(gid),
                    jnp.asarray(row_valid),
                    jnp.asarray(base_mod, dtype=jnp.int32),
                    min_win_rel=lo,
                    interpret=self._pallas_interpret,
                )
                return
        self.scatter_updates += 1
        self._state = sa.update_state(
            self.spec,
            self._state,
            jnp.asarray(values),
            jnp.asarray(colvalid),
            jnp.asarray(win_rel),
            jnp.asarray(rem),
            jnp.asarray(gid),
            jnp.asarray(row_valid),
            jnp.asarray(base_mod, dtype=jnp.int32),
        )

    def read_slot(self, slot: int) -> dict[str, np.ndarray]:
        out = sa.read_slot(self.spec, self._state, slot)
        self.bytes_d2h += sum(int(a.nbytes) for a in out.values())
        return out

    def read_slot_compact(self, slot: int):
        gids, rows = sa.read_slot_compact(self.spec, self._state, slot)
        self._count_compact_d2h(gids, rows, self.spec.group_capacity)
        return gids, rows

    def _count_compact_d2h(self, gids, rows, capacity) -> None:
        """Wire accounting for a compact read: the transfer is the pow2
        BUCKET covering the k active groups (read_slot_compact truncates
        to k on host AFTER the device_get), so counting the returned
        arrays would undercount by up to ~2x."""
        k = len(gids)
        if k == 0:
            return
        bucket = min(1 << (k - 1).bit_length(), capacity)
        per_elem = gids.dtype.itemsize + sum(
            a.dtype.itemsize for a in rows.values()
        )
        self.bytes_d2h += bucket * per_elem

    def reset_slot(self, slot: int) -> None:
        self._state = sa.reset_slot(
            self.spec, self._state, jnp.asarray(slot, dtype=jnp.int32)
        )

    def read_reset_block(self, first_slot: int, n: int) -> dict[str, np.ndarray]:
        return self.read_reset_block_finish(
            self.read_reset_block_start(first_slot, n)
        )

    def read_reset_block_start(
        self, first_slot: int, n: int, live_groups=None, lean=False
    ):
        """Dispatch the fused gather+reset and return the in-flight device
        arrays WITHOUT blocking — the device→host transfer overlaps
        whatever the host does next (typically accumulating the next
        stripe).

        ``live_groups`` (the interner's current size) bounds the
        transferred group width: gids are interner-dense, so every cell
        at index ≥ live_groups is still at its init value and need not
        cross the link.  The width is bucketed to a pow2 (floor 1024) so
        the (n, bucket) program ladder stays ≤ log2(G/1024) entries per
        block size — the bucket only grows when the interner crosses a
        pow2 boundary, a one-off compile, while the transfer shrinks by
        the full capacity/cardinality ratio (e.g. 2.6× at 100K keys in a
        262K-capacity ring, and ~all of it when capacity is
        over-provisioned)."""
        assert n <= self.spec.window_slots  # slots must be distinct
        g_bucket = self._live_bucket(live_groups)
        self._state, out = sa._gather_and_reset(
            self.spec, n, g_bucket, self._state,
            jnp.asarray(first_slot, jnp.int32), lean,
        )
        for arr in out.values():
            arr.copy_to_host_async()
        return out

    def read_reset_block_finish(self, handle) -> dict[str, np.ndarray]:
        out = jax.device_get(handle)
        self.bytes_d2h += sum(int(a.nbytes) for a in out.values())
        return out

    def prepare_finals(self, agg_specs: tuple) -> None:
        self._finals_specs = tuple(agg_specs)
        if not getattr(self, "_pallas_interpret", True):
            # pre-compile the finals ladder like the component-gather one
            # in __init__: an unseen (n, bucket) pair compiling mid-stream
            # costs seconds on a remote-compile backend.  group_capacity
            # is the property — the GLOBAL width on sharded layouts.
            for n in (1, 2, 4, 8):
                if n <= self.spec.window_slots:
                    for g_bucket in {min(1024, self.group_capacity),
                                     self.group_capacity}:
                        self._state, _ = sa._finals_and_reset(
                            self.spec, self._finals_specs, n, g_bucket,
                            self._state, jnp.asarray(0, jnp.int32),
                        )

    def _live_bucket(self, live_groups) -> int:
        """Transferred group width: pow2 of the interner's live size
        (floor 1024), capped at capacity — the single bucketing policy
        for every emission ladder (component gather AND finals), so both
        prewarm sets stay aligned with runtime requests."""
        g_bucket = self.group_capacity
        if live_groups is not None:
            g_bucket = min(
                g_bucket,
                max(1024, 1 << max(0, int(live_groups) - 1).bit_length()),
            )
        return g_bucket

    def read_reset_block_finals_start(
        self, first_slot: int, n: int, live_groups=None
    ):
        specs = getattr(self, "_finals_specs", None)
        if specs is None:
            return None
        assert n <= self.spec.window_slots
        g_bucket = self._live_bucket(live_groups)
        self._state, out = sa._finals_and_reset(
            self.spec, specs, n, g_bucket, self._state,
            jnp.asarray(first_slot, jnp.int32),
        )
        for arr in out.values():
            arr.copy_to_host_async()
        return out

    def export(self) -> dict[str, np.ndarray]:
        return sa.export_state(self._state)

    def export_start(self):
        snap = sa.clone_state(self._state)
        for arr in snap.values():
            arr.copy_to_host_async()
        return snap

    def export_finish(self, handle) -> dict[str, np.ndarray]:
        out = jax.device_get(handle)
        self.bytes_d2h += sum(int(a.nbytes) for a in out.values())
        return out

    def import_(self, host_state: dict[str, np.ndarray]) -> None:
        self._state = sa.import_state(self.spec, host_state)


class _HostPartialMixin:
    """Shared host-stripe machinery for partial_merge backends: batch
    chunk-folding, flush orchestration, and merge-program prewarming.
    Concrete classes provide ``_merge(packed, a_pad)``."""

    accumulates_host = True

    def _init_host_partial(self, stripe_group_capacity: int) -> None:
        from denormalized_tpu.ops.host_partial import HostPartialStripe

        self._stripe = HostPartialStripe(self.spec, stripe_group_capacity)
        self._pending_base_mod = 0
        self.merges = 0
        if jax.default_backend() == "tpu":
            # pre-compile every merge bucket with a no-op (all-padding)
            # stripe: which bucket a flush lands in depends on runtime
            # pacing, and an unseen size mid-stream is a multi-second
            # compile on a remote-compile backend.  Both packed layouts
            # are warmed when the spec has per-column counts: lean (the
            # null-free steady state) and full (the moment a null shows
            # up).
            variants = [False]
            if sa.lean_possible(self.spec):
                variants.append(True)
            stripe = self._stripe
            dense_floor = stripe.G * stripe.SUB  # smallest dense span
            for lean in variants:
                n_planes = stripe.n_planes(lean)
                for a_pad in stripe.transfer_buckets():
                    noop = np.zeros((n_planes + 1, a_pad + 2), np.int32)
                    noop[0, :a_pad] = -1
                    self._merge(noop, a_pad, lean)
                    if a_pad >= dense_floor:
                        # dense no-op: fold-neutral planes (a zeroed min
                        # plane would clobber state with 0.0 — dense has
                        # no validity mask); layout owned by the stripe
                        self._merge(
                            stripe.dense_noop(a_pad, lean), a_pad, lean,
                            dense=True,
                        )

    @property
    def pending_rows(self) -> int:
        return self._stripe.rows

    def update(self, *a, **k):
        raise RuntimeError(
            "partial_merge backend consumes host partials via accumulate(); "
            "the operator must not ship rows to it"
        )

    def accumulate(
        self, units_rel, rem, gid, values64, colvalid, keep, base_mod
    ) -> None:
        """Fold one batch into the host stripe, flushing/chunking so no
        row is ever dropped: a batch spanning more slide units than a
        stripe can hold (catch-up reads, giant arrival batches) is folded
        in unit-range chunks with a merge between them — the partial-path
        equivalent of the scatter path's W growth."""
        units_rel = np.asarray(units_rel, np.int64)
        stripe = self._stripe
        # units a stripe may span: both the U_MAX ring and the transfer
        # cell cap (at least one unit — transfer_buckets covers G*SUB)
        span_u = max(
            1,
            min(
                stripe.U_MAX,
                stripe.MAX_STRIPE_CELLS // max(1, stripe.G * stripe.SUB),
            ),
        )
        if keep is None and len(units_rel):
            # fast path for the steady state: no late/keep mask and the
            # whole batch fits the CURRENT stripe as-is — fold it in one
            # call with no boolean scans or masked copies.  Anything that
            # would need a flush (span overflow, row cap, units behind
            # u_base) falls through to the chunk loop below, which keeps
            # the one and only copy of the flush/admission logic.
            u_min = int(units_rel.min())
            u_max = int(units_rel.max())
            base = stripe.u_base if not stripe.is_empty() else u_min
            if (
                u_min >= base
                and u_max <= base + span_u - 1
                and (
                    stripe.is_empty()
                    or stripe.rows + len(units_rel)
                    <= stripe.MAX_STRIPE_ROWS
                )
            ):
                if stripe.is_empty():
                    self._pending_base_mod = int(base_mod)
                stripe.add_batch(units_rel, rem, gid, values64, colvalid, None)
                return
        remaining = (
            np.ones(len(units_rel), bool) if keep is None else keep.copy()
        )
        while remaining.any():
            u0 = int(units_rel[remaining].min())
            if not stripe.is_empty() and (
                u0 < stripe.u_base
                or stripe.rows >= stripe.MAX_STRIPE_ROWS
            ):
                self.flush_pending()
            base = stripe.u_base if not stripe.is_empty() else u0
            chunk = (
                remaining
                & (units_rel >= base)
                & (units_rel <= base + span_u - 1)
            )
            n_chunk = int(chunk.sum())
            if n_chunk == 0 or (
                not stripe.is_empty()
                and stripe.rows + n_chunk > stripe.MAX_STRIPE_ROWS
            ):
                self.flush_pending()
                continue
            if stripe.is_empty():
                self._pending_base_mod = int(base_mod)
            stripe.add_batch(
                units_rel, rem, gid, values64, colvalid, chunk
            )
            remaining &= ~chunk

    def flush_pending(self) -> None:
        taken = self._stripe.take_packed(self._pending_base_mod)
        if taken is None:
            return
        packed, a_pad, _u_base, lean, dense = taken
        self.bytes_h2d += int(packed.nbytes)
        self._merge(packed, a_pad, lean, dense)
        self.merges += 1


class PartialMergeWindowState(_HostPartialMixin, SingleDeviceWindowState):
    strategy_name = "partial_merge"

    """Host edge-reduction + device merge (the ``partial_merge`` strategy).

    Rows are reduced on the host into per-(slide-unit, sub, group) partials
    (native C++ single-pass, ops/host_partial.py) and the device folds each
    stripe into the HBM window ring with ONE transfer + ONE program — the
    reference's Partial/Final operator split (planner/streaming_window.rs
    :133-153) applied across the host↔accelerator boundary.  This is the
    right layout whenever the host→device link is narrow relative to the
    ingest rate: traffic scales with group cardinality × window span, not
    row count.  Device state, emission, growth, and checkpointing are
    identical to the scatter path."""

    def __init__(self, spec: sa.WindowKernelSpec):
        super().__init__(spec, "scatter")
        self._init_host_partial(spec.group_capacity)

    def _merge(
        self, packed: np.ndarray, a_pad: int, lean: bool = False,
        dense: bool = False,
    ) -> None:
        self._state = sa.merge_partials(
            self.spec, self._stripe.SUB, a_pad, lean, dense, self._state,
            jnp.asarray(packed),
        )


# ---------------------------------------------------------------------------


def _mask_to_key_shard(spec: sa.WindowKernelSpec, gid, row_valid):
    """Inside a shard_map body: rebase global group ids onto THIS key
    shard's block and mask out everyone else's rows — the one place the
    key-sharded 'exchange rides the broadcast' trick is implemented (both
    the 1-D and 2-D layouts use it)."""
    G_local = spec.group_capacity
    shard = jax.lax.axis_index(KEY_AXIS)
    local_gid = gid - shard * G_local
    mine = row_valid & (local_gid >= 0) & (local_gid < G_local)
    return jnp.clip(local_gid, 0, G_local - 1), mine


@functools.partial(jax.jit, static_argnums=(0, 1), donate_argnums=2)
def _key_sharded_update(
    spec: sa.WindowKernelSpec,
    mesh: Mesh,
    state,
    values,
    colvalid,
    win_rel,
    rem,
    gid,
    row_valid,
    base_mod,
):
    def body(state_l, values, colvalid, win_rel, rem, gid, row_valid, base_mod):
        local_gid, mine = _mask_to_key_shard(spec, gid, row_valid)
        return sa.update_state_impl(
            spec, state_l, values, colvalid, win_rel, rem, local_gid, mine, base_mod
        )

    return shard_map(
        body,
        mesh=mesh,
        in_specs=(
            {c.label: P(None, KEY_AXIS) for c in spec.components},
            P(),
            P(),
            P(),
            P(),
            P(),
            P(),
            P(),
        ),
        out_specs={c.label: P(None, KEY_AXIS) for c in spec.components},
    )(state, values, colvalid, win_rel, rem, gid, row_valid, base_mod)


class KeyShardedWindowState(WindowStateBackend):
    """Group axis sharded over the mesh; batch replicated; no per-batch
    collectives."""

    strategy_name = "key_sharded"

    def __init__(self, spec: sa.WindowKernelSpec, mesh: Mesh):
        # spec is the GLOBAL spec; each device holds G_total/n
        n = mesh.devices.size
        if spec.group_capacity % n:
            raise ValueError(
                f"group capacity {spec.group_capacity} is not divisible by "
                f"the mesh size {n}"
            )
        self.mesh = mesh
        self.n = n
        self.spec = sa.WindowKernelSpec(
            components=spec.components,
            num_value_cols=spec.num_value_cols,
            window_slots=spec.window_slots,
            group_capacity=spec.group_capacity // n,
            length_ms=spec.length_ms,
            slide_ms=spec.slide_ms,
            accum_dtype=spec.accum_dtype,
            compensated=spec.compensated,
        )
        self._sharding = NamedSharding(mesh, P(None, KEY_AXIS))
        self._state = {
            c.label: jax.device_put(
                jnp.full(
                    (spec.window_slots, spec.group_capacity),
                    self.spec.init_value(c),
                ),
                self._sharding,
            )
            for c in spec.components
        }

    @property
    def group_capacity(self) -> int:
        return self.spec.group_capacity * self.n

    def update(
        self, values, colvalid, win_rel, rem, gid, row_valid, base_mod,
        min_win_rel=None, max_win_rel=None,
    ):
        self._state = _key_sharded_update(
            self.spec,
            self.mesh,
            self._state,
            jnp.asarray(values),
            jnp.asarray(colvalid),
            jnp.asarray(win_rel),
            jnp.asarray(rem),
            jnp.asarray(gid),
            jnp.asarray(row_valid),
            jnp.asarray(base_mod, dtype=jnp.int32),
        )

    def read_slot(self, slot: int) -> dict[str, np.ndarray]:
        # jitted traced-slot gather; slicing a G-sharded array gathers one
        # (G_total,) row per component
        out = sa.read_slot(self.spec, self._state, slot)
        self.bytes_d2h += sum(int(a.nbytes) for a in out.values())
        return out

    def reset_slot(self, slot: int) -> None:
        self._state = _key_sharded_reset_slot(
            self.spec, self._state, jnp.asarray(slot, dtype=jnp.int32)
        )

    def export(self) -> dict[str, np.ndarray]:
        return jax.device_get(self._state)

    def import_(self, host_state: dict[str, np.ndarray]) -> None:
        W = self.spec.window_slots
        G_total = self.group_capacity
        for c in self.spec.components:
            buf = np.full(
                (W, G_total), np.asarray(self.spec.init_value(c)),
                dtype=np.asarray(self.spec.init_value(c)).dtype,
            )
            src = host_state.get(c.label)
            if src is not None:
                w = min(src.shape[0], W)
                g = min(src.shape[1], G_total)
                buf[:w, :g] = src[:w, :g]
            self._state[c.label] = jax.device_put(
                jnp.asarray(buf), self._sharding
            )


@functools.partial(
    jax.jit, static_argnums=(0, 1, 2, 3, 4, 5), donate_argnums=6
)
def _key_sharded_merge_partials(
    spec: sa.WindowKernelSpec,  # LOCAL spec (G_local per device)
    mesh: Mesh,
    SUB: int,
    a_pad: int,
    lean: bool,
    dense: bool,
    state,
    packed,
):
    """Sharded fold of one host-partial stripe: the packed matrix is
    replicated over ICI and every device folds only the cells whose group
    id lands in its block — the hash-exchange analog for partials (no
    collective needed; the "exchange" rides the input broadcast)."""
    G_local = spec.group_capacity
    n = mesh.devices.size

    def body(state_l, packed_l):
        shift = jax.lax.axis_index(KEY_AXIS) * G_local
        return sa.merge_partials_body(
            spec, SUB, a_pad, state_l, packed_l, G_local * n, shift, lean,
            dense,
        )

    return shard_map(
        body,
        mesh=mesh,
        in_specs=({c.label: P(None, KEY_AXIS) for c in spec.components}, P()),
        out_specs={c.label: P(None, KEY_AXIS) for c in spec.components},
    )(state, packed)


class KeyShardedPartialMergeWindowState(_HostPartialMixin, KeyShardedWindowState):
    """partial_merge over a device mesh: host stripes cover the GLOBAL
    group space; each device merges its own group block from the
    replicated packed stripe.  Emission gathers/reset via a fused global
    program (GSPMD partitions it over the same sharding)."""

    strategy_name = "partial_merge/key_sharded"

    def __init__(self, spec: sa.WindowKernelSpec, mesh: Mesh):
        super().__init__(spec, mesh)
        self._pallas_interpret = jax.default_backend() != "tpu"
        # stripe spans the GLOBAL group space
        self._init_host_partial(self.group_capacity)

    def _merge(
        self, packed: np.ndarray, a_pad: int, lean: bool = False,
        dense: bool = False,
    ) -> None:
        self._state = _key_sharded_merge_partials(
            self.spec, self.mesh, self._stripe.SUB, a_pad, lean, dense,
            self._state, jnp.asarray(packed),
        )

    # fused async gather+reset + on-device finalization + emission
    # compaction: identical machinery to the single-device backend
    # (self.group_capacity is the global width here; GSPMD partitions the
    # programs over the key sharding)
    read_reset_block = SingleDeviceWindowState.read_reset_block
    read_reset_block_start = SingleDeviceWindowState.read_reset_block_start
    read_reset_block_finish = SingleDeviceWindowState.read_reset_block_finish
    _live_bucket = SingleDeviceWindowState._live_bucket
    _count_compact_d2h = SingleDeviceWindowState._count_compact_d2h
    prepare_finals = SingleDeviceWindowState.prepare_finals
    read_reset_block_finals_start = (
        SingleDeviceWindowState.read_reset_block_finals_start
    )
    export_start = SingleDeviceWindowState.export_start
    export_finish = SingleDeviceWindowState.export_finish

    def read_slot_compact(self, slot: int):
        # state is globally shaped; the spec carries the per-device shard,
        # so the bucket cap must come from the global width
        gids, rows = sa.read_slot_compact(
            self.spec, self._state, slot, capacity=self.group_capacity
        )
        self._count_compact_d2h(gids, rows, self.group_capacity)
        return gids, rows


# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnums=(0, 1), donate_argnums=2)
def _partial_update(
    spec: sa.WindowKernelSpec,
    mesh: Mesh,
    state,
    values,
    colvalid,
    win_rel,
    rem,
    gid,
    row_valid,
    base_mod,
):
    def body(state_l, values, colvalid, win_rel, rem, gid, row_valid, base_mod):
        st = {k: v[0] for k, v in state_l.items()}
        st = sa.update_state_impl(
            spec, st, values, colvalid, win_rel, rem, gid, row_valid, base_mod
        )
        return {k: v[None] for k, v in st.items()}

    n = mesh.devices.size
    return shard_map(
        body,
        mesh=mesh,
        in_specs=(
            {c.label: P(KEY_AXIS) for c in spec.components},
            P(KEY_AXIS),
            P(KEY_AXIS),
            P(KEY_AXIS),
            P(KEY_AXIS),
            P(KEY_AXIS),
            P(KEY_AXIS),
            P(),
        ),
        out_specs={c.label: P(KEY_AXIS) for c in spec.components},
    )(state, values, colvalid, win_rel, rem, gid, row_valid, base_mod)


@functools.partial(jax.jit, static_argnums=(0, 1, 2))
def _merge_slot_over(
    spec: sa.WindowKernelSpec, mesh: Mesh, reduce_axis: str, state, slot
):
    """Final merge of one window row across device partials: psum for
    count/sum, pmin/pmax for extrema — the reference's Final stage
    (streaming_window.rs:484-489) as a single collective over
    ``reduce_axis``.  Serves both partial layouts (the per-kind fold must
    exist ONCE): partial_final reduces over the 1-D key axis; two_level
    reduces over the slice axis of the 2-D mesh, and its key axis
    assembles via the out-spec with no collective.  ``slot`` is traced
    (dynamic slice), so one compilation serves every ring slot."""
    two_d = reduce_axis == SLICE_AXIS
    state_spec = P(SLICE_AXIS, None, KEY_AXIS) if two_d else P(KEY_AXIS)
    out_spec = P(KEY_AXIS) if two_d else P()

    def body(state_l, slot):
        out = {}
        for c in spec.components:
            row = jax.lax.dynamic_index_in_dim(
                state_l[c.label][0], slot, axis=0, keepdims=False
            )
            if c.kind in ("count", "sum", "sumc"):
                out[c.label] = jax.lax.psum(row, reduce_axis)
            elif c.kind == "min":
                out[c.label] = jax.lax.pmin(row, reduce_axis)
            else:
                out[c.label] = jax.lax.pmax(row, reduce_axis)
        return out

    return shard_map(
        body,
        mesh=mesh,
        in_specs=({c.label: state_spec for c in spec.components}, P()),
        out_specs={c.label: out_spec for c in spec.components},
    )(state, slot)


def _fold_partials_host(
    spec: sa.WindowKernelSpec, host: dict, axis: int = 0
) -> dict:
    """Host-side fold of per-device partial planes along ``axis`` (the
    export path's counterpart of _merge_slot_over)."""
    out = {}
    for c in spec.components:
        b = host[c.label]
        if c.kind in ("count", "sum", "sumc"):
            out[c.label] = b.sum(axis=axis)
        elif c.kind == "min":
            out[c.label] = b.min(axis=axis)
        else:
            out[c.label] = b.max(axis=axis)
    return out


def _import_merged_into_lead(
    spec: sa.WindowKernelSpec,
    host_state: dict,
    n_lead: int,
    W: int,
    G_total: int,
    sharding,
) -> dict:
    """Load a merged (W, G) snapshot into partial 0 of an (n, W, G)
    layout, init elsewhere — restore-time equivalence: the per-kind merge
    reproduces the snapshot exactly."""
    out = {}
    for c in spec.components:
        init = np.asarray(jax.device_get(spec.init_value(c)))
        buf = np.full((n_lead, W, G_total), init, dtype=init.dtype)
        src = host_state.get(c.label)
        if src is not None:
            w = min(src.shape[0], W)
            g = min(src.shape[1], G_total)
            buf[0, :w, :g] = src[:w, :g]
        out[c.label] = jax.device_put(jnp.asarray(buf), sharding)
    return out


@functools.partial(jax.jit, static_argnums=0, donate_argnums=1)
def _partial_reset_slot(spec: sa.WindowKernelSpec, state, slot):
    for c in spec.components:
        buf = state[c.label]
        row = jnp.full((buf.shape[0], 1, buf.shape[2]), spec.init_value(c))
        state[c.label] = jax.lax.dynamic_update_slice(
            buf, row.astype(buf.dtype), (0, slot, 0)
        )
    return state


@functools.partial(jax.jit, static_argnums=0, donate_argnums=1)
def _key_sharded_reset_slot(spec: sa.WindowKernelSpec, state, slot):
    for c in spec.components:
        buf = state[c.label]
        row = jnp.full((buf.shape[1],), spec.init_value(c))
        state[c.label] = buf.at[slot].set(row.astype(buf.dtype))
    return state


class PartialFinalWindowState(WindowStateBackend):
    """Rows data-parallel across devices; full state replica per device;
    collective merge only at emission."""

    strategy_name = "partial_final"

    def __init__(self, spec: sa.WindowKernelSpec, mesh: Mesh):
        self.mesh = mesh
        self.n = mesh.devices.size
        self.spec = spec
        self._sharding = NamedSharding(mesh, P(KEY_AXIS))
        self._state = {
            c.label: jax.device_put(
                jnp.full(
                    (self.n, spec.window_slots, spec.group_capacity),
                    spec.init_value(c),
                ),
                self._sharding,
            )
            for c in spec.components
        }

    @property
    def group_capacity(self) -> int:
        return self.spec.group_capacity

    def update(
        self, values, colvalid, win_rel, rem, gid, row_valid, base_mod,
        min_win_rel=None, max_win_rel=None,
    ):
        # rows must split evenly over the mesh: bucketed batches are powers
        # of two >= mesh size, so this holds by construction
        self._state = _partial_update(
            self.spec,
            self.mesh,
            self._state,
            jnp.asarray(values),
            jnp.asarray(colvalid),
            jnp.asarray(win_rel),
            jnp.asarray(rem),
            jnp.asarray(gid),
            jnp.asarray(row_valid),
            jnp.asarray(base_mod, dtype=jnp.int32),
        )

    def read_slot(self, slot: int) -> dict[str, np.ndarray]:
        out = jax.device_get(
            _merge_slot_over(
                self.spec, self.mesh, KEY_AXIS, self._state,
                jnp.asarray(slot, jnp.int32),
            )
        )
        self.bytes_d2h += sum(int(a.nbytes) for a in out.values())
        return out

    def reset_slot(self, slot: int) -> None:
        self._state = _partial_reset_slot(
            self.spec, self._state, jnp.asarray(slot, dtype=jnp.int32)
        )

    def export(self) -> dict[str, np.ndarray]:
        """Merged (W, G) snapshot."""
        return _fold_partials_host(self.spec, jax.device_get(self._state))

    def import_(self, host_state: dict[str, np.ndarray]) -> None:
        # load merged snapshot into device 0's partial, init elsewhere
        self._state = _import_merged_into_lead(
            self.spec, host_state, self.n, self.spec.window_slots,
            self.spec.group_capacity, self._sharding,
        )


@functools.partial(jax.jit, static_argnums=(0, 1), donate_argnums=2)
def _two_level_update(
    spec: sa.WindowKernelSpec,  # LOCAL spec (G_local per key shard)
    mesh: Mesh,
    state,
    values,
    colvalid,
    win_rel,
    rem,
    gid,
    row_valid,
    base_mod,
):
    """2-D update: rows split across the slice axis (each slice applies
    only its shard of the batch — in a multi-host job each host feeds its
    own slice), group blocks split across the key axis (each device masks
    to its gid block, exactly like the 1-D key-sharded layout).  NO
    collective: the key exchange rides the within-slice input broadcast
    and slices don't talk until emission."""

    def body(state_l, values, colvalid, win_rel, rem, gid, row_valid, base_mod):
        local_gid, mine = _mask_to_key_shard(spec, gid, row_valid)
        st = {k: v[0] for k, v in state_l.items()}
        st = sa.update_state_impl(
            spec, st, values, colvalid, win_rel, rem, local_gid, mine, base_mod
        )
        return {k: v[None] for k, v in st.items()}

    return shard_map(
        body,
        mesh=mesh,
        in_specs=(
            {c.label: P(SLICE_AXIS, None, KEY_AXIS) for c in spec.components},
            P(SLICE_AXIS),
            P(SLICE_AXIS),
            P(SLICE_AXIS),
            P(SLICE_AXIS),
            P(SLICE_AXIS),
            P(SLICE_AXIS),
            P(),
        ),
        out_specs={
            c.label: P(SLICE_AXIS, None, KEY_AXIS) for c in spec.components
        },
    )(state, values, colvalid, win_rel, rem, gid, row_valid, base_mod)


class TwoLevelWindowState(WindowStateBackend):
    """2-D ``(slices, keys)`` layout composing the two 1-D strategies:
    rows data-parallel across slices (the Partial/Final axis — cross-
    slice collectives fire only at emission, so this axis tolerates DCN
    in a multi-slice job), state key-sharded within each slice (the
    hash-partition axis — per-batch traffic stays on ICI).  The dp x tp
    analog for streaming window state."""

    strategy_name = "two_level"

    def __init__(self, spec: sa.WindowKernelSpec, mesh: Mesh):
        if SLICE_AXIS not in mesh.axis_names or KEY_AXIS not in mesh.axis_names:
            raise ValueError(
                f"two_level needs a ({SLICE_AXIS}, {KEY_AXIS}) mesh; got "
                f"{mesh.axis_names}"
            )
        self.mesh = mesh
        self.n_slices = mesh.shape[SLICE_AXIS]
        self.n_keys = mesh.shape[KEY_AXIS]
        if spec.group_capacity % self.n_keys:
            raise ValueError(
                f"group capacity {spec.group_capacity} not divisible by "
                f"{self.n_keys} key shards"
            )
        self.spec = sa.WindowKernelSpec(
            components=spec.components,
            num_value_cols=spec.num_value_cols,
            window_slots=spec.window_slots,
            group_capacity=spec.group_capacity // self.n_keys,
            length_ms=spec.length_ms,
            slide_ms=spec.slide_ms,
            accum_dtype=spec.accum_dtype,
            compensated=spec.compensated,
        )
        self._sharding = NamedSharding(mesh, P(SLICE_AXIS, None, KEY_AXIS))
        self._state = {
            c.label: jax.device_put(
                jnp.full(
                    (self.n_slices, spec.window_slots, spec.group_capacity),
                    self.spec.init_value(c),
                ),
                self._sharding,
            )
            for c in spec.components
        }

    @property
    def group_capacity(self) -> int:
        return self.spec.group_capacity * self.n_keys

    def update(
        self, values, colvalid, win_rel, rem, gid, row_valid, base_mod,
        min_win_rel=None, max_win_rel=None,
    ):
        # rows split S ways (bucketed pow2 batches >= mesh rows by
        # construction, same invariant as PartialFinalWindowState)
        self._state = _two_level_update(
            self.spec,
            self.mesh,
            self._state,
            jnp.asarray(values),
            jnp.asarray(colvalid),
            jnp.asarray(win_rel),
            jnp.asarray(rem),
            jnp.asarray(gid),
            jnp.asarray(row_valid),
            jnp.asarray(base_mod, dtype=jnp.int32),
        )

    def read_slot(self, slot: int) -> dict[str, np.ndarray]:
        # cross-slice merge (the layout's only collective) + key-axis
        # assembly via the out-spec — see _merge_slot_over
        out = jax.device_get(
            _merge_slot_over(
                self.spec, self.mesh, SLICE_AXIS, self._state,
                jnp.asarray(slot, jnp.int32),
            )
        )
        self.bytes_d2h += sum(int(a.nbytes) for a in out.values())
        return out

    def reset_slot(self, slot: int) -> None:
        # global-shape program; GSPMD partitions it over self._sharding
        self._state = _partial_reset_slot(
            self.spec, self._state, jnp.asarray(slot, dtype=jnp.int32)
        )

    def export(self) -> dict[str, np.ndarray]:
        """Merged (W, G_total) snapshot (cross-slice fold on host)."""
        return _fold_partials_host(self.spec, jax.device_get(self._state))

    def import_(self, host_state: dict[str, np.ndarray]) -> None:
        # merged snapshot into slice 0, init elsewhere (restore-time
        # equivalence: sums re-merge identically across slices)
        self._state = _import_merged_into_lead(
            self.spec, host_state, self.n_slices, self.spec.window_slots,
            self.group_capacity, self._sharding,
        )


def make_sharded_state(
    spec: sa.WindowKernelSpec,
    mesh: Mesh | None,
    strategy: str = "auto",
    device_strategy: str = "scatter",
) -> WindowStateBackend:
    """Pick a layout: small state → Partial/Final (duplicate it, shard rows);
    large state → key-sharded (shard it, broadcast rows)."""
    if device_strategy not in (
        "scatter", "pallas_dense", "auto", "partial_merge"
    ):
        raise ValueError(
            f"unknown device strategy {device_strategy!r} (expected "
            "'scatter', 'pallas_dense', 'partial_merge', or 'auto')"
        )
    # first point that touches the device: complete a deferred
    # compilation-cache decision for auto-detected accelerator backends
    from denormalized_tpu.api.context import ensure_compilation_cache_for_backend

    ensure_compilation_cache_for_backend()
    if mesh is None or mesh.devices.size == 1:
        # 'auto' chooses host edge-reduction on EVERY single-device
        # backend.  On a real TPU the chip sits behind a host↔device
        # link whose cost scales with shipped bytes, and partials are
        # orders of magnitude smaller than rows (measured on the axon
        # tunnel: ~20 MB/s uplink vs a >20 MB/s decoded-row stream at
        # 1M ev/s).  On CPU JAX the link is memcpy, but the native
        # single-pass reducer (native/partial_agg.cpp, 43-88M rows/s)
        # beats shipping rows through XLA's scatter adds there too:
        # measured ~30M vs ~20M rows/s on the simple config, with
        # equivalent paced latency once emission shapes are warm.
        # Row shipping remains available explicitly ('scatter' /
        # 'pallas_dense') for co-located accelerators — and stays the
        # 'auto' pick on backends neither measurement covers (e.g. a
        # co-located GPU, where host reduction would forfeit device-side
        # scatter for no demonstrated win).
        # ... except f64 accumulators on CPU: the partial_merge stripe
        # transports f64 as an f32 hi/lo split and refuses finite sums
        # beyond f32 range (ops/host_partial.py), while CPU XLA scatter
        # keeps f64 end-to-end — don't let 'auto' turn a working f64
        # workload into a runtime OverflowError.
        if device_strategy == "auto" and (
            spec.accum_dtype == jnp.float64
            and jax.default_backend() == "cpu"
        ):
            return SingleDeviceWindowState(spec, "scatter")
        if device_strategy == "partial_merge" or (
            device_strategy == "auto"
            and jax.default_backend() in ("tpu", "cpu")
        ):
            return PartialMergeWindowState(spec)
        return SingleDeviceWindowState(spec, device_strategy)
    if SLICE_AXIS in mesh.axis_names:
        # 2-D (slices, keys) mesh: the two_level layout is the only one
        # shaped for it
        if strategy not in ("auto", "two_level"):
            raise ValueError(
                f"strategy {strategy!r} does not fit a 2-D "
                f"({SLICE_AXIS}, {KEY_AXIS}) mesh — use 'two_level'/'auto'"
            )
        if device_strategy == "partial_merge":
            raise ValueError(
                "partial_merge composes with the 1-D key-sharded mesh "
                "(host partials already ARE the slice axis); use "
                "mesh_devices without mesh_slices"
            )
        return TwoLevelWindowState(spec, mesh)
    if strategy == "two_level":
        raise ValueError(
            "two_level needs a 2-D mesh — set EngineConfig.mesh_slices"
        )
    if device_strategy == "partial_merge":
        # host partials imply the Partial/Final split already happened on
        # the host, so the mesh's job is holding the (large) group space:
        # the key-sharded layout is the only one that makes sense here
        return KeyShardedPartialMergeWindowState(spec, mesh)
    if strategy == "auto":
        strategy = (
            "partial_final" if spec.group_capacity <= 4096 else "key_sharded"
        )
    if strategy == "partial_final":
        return PartialFinalWindowState(spec, mesh)
    if strategy == "key_sharded":
        return KeyShardedWindowState(spec, mesh)
    raise ValueError(f"unknown shard strategy {strategy!r}")
