from denormalized_tpu.parallel.mesh import make_mesh, make_mesh_2d
from denormalized_tpu.parallel.sharded_state import (
    KeyShardedWindowState,
    PartialFinalWindowState,
    TwoLevelWindowState,
    make_sharded_state,
)

__all__ = [
    "make_mesh",
    "make_mesh_2d",
    "KeyShardedWindowState",
    "PartialFinalWindowState",
    "TwoLevelWindowState",
    "make_sharded_state",
]
