from denormalized_tpu.parallel.mesh import make_mesh
from denormalized_tpu.parallel.sharded_state import (
    KeyShardedWindowState,
    PartialFinalWindowState,
    make_sharded_state,
)

__all__ = [
    "make_mesh",
    "KeyShardedWindowState",
    "PartialFinalWindowState",
    "make_sharded_state",
]
