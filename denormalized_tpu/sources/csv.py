"""Bounded CSV source (the reference's csv_streaming.rs sanity path:
plain DataFusion CSV → window → output)."""

from __future__ import annotations

import csv as _csv

import numpy as np

from denormalized_tpu.common.errors import SourceError
from denormalized_tpu.common.record_batch import RecordBatch
from denormalized_tpu.common.schema import DataType, Field, Schema
from denormalized_tpu.sources.memory import MemorySource


def infer_csv_schema(path: str, sample_rows: int = 100) -> Schema:
    with open(path, newline="") as f:
        reader = _csv.reader(f)
        try:
            header = next(reader)
        except StopIteration:
            raise SourceError(f"CSV {path!r} is empty (no header line)") from None
        samples = [row for _, row in zip(range(sample_rows), reader)]
    fields = []
    for ci, name in enumerate(header):
        vals = [r[ci] for r in samples if ci < len(r) and r[ci] != ""]
        fields.append(Field(name, _infer(vals)))
    return Schema(fields)


def _infer(vals: list[str]) -> DataType:
    if not vals:
        return DataType.STRING
    try:
        ints = [int(v) for v in vals]
        return DataType.INT64
    except ValueError:
        pass
    try:
        [float(v) for v in vals]
        return DataType.FLOAT64
    except ValueError:
        pass
    lowered = {v.lower() for v in vals}
    if lowered <= {"true", "false"}:
        return DataType.BOOL
    return DataType.STRING


class CsvSource(MemorySource):
    def __init__(
        self,
        path: str,
        schema: Schema | None = None,
        timestamp_column: str | None = None,
        batch_rows: int = 8192,
        timestamp_unit: str = "ms",
    ):
        schema = schema or infer_csv_schema(path)
        batches = []
        with open(path, newline="") as f:
            reader = _csv.reader(f)
            try:
                header = next(reader)
            except StopIteration:
                raise SourceError(
                    f"CSV {path!r} is empty (no header line)"
                ) from None
            idx = {}
            for field in schema:
                if field.name not in header:
                    raise SourceError(f"CSV missing column {field.name!r}")
                idx[field.name] = header.index(field.name)
            rows = list(reader)
        for start in range(0, len(rows), batch_rows):
            chunk = rows[start : start + batch_rows]
            cols, masks = [], []
            for field in schema:
                ci = idx[field.name]
                raw = [r[ci] if ci < len(r) else "" for r in chunk]
                mask = np.array([v != "" for v in raw])
                if field.dtype is DataType.STRING:
                    col = np.array(raw, dtype=object)
                elif field.dtype is DataType.BOOL:
                    col = np.array([v.lower() == "true" for v in raw])
                else:
                    npdt = field.dtype.to_numpy()
                    try:
                        col = np.array(
                            [
                                npdt.type(v) if v != "" else npdt.type(0)
                                for v in raw
                            ],
                            dtype=npdt,
                        )
                    except (ValueError, OverflowError) as e:
                        # value outside the inferred sample's type (schema
                        # was inferred from the first rows only)
                        raise SourceError(
                            f"CSV column {field.name!r} near row {start}: "
                            f"{e}; pass an explicit schema to CsvSource"
                        ) from None
                cols.append(col)
                masks.append(None if mask.all() else mask)
            batches.append(RecordBatch(schema, cols, masks))
        if not batches:
            batches = [RecordBatch.empty(schema)]
        super().__init__(
            [batches], timestamp_column, name=path,
            timestamp_unit=timestamp_unit,
        )
