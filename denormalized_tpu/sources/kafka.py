"""Kafka source/sink connectors over the native wire client.

Mirror of the reference's Kafka layer:
- ``KafkaTopicBuilder`` (kafka_config.rs:103-339): builder for reader/writer
  configs; schema from explicit schema, inferred from sample JSON, or from
  an Avro declaration; queries the broker for the partition count.
- ``KafkaStreamRead`` (kafka_stream_read.rs:87-298): one reader per
  partition; fetch → decode → canonical-timestamp attach; offsets persisted
  through the checkpoint layer and restored by seeking.
- ``TopicWriter``/``KafkaSink`` (topic_writer.rs): per-row JSON encode →
  produce.

Transport is :mod:`denormalized_tpu.native.kafka_client` (C++), the
librdkafka-equivalent.  JSON payload decode goes through the native one-pass
columnar parser when the schema is flat.
"""

from __future__ import annotations

import ctypes
import time

import numpy as np

from denormalized_tpu.common.errors import FormatError, SourceError
from denormalized_tpu.common.record_batch import RecordBatch
from denormalized_tpu.common.schema import DataType, Field, Schema
from denormalized_tpu.common.constants import CANONICAL_TIMESTAMP_COLUMN
from denormalized_tpu.formats import StreamEncoding, make_decoder
from denormalized_tpu.formats.json_codec import (
    JsonRowEncoder,
    infer_schema_from_json,
)
from denormalized_tpu.native.build import load
from denormalized_tpu.physical.simple_execs import Sink
from denormalized_tpu.runtime import faults
from denormalized_tpu.runtime.tracing import logger
from denormalized_tpu.sources.base import (
    PartitionReader,
    Source,
    canonicalize_schema,
)


def _lib():
    lib = load("kafka_client", ["-lz"])
    if not getattr(lib, "_kc_configured", False):
        lib.kc_connect.restype = ctypes.c_void_p
        lib.kc_connect.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_int,
        ]
        lib.kc_close.argtypes = [ctypes.c_void_p]
        lib.kc_error.restype = ctypes.c_char_p
        lib.kc_error.argtypes = [ctypes.c_void_p]
        lib.kc_partition_count.restype = ctypes.c_int
        lib.kc_partition_count.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.kc_list_offset.restype = ctypes.c_int64
        lib.kc_list_offset.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int, ctypes.c_int64,
        ]
        lib.kc_produce.restype = ctypes.c_int
        lib.kc_produce.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int,
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_int, ctypes.c_int64,
        ]
        lib.kc_fetch.restype = ctypes.c_int
        lib.kc_fetch.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int,
            ctypes.c_int64, ctypes.c_int, ctypes.c_int,
        ]
        lib.kc_rec_bytes.restype = ctypes.POINTER(ctypes.c_uint8)
        lib.kc_rec_bytes.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64)
        ]
        lib.kc_rec_offsets.restype = ctypes.POINTER(ctypes.c_uint64)
        lib.kc_rec_offsets.argtypes = [ctypes.c_void_p]
        lib.kc_rec_timestamps.restype = ctypes.POINTER(ctypes.c_int64)
        lib.kc_rec_timestamps.argtypes = [ctypes.c_void_p]
        lib.kc_next_offset.restype = ctypes.c_int64
        lib.kc_next_offset.argtypes = [ctypes.c_void_p]
        lib.kc_set_external_codecs.argtypes = [ctypes.c_void_p, ctypes.c_uint32]
        lib.kc_pending_count.restype = ctypes.c_int
        lib.kc_pending_count.argtypes = [ctypes.c_void_p]
        lib.kc_pending_codec.restype = ctypes.c_int
        lib.kc_pending_codec.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.kc_pending_data.restype = ctypes.POINTER(ctypes.c_uint8)
        lib.kc_pending_data.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.kc_ingest_decompressed.restype = ctypes.c_int
        lib.kc_ingest_decompressed.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_uint64,
        ]
        lib.kc_high_watermark.restype = ctypes.c_int64
        lib.kc_high_watermark.argtypes = [ctypes.c_void_p]
        lib.kc_tls_init.restype = ctypes.c_int
        lib.kc_tls_init.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int,
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int,
        ]
        lib.kc_sasl_plain.restype = ctypes.c_int
        lib.kc_sasl_plain.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_char_p, ctypes.c_int,
        ]
        # per-record absolute Kafka offsets (tolerate a stale .so without
        # the symbol — readers then skip fetch splitting)
        lib._kc_has_rec_kafka_offsets = hasattr(lib, "kc_rec_kafka_offsets")
        if lib._kc_has_rec_kafka_offsets:
            lib.kc_rec_kafka_offsets.restype = ctypes.POINTER(ctypes.c_int64)
            lib.kc_rec_kafka_offsets.argtypes = [ctypes.c_void_p]
        lib._kc_configured = True
    return lib


class KafkaClient:
    """Thin ctypes handle over the native client (one TCP connection).

    zstd record batches decode through a hybrid path: the C++ client
    stashes the compressed records section, Python decompresses it with
    the ``zstandard`` module (when importable), and the SAME C++ record
    parser re-ingests the result — full codec parity with librdkafka.
    Without the module, zstd batches keep the error-loudly behavior."""

    #: security.protocol values the native transport implements; anything
    #: else fails LOUDLY at connect (the reference inherits the full
    #: librdkafka surface via passthrough — kafka_config.rs:48-58 — so an
    #: unsupported value here must never silently fall back to plaintext)
    SUPPORTED_PROTOCOLS = ("PLAINTEXT", "SSL", "SASL_PLAINTEXT", "SASL_SSL")
    SUPPORTED_SASL_MECHANISMS = ("PLAIN",)

    def __init__(
        self,
        bootstrap_servers: str,
        external_codecs: bool = True,
        security: dict | None = None,
    ):
        host, _, port = bootstrap_servers.partition(":")
        proto = self._validate_security(security)
        self._libref = _lib()
        err = ctypes.create_string_buffer(256)
        self._h = self._libref.kc_connect(
            host.encode(), int(port or 9092), err, 256
        )
        if not self._h:
            raise SourceError(f"kafka connect failed: {err.value.decode()}")
        if proto != "PLAINTEXT":
            try:
                self._setup_security(proto, security or {}, host)
            except Exception:
                self.close()
                raise
        self._zstd = None
        if external_codecs:
            try:
                import zstandard

                self._zstd = zstandard.ZstdDecompressor()  # reused per batch
                self._libref.kc_set_external_codecs(self._h, 1 << 4)
            except ImportError:
                pass

    @classmethod
    def _validate_security(cls, security: dict | None) -> str:
        """Canonical security.protocol, validated BEFORE any socket opens
        — unsupported transport must be a loud error, never a silent
        plaintext fallback."""
        proto = (security or {}).get("security.protocol", "PLAINTEXT")
        proto = proto.strip().upper()
        if proto not in cls.SUPPORTED_PROTOCOLS:
            raise SourceError(
                f"unsupported security.protocol {proto!r}; this client "
                f"implements {'/'.join(cls.SUPPORTED_PROTOCOLS)}"
            )
        if proto.startswith("SASL"):
            mech = (security or {}).get("sasl.mechanism", "PLAIN")
            if mech.strip().upper() not in cls.SUPPORTED_SASL_MECHANISMS:
                raise SourceError(
                    f"unsupported sasl.mechanism {mech!r}; this client "
                    "implements "
                    f"{'/'.join(cls.SUPPORTED_SASL_MECHANISMS)} "
                    "(the reference reaches SCRAM/OAUTHBEARER through "
                    "librdkafka; not implemented here)"
                )
            if not (security or {}).get("sasl.username"):
                raise SourceError(
                    f"{proto} requires sasl.username and sasl.password"
                )
        return proto

    def _setup_security(self, proto: str, security: dict, host: str) -> None:
        err = ctypes.create_string_buffer(512)
        if proto in ("SSL", "SASL_SSL"):
            ca = security.get("ssl.ca.location")
            verify = str(
                security.get("enable.ssl.certificate.verification", "true")
            ).strip().lower() not in ("false", "0", "no")
            rc = self._libref.kc_tls_init(
                self._h,
                ca.encode() if ca else None,
                1 if verify else 0,
                host.encode(),
                err,
                512,
            )
            if rc != 0:
                raise SourceError(f"TLS to {host}: {err.value.decode()}")
        if proto in ("SASL_PLAINTEXT", "SASL_SSL"):
            user = security.get("sasl.username", "")
            password = security.get("sasl.password", "")
            rc = self._libref.kc_sasl_plain(
                self._h, user.encode(), password.encode(), err, 512
            )
            if rc != 0:
                raise SourceError(err.value.decode())

    def close(self):
        if self._h:
            self._libref.kc_close(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:  # dnzlint: allow(broad-except) destructors must never raise — close() can see half-torn ctypes state at interpreter teardown
            pass

    def _err(self) -> str:
        return self._libref.kc_error(self._h).decode()

    def _handle(self):
        if not self._h:
            raise SourceError("kafka client is closed")
        return self._h

    def partition_count(self, topic: str) -> int:
        n = self._libref.kc_partition_count(self._handle(), topic.encode())
        if n < 0:
            raise SourceError(f"metadata for {topic!r}: {self._err()}")
        return n

    def list_offset(self, topic: str, partition: int, ts: int) -> int:
        off = self._libref.kc_list_offset(
            self._handle(), topic.encode(), partition, ts
        )
        if off < 0:
            raise SourceError(f"list_offsets: {self._err()}")
        return off

    def produce(self, topic: str, partition: int, payloads: list[bytes]):
        if not payloads:
            return
        if faults.armed():  # unarmed path builds no key string
            faults.inject("kafka.produce", key=f"{topic}:{partition}")
        data = b"".join(payloads)
        offs = np.zeros(len(payloads) + 1, dtype=np.uint64)
        offs[1:] = np.cumsum([len(p) for p in payloads], dtype=np.uint64)
        rc = self._libref.kc_produce(
            self._handle(),
            topic.encode(),
            partition,
            data,
            offs.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            len(payloads),
            int(time.time() * 1000),
        )
        if rc != 0:
            raise SourceError(f"produce: {self._err()}")

    def fetch(
        self, topic: str, partition: int, offset: int,
        max_bytes: int = 4 << 20, max_wait_ms: int = 100,
    ) -> tuple[list[bytes], np.ndarray, int]:
        """→ (payloads, timestamps_ms, next_offset)."""
        lib = self._libref
        n = self._fetch_raw(topic, partition, offset, max_bytes, max_wait_ms)
        if n == 0:
            return [], np.empty(0, dtype=np.int64), offset
        nb = ctypes.c_uint64()
        bptr = lib.kc_rec_bytes(self._h, ctypes.byref(nb))
        raw = ctypes.string_at(bptr, nb.value) if nb.value else b""
        offs = np.ctypeslib.as_array(lib.kc_rec_offsets(self._h), shape=(n + 1,))
        ts = np.ctypeslib.as_array(
            lib.kc_rec_timestamps(self._h), shape=(n,)
        ).copy()
        payloads = [bytes(raw[offs[i] : offs[i + 1]]) for i in range(n)]
        return payloads, ts, int(lib.kc_next_offset(self._h))

    def _fetch_raw(self, topic, partition, offset, max_bytes, max_wait_ms) -> int:
        if faults.armed():  # unarmed path builds no key string
            faults.inject("kafka.fetch", key=f"{topic}:{partition}")
        n = self._libref.kc_fetch(
            self._handle(), topic.encode(), partition, offset, max_bytes, max_wait_ms
        )
        if n < 0:
            raise SourceError(f"fetch: {self._err()}")
        pending = self._libref.kc_pending_count(self._h)
        if pending:
            # decompress stashed externally-handled batches (zstd) and
            # re-ingest through the native record parser — BEFORE any arena
            # pointers are taken (ingest appends to the arena)
            for i in range(pending):
                ln = ctypes.c_uint64()
                dptr = self._libref.kc_pending_data(self._h, i, ctypes.byref(ln))
                raw = ctypes.string_at(dptr, ln.value)
                try:
                    dobj = self._zstd.decompressobj()
                    dec = dobj.decompress(raw)
                    if not dobj.eof:
                        # truncated frame: decompressobj returns partial
                        # output without raising — that's corrupt data here
                        raise ValueError("incomplete zstd frame")
                except Exception as e:
                    raise SourceError(
                        f"zstd decompression failed for fetched batch: {e}"
                    )
                rc = self._libref.kc_ingest_decompressed(
                    self._h, i, dec, len(dec)
                )
                if rc < 0:
                    raise SourceError(f"fetch: {self._err()}")
                n = rc
        return n

    def fetch_ptrs(
        self, topic: str, partition: int, offset: int,
        max_bytes: int = 4 << 20, max_wait_ms: int = 100,
    ):
        """Raw fetch handles: (n, bytes_ptr, offsets_ptr, timestamps,
        next_offset).  Pointers reference the client's arena and stay valid
        until the next fetch on this client."""
        lib = self._libref
        n = self._fetch_raw(topic, partition, offset, max_bytes, max_wait_ms)
        if n == 0:
            return 0, None, None, np.empty(0, dtype=np.int64), offset
        nb = ctypes.c_uint64()
        bptr = lib.kc_rec_bytes(self._h, ctypes.byref(nb))
        optr = lib.kc_rec_offsets(self._h)
        ts = np.ctypeslib.as_array(
            lib.kc_rec_timestamps(self._h), shape=(n,)
        ).copy()
        return n, bptr, optr, ts, int(lib.kc_next_offset(self._h))

    def rec_kafka_offsets(self, n: int) -> np.ndarray | None:
        """Absolute Kafka offset of each record in the LAST fetch (copy),
        or None on a stale native build without the export."""
        if not getattr(self._libref, "_kc_has_rec_kafka_offsets", False):
            return None
        return np.ctypeslib.as_array(
            self._libref.kc_rec_kafka_offsets(self._h), shape=(n,)
        ).copy()

    def high_watermark(self) -> int:
        """The partition high watermark reported by the LAST fetch
        response on this client — next_offset < high_watermark means the
        broker already holds more records (catch-up backlog)."""
        return int(self._libref.kc_high_watermark(self._handle()))


def _fetch_offsets(optr, n):
    """Offsets view for live arena pointers or coalesced ndarrays."""
    if isinstance(optr, np.ndarray):
        return optr
    return np.ctypeslib.as_array(optr, shape=(n + 1,))


def _fetch_raw_bytes(bptr, offs):
    """Materialize the record bytes of either buffer representation —
    the ONE place the bytes/pointer duality is resolved, so the salvage
    path can never diverge from the parse path."""
    if isinstance(bptr, (bytes, bytearray)):
        return bytes(bptr)
    return ctypes.string_at(bptr, int(offs[-1]))


def parse_fetch_arena(parser, n, bptr, optr, ts):
    """Parse a fetch arena zero-copy; compacts away zero-length payloads
    (tombstones) keeping the timestamp column aligned.  → (batch|None, ts).

    ``bptr``/``optr`` are either live arena pointers (valid until the next
    fetch on that client) or materialized buffers — ``bytes`` plus a
    uint64 offsets ndarray — from a coalesced multi-fetch decode unit."""
    offs = _fetch_offsets(optr, n)
    if isinstance(optr, np.ndarray):
        optr = offs.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64))
    data = (
        bptr
        if isinstance(bptr, (bytes, bytearray))
        else ctypes.cast(bptr, ctypes.c_void_p)
    )
    keep = np.diff(offs) > 0
    if keep.all():
        return parser.parse_ptr(data, optr, n), ts
    idx = np.nonzero(keep)[0]
    if len(idx) == 0:
        return None, np.empty(0, dtype=np.int64)
    raw = _fetch_raw_bytes(bptr, offs)
    pieces = [raw[offs[i] : offs[i + 1]] for i in idx]
    data = b"".join(pieces)
    coffs = np.zeros(len(pieces) + 1, dtype=np.uint64)
    coffs[1:] = np.cumsum([len(p) for p in pieces], dtype=np.uint64)
    batch = parser.parse_ptr(
        data,
        coffs.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        len(pieces),
    )
    return batch, ts[idx]


# -- builder (KafkaTopicBuilder, kafka_config.rs:103-339) ----------------


class KafkaTopicBuilder:
    def __init__(self, bootstrap_servers: str):
        self.bootstrap_servers = bootstrap_servers
        self.topic: str | None = None
        self.encoding = StreamEncoding.JSON
        self.group_id = "denormalized-tpu"
        self.timestamp_column: str | None = None
        self.timestamp_unit: str = "ms"
        self.user_schema: Schema | None = None
        self.avro_schema = None
        self.opts: dict[str, str] = {}

    def with_topic(self, topic: str) -> "KafkaTopicBuilder":
        self.topic = topic
        return self

    def with_encoding(self, encoding: str) -> "KafkaTopicBuilder":
        self.encoding = StreamEncoding.from_str(encoding)
        return self

    def with_group_id(self, group_id: str) -> "KafkaTopicBuilder":
        self.group_id = group_id
        return self

    def with_timestamp_column(self, col: str) -> "KafkaTopicBuilder":
        self.timestamp_column = col
        return self

    def with_timestamp_unit(self, unit: str) -> "KafkaTopicBuilder":
        """Unit of the designated event-time column (kafka_config.rs:42);
        normalized to canonical epoch-ms at ingest.  The broker record
        timestamp is always ms, so this only matters with
        ``with_timestamp_column``."""
        from denormalized_tpu.sources.base import validate_ts_unit

        self.timestamp_unit = validate_ts_unit(unit)
        return self

    def with_schema(self, schema: Schema) -> "KafkaTopicBuilder":
        self.user_schema = schema
        return self

    def infer_schema_from_json(self, sample: str) -> "KafkaTopicBuilder":
        self.user_schema = infer_schema_from_json(sample)
        return self

    def with_avro_schema(self, decl) -> "KafkaTopicBuilder":
        from denormalized_tpu.formats.avro_codec import parse_avro_schema

        self.avro_schema = parse_avro_schema(decl)
        self.encoding = StreamEncoding.AVRO
        self.user_schema = self.avro_schema.to_engine_schema()
        return self

    def with_option(self, key: str, value: str) -> "KafkaTopicBuilder":
        # option-string spelling of the typed builder knobs (the reference
        # accepts either; ConnectionOpts passthrough, kafka_config.rs:48-58)
        if key == "timestamp_unit":
            return self.with_timestamp_unit(value)
        self.opts[key] = value
        return self

    def build_reader(self) -> "KafkaSource":
        if not self.topic or self.user_schema is None:
            raise SourceError("build_reader needs topic and schema")
        return KafkaSource(self)

    def build_writer(self) -> "KafkaSinkWriter":
        if not self.topic:
            raise SourceError("build_writer needs a topic")
        return KafkaSinkWriter(
            self.bootstrap_servers, self.topic, security=self.opts
        )


class KafkaPartitionReader(PartitionReader):
    """Per-partition fetch loop (KafkaStreamRead, kafka_stream_read.rs:87)."""

    def __init__(self, src: "KafkaSource", partition: int):
        self._src = src
        self._client = KafkaClient(
            src.builder.bootstrap_servers, security=src.builder.opts
        )
        self._topic = src.builder.topic
        self._partition = partition
        auto_offset = src.builder.opts.get("auto.offset.reset", "earliest")
        ts = -2 if auto_offset == "earliest" else -1
        self._offset = self._client.list_offset(self._topic, partition, ts)
        self._decoder = make_decoder(
            src.builder.encoding, src.user_schema, src.builder.avro_schema
        )
        self._ts_col = src.builder.timestamp_column
        self._ts_unit = src.builder.timestamp_unit
        self._consecutive_failures = 0
        # fetch splitting: a 4MB fetch can span hundreds of ms of event
        # time, and the watermark only advances on batch MIN-ts — so one
        # oversized batch delays every window close behind it by the whole
        # fetch span.  Bounded batches keep watermark granularity (and the
        # compiled batch-bucket shape) tight.  Splitting uses the EXACT
        # per-record offsets the native client records for every fetch
        # (both decode paths): approximating slice-boundary offsets by
        # arithmetic would break checkpoint exactly-once on logs with
        # gaps (compaction, control records).
        raw_max = src.builder.opts.get("max.batch.rows", 32768)
        try:
            self._max_batch_rows = int(raw_max)
        except (TypeError, ValueError):
            raise SourceError(
                f"max.batch.rows must be an integer, got {raw_max!r}"
            ) from None
        if self._max_batch_rows < 1:
            raise SourceError(
                f"max.batch.rows must be >= 1, got {self._max_batch_rows}"
            )
        # fetch coalescing: a trickle of small fetches (live tail, or a
        # broker serving few batches per response) pays the per-parse
        # Python overhead once per tiny arena.  When a fetch comes back
        # under this row count AND the response's high watermark shows
        # backlog already at the broker, keep fetching with ZERO extra
        # wait and decode the copied arenas as ONE unit — larger decode
        # units, identical records, no added latency.  0 disables.
        raw_coal = src.builder.opts.get("fetch.coalesce.rows", 4096)
        try:
            self._coalesce_rows = int(raw_coal)
        except (TypeError, ValueError):
            raise SourceError(
                f"fetch.coalesce.rows must be an integer, got {raw_coal!r}"
            ) from None
        if self._coalesce_rows < 0:
            raise SourceError(
                "fetch.coalesce.rows must be >= 0, got "
                f"{self._coalesce_rows}"
            )
        self._pending_slices: list = []
        self._snap_offset = self._offset
        # per-partition consumer lag vs the broker high watermark,
        # refreshed on every fetch response (the reader's own catch-up
        # signal, now a first-class time series)
        from denormalized_tpu import obs

        self._obs_lag = obs.gauge(
            "dnz_kafka_consumer_lag_rows",
            topic=self._topic, partition=str(partition),
        )
        #: poison records skipped by per-record salvage decode — data the
        #: stream silently dropped to keep progressing; invisible to
        #: operators before this counter existed
        self.salvaged_rows = 0
        self._obs_salvaged = obs.gauge(
            "dnz_source_salvaged_rows",
            source=self._topic, partition=str(partition),
        )
        # backlog report from the last fetch response (None = unknown):
        # consumed by the prefetch engine's idleness judgment — a reader
        # that KNOWS the broker holds more records must never be judged
        # idle, even while its next fetch/decode is in flight
        self._caught_up: bool | None = None

    # transport failures are transient: log-and-retry with reconnect, like
    # the reference's recv error handling (kafka_stream_read.rs:210-218) —
    # only repeated failure surfaces an error (and the counter resets, so
    # later reads keep retrying if the caller chooses to continue)
    _MAX_CONSECUTIVE_FAILURES = 20
    _TRANSPORT_MARKERS = ("send:", "recv:", "connect", "closed", "disconnected")

    @classmethod
    def _is_transport_error(cls, err: SourceError) -> bool:
        msg = str(err)
        return any(m in msg for m in cls._TRANSPORT_MARKERS)

    def _handle_source_error(self, err: SourceError, max_wait: float):
        # OFFSET_OUT_OF_RANGE (broker error 1): the committed offset fell
        # off the log (retention / truncated restart) — honor
        # auto.offset.reset like a real consumer instead of retrying
        if "fetch error 1" in str(err) and self._client is not None:
            reset = self._src.builder.opts.get("auto.offset.reset", "earliest")
            ts = -2 if reset == "earliest" else -1
            self._offset = self._client.list_offset(
                self._topic, self._partition, ts
            )
            logger.warning(
                "kafka %s[%d]: offset out of range — reset to %s (%d)",
                self._topic, self._partition, reset, self._offset,
            )
            return RecordBatch.empty(self._src.schema)
        if not self._is_transport_error(err):
            raise err  # broker protocol error: not transient, surface now
        self._consecutive_failures += 1
        logger.warning(
            "kafka %s[%d]: %s (attempt %d) — reconnecting",
            self._topic, self._partition, err, self._consecutive_failures,
        )
        if self._consecutive_failures >= self._MAX_CONSECUTIVE_FAILURES:
            self._consecutive_failures = 0  # future reads retry again
            raise err
        self._caught_up = None  # broker unreachable: backlog unknown
        self.close()  # never reuse a possibly-freed handle
        try:
            self._client = KafkaClient(
                self._src.builder.bootstrap_servers,
                security=self._src.builder.opts,
            )
        except SourceError:
            pass  # broker still down; next read retries the reconnect
        # bounded backoff that respects the caller's read timeout contract
        time.sleep(min(0.05 * self._consecutive_failures, max(max_wait, 0.05)))
        return RecordBatch.empty(self._src.schema)

    def _attach_ts(self, batch, kafka_ts):
        """Canonical timestamp: payload column (normalized from the
        configured timestamp_unit to epoch-ms) or the broker record
        timestamp, which the wire protocol defines as ms
        (kafka_stream_read.rs:222-266)."""
        # decoder-output fault site: fires once per rowful decoded batch
        # on BOTH decode paths.  A (default, non-transport) error here
        # escapes the reader and exercises the prefetch supervisor; the
        # advanced fetch cursor is safe because the supervisor reseeks the
        # rebuilt reader to the last ENQUEUED snapshot.
        if faults.armed():  # unarmed path builds no key string
            faults.inject("decode", key=f"{self._topic}:{self._partition}")
        if self._ts_col is not None:
            from denormalized_tpu.sources.base import normalize_ts_to_ms

            ts = normalize_ts_to_ms(batch.column(self._ts_col), self._ts_unit)
        else:
            ts = kafka_ts
        return batch.with_column(
            Field(
                CANONICAL_TIMESTAMP_COLUMN, DataType.TIMESTAMP_MS, nullable=False
            ),
            ts,
        )

    def read(self, timeout_s: float | None = None):
        # zero-copy hot path: flat-JSON schemas parse straight from the
        # fetch arena (no Python payload objects).  The offset is committed
        # BEFORE decoding; a poison payload is salvaged per-record (below)
        # so the stream — and the offsets the checkpoint persists — keep
        # progressing past it without dropping its co-fetched good records.
        if self._pending_slices:
            batch, snap = self._pending_slices.pop(0)
            self._snap_offset = snap
            return batch
        native = getattr(self._decoder, "_native", None)
        max_wait = int((timeout_s or 0.1) * 1000)
        try:
            batch = self._read_once(native, max_wait)
        except SourceError as e:
            batch = self._handle_source_error(e, timeout_s or 0.1)
        if not self._pending_slices:
            # whole-fetch yield (no split): snapshot == fetch cursor
            self._snap_offset = self._offset
        return batch

    def _salvage_decode(self, payloads, kafka_ts, err):
        """A poison payload in the fetch: decode per-record and skip ONLY
        the undecodable ones.  Raising instead would abort the query with
        the advanced offset never checkpointed — a crash loop on restart —
        and dropping the whole fetch would lose up to 4MB of good records
        alongside one bad byte."""
        good, keep, first_err = [], [], err
        n_bad = 0
        for i, p in enumerate(payloads):
            if not p:
                continue  # tombstone: no data to lose, not "undecodable"
            try:
                self._decoder.push(p)
                b = self._decoder.flush()
            except FormatError as e:
                n_bad += 1
                if first_err is None:
                    first_err = e
                continue
            if b.num_rows:
                good.append(b)
                keep.append(i)
        self.salvaged_rows += n_bad
        self._obs_salvaged.set(self.salvaged_rows)
        logger.warning(
            "kafka %s[%d]: skipped %d undecodable record(s) at offsets "
            "<%d: %s",
            self._topic, self._partition, n_bad, self._offset, first_err,
        )
        if not good:
            return None, kafka_ts[:0]
        return RecordBatch.concat(good), kafka_ts[np.asarray(keep)]

    #: bound on fetches combined into one coalesced decode unit
    _MAX_COALESCED_FETCHES = 16

    def _coalesce_fetches(self, n, bptr, optr, kafka_ts, next_off):
        """Combine a small fetch with immediately-available backlog into
        one decode unit.  Arenas are copied (each fetch invalidates the
        previous fetch's pointers on this client); per-record absolute
        Kafka offsets are captured per fetch so oversize splitting keeps
        its exact checkpoint semantics.  Extra fetches use max_wait=0 —
        only records ALREADY at the broker coalesce, never added wait.
        → (n, data_bytes, offsets_ndarray, ts, next_off, rec_offs|None)."""
        offs = np.ctypeslib.as_array(optr, shape=(n + 1,))
        chunks = [(
            ctypes.string_at(bptr, int(offs[-1])),
            offs.copy(),
            kafka_ts,
            self._client.rec_kafka_offsets(n),
        )]
        total = n
        while (
            total < self._coalesce_rows
            and self._caught_up is False
            and len(chunks) < self._MAX_COALESCED_FETCHES
        ):
            try:
                n2, bptr2, optr2, ts2, off2 = self._client.fetch_ptrs(
                    self._topic, self._partition, self._offset, max_wait_ms=0
                )
            except SourceError:
                # records already collected must still decode — the
                # cursor has advanced past them; surface the transport
                # problem on the NEXT read instead of dropping data
                self._caught_up = None
                break
            self._offset = off2
            self._caught_up = off2 >= self._client.high_watermark()
            if n2 == 0:
                break
            next_off = off2
            offs2 = np.ctypeslib.as_array(optr2, shape=(n2 + 1,))
            chunks.append((
                ctypes.string_at(bptr2, int(offs2[-1])),
                offs2.copy(),
                ts2,
                self._client.rec_kafka_offsets(n2),
            ))
            total += n2
        if len(chunks) == 1:
            raw, offs0, ts, ro = chunks[0]
            return n, raw, offs0, ts, next_off, ro
        data = b"".join(c[0] for c in chunks)
        comb = np.zeros(total + 1, dtype=np.uint64)
        pos = 0
        shift = np.uint64(0)
        for raw, offs_c, _ts, _ro in chunks:
            k = len(offs_c) - 1
            comb[pos + 1 : pos + k + 1] = offs_c[1:] + shift
            pos += k
            shift += offs_c[-1]
        ts_all = np.concatenate([c[2] for c in chunks])
        rec_offs = None
        if all(c[3] is not None for c in chunks):
            rec_offs = np.concatenate([c[3] for c in chunks])
        return total, data, comb, ts_all, next_off, rec_offs

    def _read_once(self, native, max_wait):
        if self._client is None:
            raise SourceError("kafka client disconnected")
        if native is not None:
            n, bptr, optr, kafka_ts, next_off = self._client.fetch_ptrs(
                self._topic, self._partition, self._offset, max_wait_ms=max_wait
            )
            self._consecutive_failures = 0
            self._offset = next_off
            hw = self._client.high_watermark()
            self._caught_up = next_off >= hw
            self._obs_lag.set(max(0, hw - next_off))
            if n == 0:
                return RecordBatch.empty(self._src.schema)
            rec_offs = None
            if (
                self._coalesce_rows
                and n < self._coalesce_rows
                and self._caught_up is False
            ):
                n, bptr, optr, kafka_ts, next_off, rec_offs = (
                    self._coalesce_fetches(n, bptr, optr, kafka_ts, next_off)
                )
            try:
                batch, kafka_ts = parse_fetch_arena(
                    native, n, bptr, optr, kafka_ts
                )
            except FormatError as e:
                offs = _fetch_offsets(optr, n)
                raw = _fetch_raw_bytes(bptr, offs)
                payloads = [
                    raw[offs[i] : offs[i + 1]] for i in range(n)
                ]
                batch, kafka_ts = self._salvage_decode(payloads, kafka_ts, e)
            if batch is None:
                return RecordBatch.empty(self._src.schema)
            return self._maybe_split(
                self._attach_ts(batch, kafka_ts), n, next_off, rec_offs
            )

        payloads, kafka_ts, next_off = self._client.fetch(
            self._topic, self._partition, self._offset, max_wait_ms=max_wait
        )
        self._consecutive_failures = 0
        # commit before decode (see above)
        self._offset = next_off
        hw = self._client.high_watermark()
        self._caught_up = next_off >= hw
        self._obs_lag.set(max(0, hw - next_off))
        n_fetch = len(payloads)
        if not payloads:
            # live source: no data within the wait — empty batch, stay open
            return RecordBatch.empty(self._src.schema)
        # drop zero-length payloads together with their timestamps so rows
        # and the kafka-timestamp column stay aligned
        if any(len(p) == 0 for p in payloads):
            keep = [i for i, p in enumerate(payloads) if len(p)]
            kafka_ts = kafka_ts[keep]
            payloads = [payloads[i] for i in keep]
            if not payloads:
                return RecordBatch.empty(self._src.schema)
        try:
            for p in payloads:
                self._decoder.push(p)
            batch = self._decoder.flush()
        except FormatError as e:
            batch, kafka_ts = self._salvage_decode(payloads, kafka_ts, e)
            if batch is None:
                return RecordBatch.empty(self._src.schema)
        return self._maybe_split(
            self._attach_ts(batch, kafka_ts), n_fetch, next_off
        )

    def caught_up(self) -> bool | None:
        """Backlog report for the prefetch engine: ``False`` = the last
        fetch response showed records beyond this reader's cursor (a
        catch-up is in flight — never judge this partition idle),
        ``True`` = cursor at the high watermark, ``None`` = unknown (no
        fetch yet, or reconnecting)."""
        return self._caught_up

    def close(self) -> None:
        """Release the native client connection — the prefetch supervisor
        calls this on the crashed reader it replaces, so restarts never
        leak broker sockets/arena handles until interpreter exit."""
        old = self._client
        self._client = None
        if old is not None:
            try:
                old.close()
            except Exception:  # dnzlint: allow(broad-except) best-effort release of a dead broker connection — the caller is replacing it precisely because it failed
                pass

    def decode_fallback_rows(self) -> int:
        # the decoder counts rows it pushed through the Python path (the
        # zero-copy native arena parse never touches the decoder's
        # push/flush, so native rows stay out of the count by design)
        return int(getattr(self._decoder, "decode_fallback_rows", 0))

    def offset_snapshot(self) -> dict:
        # _snap_offset trails _offset while a split fetch drains: it
        # covers exactly the YIELDED slices, so a barrier between slices
        # checkpoints neither lost nor duplicated rows
        return {"partition": self._partition, "offset": int(self._snap_offset)}

    def offset_restore(self, snap: dict) -> None:
        # in-flight work past the restored offset — undrained split
        # slices here, plus anything a prefetch worker buffered upstream
        # (discarded by the restore happening BEFORE workers spawn) —
        # must be dropped, not replayed on top of the seek-back
        self._offset = int(snap.get("offset", self._offset))
        self._snap_offset = self._offset
        self._pending_slices.clear()
        self._caught_up = None

    def _maybe_split(self, batch, n_fetch, next_off, rec_offs=None):
        """Split an oversized CLEANLY-decoded batch.  Rows must align 1:1
        with the fetch's records for the per-record offsets to apply —
        tombstone-dropped or salvaged fetches skip splitting.  A
        coalesced decode unit passes its per-fetch-captured ``rec_offs``
        (the client only retains the LAST fetch's)."""
        if batch.num_rows > self._max_batch_rows and batch.num_rows == n_fetch:
            if rec_offs is None:
                rec_offs = self._client.rec_kafka_offsets(n_fetch)
            return self._split_oversized(batch, rec_offs, next_off)
        return batch

    def _split_oversized(self, batch, rec_offs, next_off):
        """Return the first ≤max.batch.rows slice; stash the rest with the
        EXACT kafka offset each slice's yield advances the snapshot to."""
        n = batch.num_rows
        if n <= self._max_batch_rows or rec_offs is None:
            self._snap_offset = next_off
            return batch
        for a in range(0, n, self._max_batch_rows):
            b = min(a + self._max_batch_rows, n)
            snap = next_off if b == n else int(rec_offs[b])
            self._pending_slices.append((batch.slice(a, b - a), snap))
        batch, self._snap_offset = self._pending_slices.pop(0)
        return batch


class KafkaSource(Source):
    def __init__(self, builder: KafkaTopicBuilder):
        self.builder = builder
        self.name = builder.topic
        self.user_schema = builder.user_schema
        self._schema = canonicalize_schema(builder.user_schema)
        client = KafkaClient(builder.bootstrap_servers,
                             security=builder.opts)
        try:
            self._npartitions = client.partition_count(builder.topic)
        finally:
            client.close()
        if self._npartitions <= 0:
            raise SourceError(f"topic {builder.topic!r} has no partitions")

    @property
    def schema(self) -> Schema:
        return self._schema

    def partitions(self) -> list[PartitionReader]:
        return [
            KafkaPartitionReader(self, p) for p in range(self._npartitions)
        ]

    def partition_factories(self) -> list:
        """Per-partition rebuild hooks for the prefetch supervisor: a
        fresh reader opens its own native client connection, then the
        supervisor seeks it to the last enqueued offset snapshot."""
        return [
            (lambda p=p: KafkaPartitionReader(self, p))
            for p in range(self._npartitions)
        ]

    @property
    def unbounded(self) -> bool:
        return True

    def with_projection(self, names: set[str]):
        """JSON decode is key-matched, so a narrowed schema skips unneeded
        fields inside the native parser — decode work drops with the column
        count.  Avro decode is POSITIONAL (every field must be walked), so
        pushdown is declined there."""
        import copy

        if self.builder.encoding is not StreamEncoding.JSON:
            return None
        keep = set(names)
        if self.builder.timestamp_column:
            keep.add(self.builder.timestamp_column)
        fields = [f for f in self.user_schema if f.name in keep]
        if len(fields) == len(self.user_schema) or not fields:
            return None  # nothing to prune (or nothing left: fall back)
        src = copy.copy(self)
        src.builder = copy.copy(self.builder)
        src.builder.user_schema = Schema(fields)
        src.user_schema = src.builder.user_schema
        src._schema = canonicalize_schema(src.user_schema)
        return src


class KafkaSinkWriter(Sink):
    """JSON row producer (KafkaSink::write_all, topic_writer.rs:102-127),
    round-robin over partitions.

    Produce failures retry a bounded number of times with exponential
    backoff + jitter (the ``commit_retries`` pattern from
    state/checkpoint.py) before surfacing: the sink was the last I/O
    boundary where ONE broker hiccup failed the whole segment while
    every other boundary self-heals.  A retry after a produce whose
    response was lost can duplicate records — the sink's existing
    at-least-once contract, now merely more likely to be exercised."""

    #: bounded transient-produce retries (attempt count, not extra tries)
    _WRITE_ATTEMPTS = 4
    _BACKOFF_BASE_S = 0.05

    def __init__(self, bootstrap_servers: str, topic: str,
                 security: dict | None = None):
        from denormalized_tpu import obs

        self._client = KafkaClient(bootstrap_servers, security=security)
        self._topic = topic
        self._encoder = JsonRowEncoder()
        try:
            self._npartitions = max(self._client.partition_count(topic), 1)
        except SourceError:
            self._npartitions = 1
        self._rr = 0
        #: transient produce errors absorbed by the bounded retry
        self.sink_retries = 0
        self._obs_retries = obs.counter("dnz_sink_retries_total")

    def write(self, batch: RecordBatch) -> None:
        import random

        payloads = self._encoder.encode(batch)
        if not payloads:
            return
        last: SourceError | None = None
        for attempt in range(1, self._WRITE_ATTEMPTS + 1):
            try:
                faults.inject("sink.write", key=self._topic)
                self._client.produce(self._topic, self._rr, payloads)
                last = None
                break
            except SourceError as e:
                last = e
                self.sink_retries += 1
                self._obs_retries.add(1)
                logger.warning(
                    "kafka sink %s: produce failed (%s) — attempt %d/%d",
                    self._topic, e, attempt, self._WRITE_ATTEMPTS,
                )
                if attempt < self._WRITE_ATTEMPTS:
                    # exp backoff + jitter so N writers recovering from
                    # one broker flap don't re-stampede it in lockstep
                    time.sleep(
                        self._BACKOFF_BASE_S
                        * (2 ** (attempt - 1))
                        * (1.0 + random.random())
                    )
        if last is not None:
            raise last
        self._rr = (self._rr + 1) % self._npartitions

    def close(self) -> None:
        self._client.close()
