"""In-memory / replay sources.

The deliberate test seam the reference lacks (SURVEY.md §4: its de-facto
integration test is running examples against a live Kafka docker image).  A
:class:`MemorySource` replays pre-built batches deterministically, partitioned
like a Kafka topic; :class:`GeneratorSource` synthesizes load in-process (the
`emit_measurements` analog, examples/examples/emit_measurements.rs:17-84)
without a broker.
"""

from __future__ import annotations

import time
from typing import Callable, Iterable, Sequence

from denormalized_tpu.common.record_batch import RecordBatch
from denormalized_tpu.common.schema import Schema
from denormalized_tpu.sources.base import (
    PartitionReader,
    Source,
    attach_canonical_timestamp,
    canonicalize_schema,
    validate_ts_unit,
)


class _MemoryPartition(PartitionReader):
    def __init__(
        self,
        batches: Sequence[RecordBatch],
        timestamp_column: str | None,
        timestamp_unit: str = "ms",
    ) -> None:
        self._batches = list(batches)
        self._pos = 0
        self._ts_col = timestamp_column
        self._ts_unit = timestamp_unit

    def read(self, timeout_s: float | None = None):
        while self._pos < len(self._batches):
            b = self._batches[self._pos]
            self._pos += 1
            b = attach_canonical_timestamp(
                b, self._ts_col, fallback_ms=int(time.time() * 1000),
                timestamp_unit=self._ts_unit,
            )
            return b
        return None

    def offset_snapshot(self) -> dict:
        return {"pos": self._pos}

    def offset_restore(self, snap: dict) -> None:
        self._pos = int(snap.get("pos", 0))


class MemorySource(Source):
    """Replayable bounded source over per-partition batch lists."""

    def __init__(
        self,
        partition_batches: Sequence[Sequence[RecordBatch]],
        timestamp_column: str | None = None,
        name: str = "memory",
        timestamp_unit: str = "ms",
    ) -> None:
        if not partition_batches or not any(len(p) for p in partition_batches):
            raise ValueError("MemorySource needs at least one batch")
        self._parts = [list(p) for p in partition_batches]
        self._ts_col = timestamp_column
        self._ts_unit = validate_ts_unit(timestamp_unit)
        self.name = name
        first = next(b for p in self._parts for b in p)
        user_schema = first.schema
        self._schema = canonicalize_schema(user_schema)

    @staticmethod
    def from_batches(
        batches: Sequence[RecordBatch],
        timestamp_column: str | None = None,
        num_partitions: int = 1,
        name: str = "memory",
        timestamp_unit: str = "ms",
    ) -> "MemorySource":
        parts: list[list[RecordBatch]] = [[] for _ in range(num_partitions)]
        for i, b in enumerate(batches):
            parts[i % num_partitions].append(b)
        return MemorySource(parts, timestamp_column, name, timestamp_unit)

    @property
    def schema(self) -> Schema:
        return self._schema

    def partitions(self) -> list[PartitionReader]:
        return [
            _MemoryPartition(p, self._ts_col, self._ts_unit)
            for p in self._parts
        ]

    @property
    def unbounded(self) -> bool:
        return False


class _GeneratorPartition(PartitionReader):
    def __init__(
        self,
        gen: Iterable[RecordBatch],
        timestamp_column: str | None,
        timestamp_unit: str = "ms",
    ) -> None:
        self._it = iter(gen)
        self._ts_col = timestamp_column
        self._ts_unit = timestamp_unit
        self._count = 0

    def read(self, timeout_s: float | None = None):
        try:
            b = next(self._it)
        except StopIteration:
            return None
        self._count += 1
        return attach_canonical_timestamp(
            b, self._ts_col, fallback_ms=int(time.time() * 1000),
            timestamp_unit=self._ts_unit,
        )

    def offset_snapshot(self) -> dict:
        return {"count": self._count}


class GeneratorSource(Source):
    """Synthesized stream: one generator factory per partition."""

    def __init__(
        self,
        user_schema: Schema,
        partition_factories: Sequence[Callable[[], Iterable[RecordBatch]]],
        timestamp_column: str | None = None,
        unbounded: bool = True,
        name: str = "generator",
        timestamp_unit: str = "ms",
    ) -> None:
        self._schema = canonicalize_schema(user_schema)
        self._factories = list(partition_factories)
        self._ts_col = timestamp_column
        self._ts_unit = validate_ts_unit(timestamp_unit)
        self._unbounded = unbounded
        self.name = name

    @property
    def schema(self) -> Schema:
        return self._schema

    def partitions(self) -> list[PartitionReader]:
        return [
            _GeneratorPartition(f(), self._ts_col, self._ts_unit)
            for f in self._factories
        ]

    @property
    def unbounded(self) -> bool:
        return self._unbounded
