"""Source connector abstraction.

Mirror of the reference's source seam: a ``TableProvider`` whose scan yields
one ``PartitionStream`` per Kafka partition (topic_reader.rs:25-80,
stream_table.rs:57-65).  A :class:`Source` describes schema + partitioning;
each :class:`PartitionReader` is an independent cursor that the source exec
drives (on threads for live connectors).

Every source attaches the canonical event-time column
(``CANONICAL_TIMESTAMP_COLUMN``) exactly like the reference's
``KafkaStreamRead`` attaches ``canonical_timestamp`` from either the broker
timestamp or a designated payload column (kafka_stream_read.rs:222-266).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from denormalized_tpu.common.constants import CANONICAL_TIMESTAMP_COLUMN
from denormalized_tpu.common.record_batch import RecordBatch
from denormalized_tpu.common.schema import DataType, Field, Schema


def canonicalize_schema(user_schema: Schema) -> Schema:
    """User schema + internal event-time column (the reference's
    ``create_canonical_schema``, kafka_config.rs:186-214)."""
    if user_schema.has(CANONICAL_TIMESTAMP_COLUMN):
        return user_schema
    return user_schema.append(
        Field(CANONICAL_TIMESTAMP_COLUMN, DataType.TIMESTAMP_MS, nullable=False)
    )


def attach_canonical_timestamp(
    batch: RecordBatch, timestamp_column: str | None, fallback_ms: int
) -> RecordBatch:
    """Attach event time: from ``timestamp_column`` when configured, else the
    ingestion time (the Kafka-broker-timestamp analog)."""
    if batch.schema.has(CANONICAL_TIMESTAMP_COLUMN):
        return batch
    if timestamp_column is not None:
        ts = np.asarray(batch.column(timestamp_column), dtype=np.int64)
    else:
        ts = np.full(batch.num_rows, fallback_ms, dtype=np.int64)
    return batch.with_column(
        Field(CANONICAL_TIMESTAMP_COLUMN, DataType.TIMESTAMP_MS, nullable=False), ts
    )


class PartitionReader:
    """Cursor over one source partition."""

    def read(self, timeout_s: float | None = None) -> Optional[RecordBatch]:
        """Next batch, or None when the partition is exhausted (bounded
        sources) / the timeout elapsed (live sources return empty batches)."""
        raise NotImplementedError

    # -- checkpoint hooks (reference BatchReadMetadata offsets,
    # kafka_stream_read.rs:49-65,275-289) -------------------------------
    def offset_snapshot(self) -> dict:
        return {}

    def offset_restore(self, snap: dict) -> None:
        pass


class Source:
    name: str = "source"

    @property
    def schema(self) -> Schema:
        """Canonical schema (includes internal timestamp column)."""
        raise NotImplementedError

    def partitions(self) -> list[PartitionReader]:
        raise NotImplementedError

    @property
    def unbounded(self) -> bool:
        return True

    def with_projection(self, names: set[str]) -> "Source | None":
        """Reader-level projection pushdown: return a copy of this source
        that only DECODES the named columns, or None when unsupported (the
        optimizer then falls back to a Project above the Scan).  The
        canonical timestamp machinery must keep working — implementations
        retain their timestamp column regardless of ``names``."""
        return None
