"""Source connector abstraction.

Mirror of the reference's source seam: a ``TableProvider`` whose scan yields
one ``PartitionStream`` per Kafka partition (topic_reader.rs:25-80,
stream_table.rs:57-65).  A :class:`Source` describes schema + partitioning;
each :class:`PartitionReader` is an independent cursor that the source exec
drives (on threads for live connectors).

Every source attaches the canonical event-time column
(``CANONICAL_TIMESTAMP_COLUMN``) exactly like the reference's
``KafkaStreamRead`` attaches ``canonical_timestamp`` from either the broker
timestamp or a designated payload column (kafka_stream_read.rs:222-266).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from denormalized_tpu.common.constants import CANONICAL_TIMESTAMP_COLUMN
from denormalized_tpu.common.errors import SourceError
from denormalized_tpu.common.record_batch import RecordBatch
from denormalized_tpu.common.schema import DataType, Field, Schema

# timestamp_unit spellings → canonical unit (kafka_config.rs:42 declares
# the event-time column's unit; without it a seconds- or
# microseconds-resolution topic silently mis-windows by 1000x)
_TS_UNITS = {
    "s": "s", "sec": "s", "second": "s", "seconds": "s",
    "ms": "ms", "millisecond": "ms", "milliseconds": "ms",
    "us": "us", "microsecond": "us", "microseconds": "us",
    "ns": "ns", "nanosecond": "ns", "nanoseconds": "ns",
}


def validate_ts_unit(unit: str | None) -> str:
    """Canonicalize a timestamp_unit spelling; raise loudly at BUILD time
    for unsupported units (not per-batch, deep in the read loop)."""
    canon = _TS_UNITS.get((unit or "ms").strip().lower())
    if canon is None:
        raise SourceError(
            f"unsupported timestamp_unit {unit!r}; expected one of "
            "s / ms / us / ns"
        )
    return canon


def normalize_ts_to_ms(col, unit: str | None):
    """Event-time column → canonical epoch-milliseconds int64.  Float
    columns scale before truncation (a float-seconds column must not lose
    its sub-second part)."""
    unit = validate_ts_unit(unit)
    if unit == "ms":
        return np.asarray(col, dtype=np.int64)
    a = np.asarray(col)
    if unit == "s":
        if a.dtype.kind == "f":
            return np.round(a * 1000.0).astype(np.int64)
        return a.astype(np.int64, copy=False) * 1000
    div = 1000 if unit == "us" else 1_000_000
    if a.dtype.kind == "f":
        return np.round(a / div).astype(np.int64)
    return a.astype(np.int64, copy=False) // div


def canonicalize_schema(user_schema: Schema) -> Schema:
    """User schema + internal event-time column (the reference's
    ``create_canonical_schema``, kafka_config.rs:186-214)."""
    if user_schema.has(CANONICAL_TIMESTAMP_COLUMN):
        return user_schema
    return user_schema.append(
        Field(CANONICAL_TIMESTAMP_COLUMN, DataType.TIMESTAMP_MS, nullable=False)
    )


def attach_canonical_timestamp(
    batch: RecordBatch,
    timestamp_column: str | None,
    fallback_ms: int,
    timestamp_unit: str | None = "ms",
) -> RecordBatch:
    """Attach event time: from ``timestamp_column`` when configured
    (normalized from ``timestamp_unit`` to epoch-ms), else the ingestion
    time (the Kafka-broker-timestamp analog, always ms)."""
    if batch.schema.has(CANONICAL_TIMESTAMP_COLUMN):
        return batch
    if timestamp_column is not None:
        ts = normalize_ts_to_ms(batch.column(timestamp_column), timestamp_unit)
    else:
        ts = np.full(batch.num_rows, fallback_ms, dtype=np.int64)
    return batch.with_column(
        Field(CANONICAL_TIMESTAMP_COLUMN, DataType.TIMESTAMP_MS, nullable=False), ts
    )


class PartitionReader:
    """Cursor over one source partition."""

    def read(self, timeout_s: float | None = None) -> Optional[RecordBatch]:
        """Next batch, or None when the partition is exhausted (bounded
        sources) / the timeout elapsed (live sources return empty batches)."""
        raise NotImplementedError

    # -- checkpoint hooks (reference BatchReadMetadata offsets,
    # kafka_stream_read.rs:49-65,275-289) -------------------------------
    def offset_snapshot(self) -> dict:
        return {}

    def offset_restore(self, snap: dict) -> None:
        pass

    # -- optional decode-path observability ------------------------------
    def decode_fallback_rows(self) -> int:
        """Rows this reader decoded through a pure-Python fallback path
        (native parser unavailable, or the schema has a shape the native
        shredder declines).  Aggregated into ``SourceExec.metrics()`` so
        a topic silently riding the ~30x-slower decode path is visible —
        0 for readers with no payload decode stage (memory, CSV)."""
        return 0

    # -- optional backlog report ----------------------------------------
    def caught_up(self) -> bool | None:
        """Does this reader KNOW whether more data is already waiting at
        the source?  ``False`` = yes, backlog exists (the prefetch
        engine then never judges the partition idle, even mid-fetch);
        ``True`` = the cursor is at the source's frontier; ``None``
        (default) = no backlog knowledge — idleness falls back to the
        wall-clock-since-last-rows judgment."""
        return None


class Source:
    name: str = "source"

    @property
    def schema(self) -> Schema:
        """Canonical schema (includes internal timestamp column)."""
        raise NotImplementedError

    def partitions(self) -> list[PartitionReader]:
        raise NotImplementedError

    def partition_factories(self) -> "list | None":
        """Optional per-partition reader factories for the prefetch
        supervisor: element ``i`` is a zero-arg callable rebuilding
        partition ``i``'s reader after its worker crashed (the supervisor
        then seeks the fresh reader to the last enqueued offset snapshot
        via ``offset_restore``).  ``None`` (default) disables supervised
        restarts for this source — a worker crash surfaces as a query
        error, the pre-supervisor behavior."""
        return None

    @property
    def unbounded(self) -> bool:
        return True

    def with_projection(self, names: set[str]) -> "Source | None":
        """Reader-level projection pushdown: return a copy of this source
        that only DECODES the named columns, or None when unsupported (the
        optimizer then falls back to a Project above the Scan).  The
        canonical timestamp machinery must keep working — implementations
        retain their timestamp column regardless of ``names``."""
        return None
