"""Symmetric streaming hash join.

The reference gets stream-stream joins "for free" from DataFusion's join over
two windowed streams (datastream.rs:126-177; examples/examples/stream_join.rs
joins two windowed aggregates on (sensor, window bounds)).  We implement the
streaming join ourselves: a symmetric hash join that builds a table per side
and probes the opposite table as batches arrive from either input.

The build/probe machinery is fully vectorized: join keys intern through ONE
shared :class:`GroupInterner` (both sides see the same dense ids, and string
keys ride the native PyObject fast path), and each side keeps its rows as
chained arrays — ``head[gid]`` points at the side's newest row for a key and
``link[row]`` at the previous one.  Inserts chain an entire batch with a
stable sort over its gids; probes walk all chains simultaneously, peeling
one chain hop per numpy iteration (iterations = longest duplicate chain, 1
for unique-key streams).  No per-row Python in either direction — the raw
1M ev/s stream-join case the reference inherits from DataFusion's
vectorized join no longer melts here either.

Memory is bounded by watermark-driven eviction: a row can only match rows
whose event time is within ``retention_ms`` of the join watermark (the min of
both sides' watermarks), after which it is evicted — and, for outer joins,
emitted unmatched at eviction/EOS.  Both children are pumped by threads so a
slow side cannot stall the other (the reference relies on tokio task
scheduling for the same property).

Two extensions ride the same probe (docs/joins.md):

- **Hot-key sub-partitioning** (PanJoin-style skew adaptation): a
  celebrity key's chain walk costs one numpy iteration per retained
  duplicate — O(chain) serialization.  When the closed-loop policy
  (obs/doctor/actions.py) names a key hot from the intern-time
  Space-Saving sketch, :meth:`_SideState.adapt` migrates that key's
  rows out of the hash chains into a dense contiguous block
  (:class:`_HotStore`, SoA like SessionTable), and probes against it
  become one mask + one contiguous multi-arange gather.  Cold keys keep
  the chain path untouched; ``fold`` re-chains a decayed key.  Pair
  ORDER is part of the operator contract — probe-major, newest-first
  per probe row — and both layouts produce it exactly, so an adapted
  run's emissions are byte-identical to the unadapted oracle.
- **Band (interval) predicates**: ``left_expr - right_expr ∈ [lower,
  upper]`` evaluated per side at insert into a cached per-row value
  array, then applied to the equi pairs as one vectorized filter
  BEFORE any row gather — the enrichment/temporal-correlation shape
  (``ts BETWEEN a AND b``) costs index arithmetic, not materialization.
"""

from __future__ import annotations

import queue as queue_mod
import threading
import time
from collections import deque
from typing import Iterator

import numpy as np

from denormalized_tpu.common.constants import CANONICAL_TIMESTAMP_COLUMN
from denormalized_tpu.common.errors import PlanError
from denormalized_tpu.common.record_batch import RecordBatch
from denormalized_tpu.common.schema import Schema
from denormalized_tpu.logical.expr import Expr
from denormalized_tpu.logical.plan import JoinKind
from denormalized_tpu.ops.interner import GroupInterner
from denormalized_tpu.physical.base import (
    EOS,
    WM_ANNOUNCE,
    EndOfStream,
    ExecOperator,
    Marker,
    StreamItem,
    WatermarkHint,
)


def band_evict_mask(
    batch_max_ts: np.ndarray,
    horizon: int,
    batch_band_max: np.ndarray | None,
    band_horizon: float | None,
) -> np.ndarray:
    """Whole-batch eviction verdicts from the cached per-batch maxima:
    a batch drops when every retained row is older than the time
    horizon OR — for interval joins — its band maximum sits so far
    behind the other side's band watermark that no future row can land
    in band.  ONE vectorized compare per eviction tick over the cached
    maxima; retained row data is never rescanned here."""
    drop = batch_max_ts < horizon
    if band_horizon is not None and batch_band_max is not None:
        drop = drop | (batch_band_max < band_horizon)
    return drop


class _HotStore:
    """Dense hot-key sub-partitions for one join side.

    One pooled int64 row-id buffer holds every hot key's block as a
    contiguous run with slack (CSR-with-slack, SoA like SessionTable's
    slot table): per slot ``(gid, start, len, cap)``, plus a gid→slot
    ``lookup`` array sized like the side's ``head``.  Appends write
    in-place into the slack; a full block relocates to the pool tail
    with doubled capacity (amortized O(1) per appended row).  Block
    rows are ALWAYS ascending global row ids — migration selects rows
    in insert order and appends only ever add newer rows — which is
    what lets a snapshot carry one representative row per block and
    restore rebuild the exact layout.
    """

    __slots__ = (
        "pool", "used", "slot_gid", "slot_start", "slot_len", "slot_cap",
        "nslots", "lookup",
    )

    def __init__(self) -> None:
        # zeros, not empty: cross-thread accounting reads (state_info's
        # hot attribution) may race a relocation and observe slack —
        # zero is a VALID row id that degrades to stale numbers, where
        # uninitialized garbage would index out of bounds
        self.pool = np.zeros(1024, dtype=np.int64)
        self.used = 0
        self.slot_gid = np.full(8, -1, dtype=np.int64)
        self.slot_start = np.zeros(8, dtype=np.int64)
        self.slot_len = np.zeros(8, dtype=np.int64)
        self.slot_cap = np.zeros(8, dtype=np.int64)
        self.nslots = 0
        self.lookup = np.full(1024, -1, dtype=np.int64)  # gid -> slot

    # -- bookkeeping -----------------------------------------------------
    def ensure_gids(self, max_gid: int) -> None:
        cap = len(self.lookup)
        if max_gid < cap:
            return
        while cap <= max_gid:
            cap *= 2
        new = np.full(cap, -1, dtype=np.int64)
        new[: len(self.lookup)] = self.lookup
        self.lookup = new

    def contains(self, gid: int) -> bool:
        return 0 <= gid < len(self.lookup) and self.lookup[gid] >= 0

    def gids(self) -> np.ndarray:
        return self.slot_gid[: self.nslots].copy()

    def rows_total(self) -> int:
        return int(self.slot_len[: self.nslots].sum())

    def rows_all(self) -> np.ndarray:
        """Every hot row id (per-slot order, slots concatenated)."""
        if self.nslots == 0:
            return np.empty(0, dtype=np.int64)
        return np.concatenate([
            self.pool[self.slot_start[s]: self.slot_start[s]
                      + self.slot_len[s]]
            for s in range(self.nslots)
        ])

    def reps(self) -> list[int]:
        """One representative row id (the block's OLDEST row) per
        non-empty block — with the ascending-row-id invariant, a block
        is fully reconstructible from the gid its representative
        carries, so this is all a snapshot needs to persist."""
        return [
            int(self.pool[self.slot_start[s]])
            for s in range(self.nslots)
            if self.slot_len[s] > 0
        ]

    def clear(self) -> None:
        if self.nslots:
            self.lookup[self.slot_gid[: self.nslots]] = -1
        self.slot_gid[: self.nslots] = -1
        self.slot_len[: self.nslots] = 0
        self.nslots = 0
        self.used = 0

    # -- growth ----------------------------------------------------------
    def _compact(self) -> None:
        """Repack every block contiguous at the head of a fresh pool
        (reclaims relocation holes and removed blocks' slack).  Blocks
        are not position-ordered — relocations move them to the tail —
        so repacking copies into a new buffer, never in place."""
        need = int(
            np.maximum(64, 2 * self.slot_len[: self.nslots]).sum()
        )
        if need > len(self.pool):
            return  # not enough room even compacted — caller grows
        new_pool = np.zeros(len(self.pool), dtype=np.int64)
        new_start = self.slot_start.copy()
        new_used = 0
        for s in range(self.nslots):
            ln = int(self.slot_len[s])
            cap = max(64, 2 * ln)
            new_pool[new_used: new_used + ln] = self.pool[
                self.slot_start[s]: self.slot_start[s] + ln
            ]
            new_start[s] = new_used
            self.slot_cap[s] = cap
            new_used += cap
        # publish whole arrays (never mutate the live ones in place):
        # a racing accounting read sees either layout, or a brief
        # new-pool/old-starts mix whose row ids are stale-but-bounded
        self.pool = new_pool
        self.slot_start = new_start
        self.used = new_used

    def _ensure_pool(self, extra: int) -> None:
        if self.used + extra <= len(self.pool):
            return
        live = self.rows_total()
        if live + 2 * extra + 64 * max(self.nslots, 1) <= len(self.pool) // 2:
            self._compact()
            if self.used + extra <= len(self.pool):
                return
        cap = len(self.pool)
        while self.used + extra > cap:
            cap *= 2
        new = np.zeros(cap, dtype=np.int64)
        new[: self.used] = self.pool[: self.used]
        self.pool = new

    def _ensure_slots(self) -> None:
        if self.nslots < len(self.slot_gid):
            return
        cap = 2 * len(self.slot_gid)
        for name in ("slot_gid", "slot_start", "slot_len", "slot_cap"):
            old = getattr(self, name)
            new = np.full(cap, -1, dtype=np.int64) if name == "slot_gid" \
                else np.zeros(cap, dtype=np.int64)
            new[: self.nslots] = old[: self.nslots]
            setattr(self, name, new)

    # -- mutation --------------------------------------------------------
    def adopt(self, gid: int, rows: np.ndarray) -> None:
        """Open a block for ``gid`` with the given (ascending) rows."""
        n = len(rows)
        cap = max(64, 2 * n)
        self._ensure_pool(cap)
        self._ensure_slots()
        s = self.nslots
        start = self.used
        self.pool[start: start + n] = rows
        self.slot_gid[s] = gid
        self.slot_start[s] = start
        self.slot_len[s] = n
        self.slot_cap[s] = cap
        self.used += cap
        self.nslots += 1
        self.ensure_gids(gid)
        self.lookup[gid] = s

    def append(self, slot: int, rows: np.ndarray) -> None:
        """Append (ascending, newer-than-existing) rows to a block,
        relocating it to the tail with doubled capacity when full."""
        n = len(rows)
        ln = int(self.slot_len[slot])
        if ln + n > self.slot_cap[slot]:
            cap = max(64, 2 * (ln + n))
            self._ensure_pool(cap)
            old = self.pool[
                self.slot_start[slot]: self.slot_start[slot] + ln
            ].copy()
            start = self.used
            self.pool[start: start + ln] = old
            self.slot_start[slot] = start
            self.slot_cap[slot] = cap
            self.used += cap
        start = int(self.slot_start[slot])
        self.pool[start + ln: start + ln + n] = rows
        self.slot_len[slot] = ln + n

    def remove(self, gid: int) -> np.ndarray:
        """Close a block and return its rows (ascending); the pool hole
        is reclaimed by the next compaction."""
        s = int(self.lookup[gid])
        rows = self.pool[
            self.slot_start[s]: self.slot_start[s] + self.slot_len[s]
        ].copy()
        self.lookup[gid] = -1
        last = self.nslots - 1
        if s != last:
            for name in ("slot_gid", "slot_start", "slot_len", "slot_cap"):
                getattr(self, name)[s] = getattr(self, name)[last]
            self.lookup[self.slot_gid[s]] = s
        self.slot_gid[last] = -1
        self.slot_len[last] = 0
        self.nslots = last
        return rows

    # -- probe kernels (pinned loop-free in hotpaths.toml) ---------------
    def slot_of(self, gids: np.ndarray) -> np.ndarray:
        """Per-probe-row hot slot index (-1 = cold), bounds-safe for
        gids past the lookup's current capacity."""
        lk = self.lookup
        safe = np.minimum(gids.astype(np.int64), len(lk) - 1)
        return np.where(gids < len(lk), lk[safe], -1)

    def probe_pairs(
        self, slots: np.ndarray, p_idx: np.ndarray
    ) -> tuple[np.ndarray, np.ndarray]:
        """All (probe_row, build_row) pairs for hot probe rows: one
        multi-arange over the contiguous blocks — probe-major, newest
        build row first per probe row (the chain walk's per-key order),
        so hot and cold pairs interleave into one deterministic
        contract."""
        lens = self.slot_len[slots]
        nz = lens > 0
        if not nz.all():
            slots = slots[nz]
            p_idx = p_idx[nz]
            lens = lens[nz]
        total = int(lens.sum())
        if total == 0:
            e = np.empty(0, dtype=np.int64)
            return e, e.copy()
        pp = np.repeat(p_idx, lens)
        ends = np.cumsum(lens)
        k = np.arange(total, dtype=np.int64) - np.repeat(ends - lens, lens)
        bstart = np.repeat(self.slot_start[slots], lens)
        blen = np.repeat(lens, lens)
        bb = self.pool[bstart + (blen - 1 - k)]
        return pp, bb

    def nbytes(self) -> int:
        """Live accounting bytes: hot row ids only (pool slack and the
        gid lookup are capacity, deliberately excluded so the number is
        restore-invariant like all state_info fields)."""
        return self.rows_total() * int(self.pool.itemsize)


class _SideState:
    """Chained-array row store for one join side."""

    __slots__ = (
        "batches",
        "batch_max_ts",
        "batch_band_max",
        "band_wm",
        "head",
        "link",
        "row_bi",
        "row_ri",
        "row_gid",
        "matched",
        "row_band",
        "hot",
        "count",
        "watermark",
        "src_watermarks",
        "done",
    )

    def __init__(self, with_band: bool = False) -> None:
        self.batches: list[RecordBatch] = []  # retained row storage
        self.batch_max_ts: list[int] = []  # cached per-batch max event time
        # band-aware eviction bookkeeping (interval joins): per-batch max
        # FINITE band value (NaN matches nothing, so an all-NaN batch is
        # -inf = immediately band-dead), and this side's band watermark —
        # the max over batches of min finite band value, the band-space
        # analog of the event-time watermark.  The OTHER side's rows
        # whose band reach lies below band_wm - slack can never match a
        # future row of this side (docs/joins.md, band-aware eviction).
        self.batch_band_max: list[float] = []
        self.band_wm: float | None = None
        self.head = np.full(1024, -1, dtype=np.int64)  # gid -> newest row
        self.link = np.empty(1024, dtype=np.int64)  # row -> older same-key row
        self.row_bi = np.empty(1024, dtype=np.int32)
        self.row_ri = np.empty(1024, dtype=np.int32)
        self.row_gid = np.empty(1024, dtype=np.int32)
        self.matched = np.zeros(1024, dtype=bool)
        # cached band-expression value per row (interval joins); NaN =
        # null band value, which matches nothing
        self.row_band = np.empty(1024, dtype=np.float64) if with_band else None
        self.hot = _HotStore()
        self.count = 0
        self.watermark: int | None = None
        # True once this side's source sent a kind="partition" hint:
        # batch min-ts no longer advances this side's watermark
        self.src_watermarks = False
        self.done = False

    def _ensure_rows(self, n: int) -> None:
        need = self.count + n
        cap = len(self.link)
        if need <= cap:
            return
        while cap < need:
            cap *= 2
        names = ["link", "row_bi", "row_ri", "row_gid"]
        if self.row_band is not None:
            names.append("row_band")
        for name in names:
            old = getattr(self, name)
            new = np.empty(cap, dtype=old.dtype)
            new[: self.count] = old[: self.count]
            setattr(self, name, new)
        m = np.zeros(cap, dtype=bool)
        m[: self.count] = self.matched[: self.count]
        self.matched = m

    def ensure_gids(self, max_gid: int) -> None:
        cap = len(self.head)
        if max_gid < cap:
            return
        while cap <= max_gid:
            cap *= 2
        new = np.full(cap, -1, dtype=np.int64)
        new[: len(self.head)] = self.head
        self.head = new

    def _chain(self, gids: np.ndarray, rows: np.ndarray) -> None:
        """Link ``rows`` (ascending global ids) into the per-key chains with
        one stable sort: within a same-gid run each row links to its
        predecessor, the run's first row links to the key's previous head,
        and the run's last row becomes the new head."""
        n = len(gids)
        if n == 0:
            return
        order = np.argsort(gids, kind="stable")
        gs = gids[order]
        rs = rows[order]
        first = np.empty(n, dtype=bool)
        first[0] = True
        first[1:] = gs[1:] != gs[:-1]
        linkv = np.empty(n, dtype=np.int64)
        linkv[~first] = rs[:-1][~first[1:]]
        linkv[first] = self.head[gs[first]]
        self.link[rs] = linkv
        last = np.empty(n, dtype=bool)
        last[-1] = True
        last[:-1] = first[1:]
        self.head[gs[last]] = rs[last]

    def insert(
        self,
        batch: RecordBatch,
        gids: np.ndarray,
        band_vals: np.ndarray | None = None,
    ) -> None:
        """Append a batch and chain its rows — no per-row Python.  Rows
        whose key holds a hot sub-partition append to that block instead
        of the chains."""
        n = len(gids)
        self._ensure_rows(n)
        self.ensure_gids(int(gids.max()) if n else 0)
        base = self.count
        bi = len(self.batches)
        self.batches.append(batch)
        self.batch_max_ts.append(
            int(
                np.asarray(
                    batch.column(CANONICAL_TIMESTAMP_COLUMN), dtype=np.int64
                ).max()
            )
            if batch.num_rows
            else np.iinfo(np.int64).min
        )
        self.row_bi[base : base + n] = bi
        self.row_ri[base : base + n] = np.arange(n, dtype=np.int32)
        self.row_gid[base : base + n] = gids
        self.matched[base : base + n] = False
        if self.row_band is not None:
            self.row_band[base : base + n] = band_vals
            fin = band_vals[~np.isnan(band_vals)]
            if len(fin):
                self.batch_band_max.append(float(fin.max()))
                bmin = float(fin.min())
                self.band_wm = (
                    bmin if self.band_wm is None else max(self.band_wm, bmin)
                )
            else:
                self.batch_band_max.append(float("-inf"))
        self.count += n
        rows = np.arange(base, base + n, dtype=np.int64)
        if self.hot.nslots:
            slots = self.hot.slot_of(gids)
            hm = slots >= 0
            if hm.any():
                self._append_hot(slots[hm], rows[hm])
                rows = rows[~hm]
                gids = gids[~hm]
        self._chain(gids, rows)

    def _append_hot(self, slots: np.ndarray, rows: np.ndarray) -> None:
        """Route a batch's hot rows into their blocks: one segmented
        pass grouping by slot (iterates DISTINCT hot keys present in
        the batch — a handful — never rows)."""
        order = np.argsort(slots, kind="stable")
        ss = slots[order]
        rr = rows[order]
        bounds = np.nonzero(
            np.concatenate(([True], ss[1:] != ss[:-1]))
        )[0]
        ends = np.append(bounds[1:], len(ss))
        for b0, b1 in zip(bounds.tolist(), ends.tolist()):
            self.hot.append(int(ss[b0]), rr[b0:b1])

    def rebuild(
        self,
        batches: list[RecordBatch],
        batch_max_ts: list[int],
        gids: np.ndarray,
        bis: np.ndarray,
        ris: np.ndarray,
        matched: np.ndarray,
        band: np.ndarray | None = None,
    ) -> None:
        """Replace all chained state with the given rows (insert order).
        Hot sub-partitions are cleared — callers that keep keys hot
        re-adopt them via :meth:`rehot` right after."""
        self.batches = batches
        self.batch_max_ts = batch_max_ts
        self.head.fill(-1)
        self.hot.clear()
        self.count = 0
        m = len(gids)
        self._ensure_rows(m)
        if m:
            self.ensure_gids(int(gids.max()))
        self.row_bi[:m] = bis
        self.row_ri[:m] = ris
        self.row_gid[:m] = gids
        self.matched[:m] = matched
        if self.row_band is not None:
            self.row_band[:m] = band
            # recompute per-batch band maxima from the retained rows:
            # eviction is whole-batch, so every retained batch keeps all
            # its rows and the recomputed maxima equal the originals.
            # band_wm is a monotone high-water mark over ALL batches
            # ever inserted and survives the rebuild untouched.
            self.batch_band_max = []
            if m and band is not None:
                bounds = np.nonzero(
                    np.concatenate(([True], bis[1:] != bis[:-1]))
                )[0]
                vals = np.asarray(band[:m], dtype=np.float64)
                vals = np.where(np.isnan(vals), float("-inf"), vals)
                self.batch_band_max = [
                    float(x) for x in np.maximum.reduceat(vals, bounds)
                ]
        else:
            self.batch_band_max = []
        self.count = m
        self._chain(gids, np.arange(m, dtype=np.int64))

    # -- hot-key sub-partitioning ---------------------------------------
    def adapt(self, gid: int) -> bool:
        """Migrate one key's rows out of the hash chains into a dense
        hot block.  The chain is unlinked wholesale (``head[gid] = -1``
        — stale ``link`` entries are unreachable and harmless); block
        rows are the key's rows in insert order (ascending row ids)."""
        gid = int(gid)
        if self.hot.contains(gid):
            return False
        rows = np.nonzero(
            self.row_gid[: self.count] == gid
        )[0].astype(np.int64)
        self.hot.adopt(gid, rows)
        if gid < len(self.head):
            self.head[gid] = -1
        return True

    def fold(self, gid: int) -> None:
        """De-adapt: fold a decayed hot block back into the chains."""
        gid = int(gid)
        rows = self.hot.remove(gid)
        if len(rows):
            self._chain(
                np.full(len(rows), gid, dtype=np.int64), rows
            )

    def rehot(self, hot_gids) -> None:
        """Re-adopt hot keys after a :meth:`rebuild` renumbered rows
        (eviction, re-intern, restore): each key's block is exactly its
        rows in insert order.  ONE membership-mask + grouping pass over
        ``row_gid`` covers every hot key — eviction already pays one
        O(rows) rebuild, so re-adoption must not multiply that by the
        hot-key count."""
        self.hot.clear()
        gids_arr = np.unique(np.asarray(list(hot_gids), dtype=np.int64))
        if len(gids_arr) == 0:
            return
        rg = self.row_gid[: self.count].astype(np.int64, copy=False)
        mark = np.zeros(int(gids_arr.max()) + 1, dtype=bool)
        mark[gids_arr] = True
        safe = np.minimum(rg, len(mark) - 1)
        rows = np.nonzero((rg < len(mark)) & mark[safe])[0].astype(np.int64)
        # stable grouping keeps each key's rows ascending (insert order)
        order = np.argsort(rg[rows], kind="stable")
        rs = rows[order]
        gs = rg[rows][order]
        bounds = np.nonzero(
            np.concatenate(([True], gs[1:] != gs[:-1]))
        )[0] if len(rs) else np.empty(0, dtype=np.int64)
        ends = np.append(bounds[1:], len(rs))
        seen = set()
        for b0, b1 in zip(bounds.tolist(), ends.tolist()):
            g = int(gs[b0])
            seen.add(g)
            self.hot.adopt(g, rs[b0:b1])
        for g in gids_arr.tolist():
            if g not in seen:
                # a hot key whose rows all evicted keeps its (empty)
                # block — it stays hot until the policy folds it
                self.hot.adopt(int(g), np.empty(0, dtype=np.int64))
        for g in gids_arr.tolist():
            if g < len(self.head):
                self.head[g] = -1

    def probe(self, gids: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """All (probe_row, build_row) pairs for the batch, PROBE-MAJOR:
        ordered by probe row, newest build row first within one probe
        row.  Cold keys walk every chain simultaneously (one hop per
        numpy iteration = one per duplicate of the longest chain); hot
        keys expand their contiguous blocks in one multi-arange.  Both
        layouts produce the identical order, so adapting a key never
        changes emissions."""
        n = len(gids)
        safe = np.minimum(gids.astype(np.int64), len(self.head) - 1)
        cur = np.where(gids < len(self.head), self.head[safe], -1)
        p = np.arange(n, dtype=np.int64)
        outs_p: list[np.ndarray] = []
        outs_b: list[np.ndarray] = []
        while True:
            m = cur >= 0
            if not m.any():
                break
            p = p[m]
            cur = cur[m]
            outs_p.append(p)
            outs_b.append(cur)
            cur = self.link[cur]
        if outs_p:
            cp = np.concatenate(outs_p)
            cb = np.concatenate(outs_b)
            if len(outs_p) > 1:
                # the walk yields hop-major; re-order to probe-major
                # (hop h IS the newest-first rank within a probe row,
                # so (p, hop) is the contract order).  No sort needed:
                # hop blocks are nested prefixes of the probe set, so a
                # pair's destination is start[p] + hop — one bincount +
                # cumsum + scatter, O(pairs).  Single-hop batches —
                # every unique-key workload — skip even that.
                counts = np.bincount(cp, minlength=n)
                start = np.cumsum(counts) - counts
                hop_of = np.repeat(
                    np.arange(len(outs_p), dtype=np.int64),
                    [len(o) for o in outs_p],
                )
                dest = start[cp] + hop_of
                op_ = np.empty_like(cp)
                ob_ = np.empty_like(cb)
                op_[dest] = cp
                ob_[dest] = cb
                cp, cb = op_, ob_
        else:
            cp = np.empty(0, dtype=np.int64)
            cb = cp.copy()
        if not self.hot.nslots:
            return cp, cb
        slots = self.hot.slot_of(gids)
        hm = slots >= 0
        if not hm.any():
            return cp, cb
        hp, hb = self.hot.probe_pairs(
            slots[hm], np.nonzero(hm)[0].astype(np.int64)
        )
        if len(cp) == 0:
            return hp, hb
        if len(hp) == 0:
            return cp, cb
        return self.merge_pairs(cp, cb, hp, hb)

    @staticmethod
    def merge_pairs(
        cp: np.ndarray, cb: np.ndarray, hp: np.ndarray, hb: np.ndarray
    ) -> tuple[np.ndarray, np.ndarray]:
        """Merge two probe-major pair streams over DISJOINT probe rows
        (a probe row's key is either hot or cold, never both) into one
        probe-major stream — searchsorted offsets + two scatters, no
        sort over the combined pair count."""
        off_c = np.searchsorted(hp, cp)
        off_h = np.searchsorted(cp, hp)
        out_p = np.empty(len(cp) + len(hp), dtype=np.int64)
        out_b = np.empty(len(cp) + len(hp), dtype=np.int64)
        ic = np.arange(len(cp), dtype=np.int64) + off_c
        ih = np.arange(len(hp), dtype=np.int64) + off_h
        out_p[ic] = cp
        out_b[ic] = cb
        out_p[ih] = hp
        out_b[ih] = hb
        return out_p, out_b

    def gather(self, build_rows: np.ndarray) -> RecordBatch:
        """Materialize build-side rows (columns and masks) in order."""
        bis = self.row_bi[build_rows]
        ris = self.row_ri[build_rows]
        order = np.argsort(bis, kind="stable")
        inv = np.empty(len(order), dtype=np.int64)
        inv[order] = np.arange(len(order))
        bounds = np.nonzero(
            np.concatenate(([True], bis[order][1:] != bis[order][:-1]))
        )[0]
        ends = np.append(bounds[1:], len(order))
        pieces = []
        for b0, b1 in zip(bounds, ends):
            sel = order[b0:b1]
            pieces.append(
                self.batches[int(bis[sel[0]])].take(
                    ris[sel].astype(np.int64)
                )
            )
        merged = pieces[0] if len(pieces) == 1 else RecordBatch.concat(pieces)
        # back to probe-pair order
        return merged.take(inv)


class _JoinTier:
    """Cold tier of one streaming join: spills whole retained batches
    (the row payload — the chained index arrays stay resident, they ARE
    the probe structure) per side into the LSM, reloading a batch only
    when a probe hit, an outer-join unmatched emission, or a checkpoint
    actually needs its rows.  Cold rank: least-recently reloaded first,
    oldest event time as the tiebreak — retention-horizon rows evicted
    cold can die in the LSM without ever being read back.

    The newest batch of each side is never spilled (it is the batch the
    operator is processing)."""

    __slots__ = (
        "op", "node_id", "ctrl", "clock", "touch", "est", "blocks",
        "spilled_bytes", "spilled_rows", "_next",
    )

    #: estimated row-array overhead per retained row (link/bi/ri/gid/
    #: matched across the chained arrays)
    ROW_OVERHEAD = 32

    def __init__(self, op: "StreamingJoinExec", node_id: str, ctrl) -> None:
        self.op = op
        self.node_id = node_id
        self.ctrl = ctrl
        self.clock = 0
        # per side, aligned with side.batches: touch stamp + cached
        # accounting-bytes estimate; spilled-block map {bi: {...}}
        self.touch: list[list[int]] = [[], []]
        self.est: list[list[int]] = [[], []]
        self.blocks: list[dict[int, dict]] = [{}, {}]
        self.spilled_bytes = 0
        self.spilled_rows = 0
        self._next = 0
        ctrl.register(node_id, op, self.resident_bytes)

    def _side_idx(self, side) -> int:
        return 0 if side is self.op._sides[0] else 1

    def resident_bytes(self) -> int:
        """Cheap per-batch budget input: cached per-batch estimates of
        the RESIDENT batches plus the row-array overhead (O(#batches),
        the same bound the eviction scan already pays per batch).

        May be called from ANOTHER operator's thread (the controller
        sums every adapter): snapshot the list references and bound the
        index, so racing the join thread between batches.append and
        est.append tears to a one-batch underestimate, never an
        IndexError."""
        sides = self.op._sides
        if sides is None:
            return 0
        total = 0
        for sid, side in enumerate(sides):
            est = self.est[sid]
            batches = side.batches
            for bi in range(min(len(batches), len(est))):
                if batches[bi] is not None:
                    total += est[bi]
            total += side.count * self.ROW_OVERHEAD
        return total

    @property
    def any_spilled(self) -> bool:
        return bool(self.blocks[0]) or bool(self.blocks[1])

    def note_insert(self, side_id: int, batch: RecordBatch) -> None:
        from denormalized_tpu.obs.statewatch import rb_nbytes

        self.clock += 1
        self.touch[side_id].append(self.clock)
        self.est[side_id].append(rb_nbytes(batch))

    # -- reload-on-touch --------------------------------------------------
    def ensure_rows_resident(self, side, build_rows: np.ndarray) -> None:
        """Reload every spilled batch the given build rows live in —
        called right before ``gather`` materializes them."""
        sid = self._side_idx(side)
        if not self.blocks[sid] or len(build_rows) == 0:
            return
        for bi in np.unique(side.row_bi[build_rows]).tolist():
            if int(bi) in self.blocks[sid]:
                self._reload(sid, side, int(bi))
        self._write_manifest()

    def _reload(self, sid: int, side, bi: int) -> None:
        meta = self.blocks[sid].pop(bi)
        raw = self.ctrl.get_block(self.node_id, meta["id"])
        from denormalized_tpu.state.tiering import rb_from_blob

        schema = (self.op.left if sid == 0 else self.op.right).schema
        rb, _extra = rb_from_blob(raw, schema)
        side.batches[bi] = rb
        self.clock += 1
        self.touch[sid][bi] = self.clock
        self.spilled_bytes -= meta["bytes"]
        self.spilled_rows -= meta["rows"]
        self.ctrl.note_reload(self.node_id, 1, len(raw))
        self.ctrl.delete_block(self.node_id, meta["id"])
        self.op._state_info_cache = None

    # -- eviction interplay ----------------------------------------------
    def evict_prepare(
        self, side, is_left: bool, drop_bi: np.ndarray, um: np.ndarray | None
    ) -> None:
        """Before the eviction gather: reload dropped spilled batches
        that still owe unmatched emissions; DELETE the rest unread (cold
        rows dying at the horizon never come back from the LSM)."""
        sid = self._side_idx(side)
        if not self.blocks[sid]:
            return
        n = side.count
        needed: set[int] = set()
        if um is not None and um.any():
            needed = set(np.unique(side.row_bi[:n][um]).tolist())
        for bi in drop_bi.tolist():
            if int(bi) not in self.blocks[sid]:
                continue
            if int(bi) in needed:
                self._reload(sid, side, int(bi))
            else:
                meta = self.blocks[sid].pop(int(bi))
                self.spilled_bytes -= meta["bytes"]
                self.spilled_rows -= meta["rows"]
                self.ctrl.delete_block(self.node_id, meta["id"])
        self._write_manifest()

    def evict_remap(self, side, drop_set: np.ndarray, remap_bi) -> None:
        """After ``rebuild`` renumbered batch indices, renumber the
        touch stamps and block map the same way."""
        sid = self._side_idx(side)
        self.touch[sid] = [
            t for bi, t in enumerate(self.touch[sid]) if not drop_set[bi]
        ]
        self.est[sid] = [
            e for bi, e in enumerate(self.est[sid]) if not drop_set[bi]
        ]
        if self.blocks[sid]:
            self.blocks[sid] = {
                int(remap_bi[bi]): meta
                for bi, meta in self.blocks[sid].items()
            }

    # -- eviction ---------------------------------------------------------
    def maybe_spill(self) -> None:
        need = self.ctrl.over_budget()
        if need <= 0:
            self.ctrl.relax(self.node_id)
            return
        sides = self.op._sides
        # (stamp, max_ts, sid, bi) of every resident, spillable batch —
        # the NEWEST batch of each side stays resident, and any batch
        # holding hot sub-partition rows is DEPRIORITIZED: a hot block
        # is probed every batch by definition, so spilling its storage
        # would guarantee a reload-per-batch thrash loop.  Hot batches
        # remain a LAST RESORT (appended after every cold candidate)
        # rather than excluded outright — a celebrity key present in
        # every batch must not make the state budget unenforceable and
        # escalate to permanent backpressure; if the hot tail does get
        # spilled, the spill-thrashing verdict reports the ping-pong.
        cands = []
        hot_cands = []
        for sid, side in enumerate(sides):
            newest = len(side.batches) - 1
            hot_bis: set[int] = set()
            if side.hot.nslots:
                ra = side.hot.rows_all()
                if len(ra):
                    hot_bis = set(
                        np.unique(side.row_bi[ra]).tolist()
                    )
            for bi, b in enumerate(side.batches):
                if b is None or bi == newest or b.num_rows == 0:
                    continue
                target = hot_cands if bi in hot_bis else cands
                target.append(
                    (self.touch[sid][bi], side.batch_max_ts[bi], sid, bi)
                )
        cands.sort()
        hot_cands.sort()
        cands += hot_cands
        freed = 0
        spilled_any = False
        from denormalized_tpu.common.errors import StateError

        for _stamp, _mx, sid, bi in cands:
            if freed >= need:
                break
            try:
                self._spill(sid, sides[sid], bi)
            except StateError as e:
                # failed eviction put: the batch stays resident; degrade
                # to backpressure below rather than kill the query
                from denormalized_tpu.runtime.tracing import logger

                logger.warning(
                    "spill: join eviction put failed (%s) — batch stays "
                    "resident", e,
                )
                break
            freed += self.blocks[sid][bi]["est"]
            spilled_any = True
        if spilled_any:
            self._write_manifest()
            self.op._state_info_cache = None
        self.ctrl.check_pressure(self.node_id)

    def _spill(self, sid: int, side, bi: int) -> None:
        from denormalized_tpu.obs.statewatch import rb_nbytes
        from denormalized_tpu.state.tiering import rb_to_blob

        batch = side.batches[bi]
        blob = rb_to_blob(
            batch, extra_meta={"max_ts": int(side.batch_max_ts[bi])}
        )
        block_id = f"s{sid}b{self._next}"
        self._next += 1
        nbytes = self.ctrl.put_block(self.node_id, block_id, blob)
        self.blocks[sid][bi] = {
            "id": block_id,
            "bytes": nbytes,
            "rows": batch.num_rows,
            "est": rb_nbytes(batch),
        }
        side.batches[bi] = None
        self.spilled_bytes += nbytes
        self.spilled_rows += batch.num_rows
        self.ctrl.note_spill(self.node_id, 1, nbytes)

    def _write_manifest(self) -> None:
        self.ctrl.write_manifest(
            self.node_id,
            [m["id"] for s in self.blocks for m in s.values()],
        )

    def info(self) -> dict:
        return {
            "spilled_bytes": self.spilled_bytes,
            "spilled_keys": self.spilled_rows,
            "spilled_blocks": len(self.blocks[0]) + len(self.blocks[1]),
            "spill": self.ctrl.spill_stats(self.node_id),
        }

    # -- checkpoint integration -------------------------------------------
    def snapshot_refs(self, coord, key: str, epoch: int) -> list[dict]:
        refs = []
        for sid in (0, 1):
            for bi in sorted(self.blocks[sid]):
                meta = self.blocks[sid][bi]
                self.ctrl.copy_block_to_epoch(
                    coord, key, epoch, self.node_id, meta["id"]
                )
                refs.append({
                    "side": sid, "bi": bi, "id": meta["id"],
                    "bytes": meta["bytes"], "rows": meta["rows"],
                    "est": meta["est"],
                })
        return refs

    def restore_block(self, coord, key: str, ref: dict) -> None:
        """Epoch blob → spill namespace; tier map entry re-armed without
        materializing the rows."""
        raw = self.ctrl.restore_block_from_epoch(
            coord, key, self.node_id, ref["id"]
        )
        sid, bi = int(ref["side"]), int(ref["bi"])
        self.blocks[sid][bi] = {
            "id": ref["id"], "bytes": len(raw),
            "rows": int(ref["rows"]), "est": int(ref["est"]),
        }
        self.spilled_bytes += len(raw)
        self.spilled_rows += int(ref["rows"])
        seq = int(ref["id"].rsplit("b", 1)[1])
        self._next = max(self._next, seq + 1)

    def align_touch(self, sides) -> None:
        """After a restore rebuilt the batch lists, re-seed the touch
        stamps (everything equally cold; reload order then follows event
        time) and the per-batch byte estimates."""
        from denormalized_tpu.obs.statewatch import rb_nbytes

        for sid, side in enumerate(sides):
            self.touch[sid] = [0] * len(side.batches)
            self.est[sid] = [
                self.blocks[sid][bi]["est"] if b is None else rb_nbytes(b)
                for bi, b in enumerate(side.batches)
            ]


class StreamingJoinExec(ExecOperator):
    def __init__(
        self,
        left: ExecOperator,
        right: ExecOperator,
        kind: JoinKind,
        left_keys: list[str],
        right_keys: list[str],
        filter_expr: Expr | None,
        schema: Schema,
        *,
        retention_ms: int = 300_000,
        band=None,
        band_slack_ms: int | None = None,
        adaptive: bool = True,
        adapt_interval_s: float = 1.0,
    ) -> None:
        if len(left_keys) != len(right_keys) or not left_keys:
            raise PlanError("join requires equal non-empty key lists")
        self.left = left
        self.right = right
        self.kind = kind
        self.left_keys = left_keys
        self.right_keys = right_keys
        self.filter_expr = filter_expr
        self.schema = schema
        self.retention_ms = retention_ms
        # band (interval) predicate: left_expr - right_expr must land in
        # [lower_ms, upper_ms] for a pair to join (logical.plan.JoinBand)
        self.band = band
        # band-aware eviction slack (docs/joins.md): a retained row is
        # band-dead once its band reach lies more than slack below the
        # OTHER side's band watermark — slack absorbs band-space
        # lateness the same way allowed-lateness absorbs event-time
        # lateness.  None (the default) disables band-aware eviction
        # entirely (retention-only, the pre-band behavior).
        self._band_slack_ms = band_slack_ms
        if band is not None:
            if band.lower_ms is None and band.upper_ms is None:
                raise PlanError(
                    "join band needs at least one bound (both lower_ms "
                    "and upper_ms are None)"
                )
            for e, side_schema, label in (
                (band.left_expr, left.schema, "left"),
                (band.right_expr, right.schema, "right"),
            ):
                missing = e.columns_referenced() - set(side_schema.names)
                if missing:
                    raise PlanError(
                        f"join band {label} expression references "
                        f"{sorted(missing)} not present on the {label} "
                        "input"
                    )
        # equi-key dtype compatibility: the shared interner assigns ids per
        # column PATH (numeric dict vs native string), so joining a STRING
        # key against a numeric key would silently collide unrelated ids
        for lk, rk in zip(left_keys, right_keys):
            lf = left.schema.field(lk)
            rf = right.schema.field(rk)
            ok = lf.dtype is rf.dtype or (
                lf.dtype.is_numeric and rf.dtype.is_numeric
            )
            if not ok:
                raise PlanError(
                    f"join key dtype mismatch: {lk}: {lf.dtype} vs "
                    f"{rk}: {rf.dtype}"
                )
        self._metrics = {"rows_out": 0, "evicted": 0}
        from denormalized_tpu import obs
        from denormalized_tpu.obs import statewatch

        self.bind_obs("join")
        # state observatory: one heavy-hitter/cardinality sketch pair
        # PER SIDE — "which side is skewed" is the verdict that matters
        # for adaptive sub-partitioning, and the two sides share an
        # interner so gids are comparable but their distributions aren't
        # windowed sketches (decay_every): the adaptation policy folds a
        # hot-key sub-partition when the key's share decays — a monotone
        # sketch only lets shares fall as 1/total, so a celebrity that
        # retired early in a long run would stay "hot" forever; the
        # exponential window makes shares track recent traffic and the
        # fold trigger fire within a bounded row horizon
        self._sw = statewatch.make_watch(
            "join", decay_every=statewatch.JOIN_SKETCH_DECAY_ROWS
        )
        self._sw_right = statewatch.make_watch(
            "join", decay_every=statewatch.JOIN_SKETCH_DECAY_ROWS
        )
        self._sides = None  # run()'s live (_SideState, _SideState) pair
        # closed-loop skew adaptation (obs/doctor/actions.py): the policy
        # runs on the join's own thread between batches.  It needs live
        # sketches — with metrics disabled make_watch hands out the null
        # watch, so the adaptive path owns real ones instead (their
        # update is the same microseconds-per-batch the obs overhead
        # gate already covers).
        self._policy = None
        # policy-owned sketches sample every 4th batch: with metrics off
        # the pre-adaptive operator fed no sketch at all, and the policy
        # decides at second granularity — a 1/4 row sample keeps shares
        # unbiased while cutting the only cold-path cost adaptation adds
        self._sw_sample = 0
        self._sw_batches = [0, 0]
        if adaptive:
            from denormalized_tpu.obs.doctor.actions import (
                JoinAdaptationPolicy,
            )

            self._policy = JoinAdaptationPolicy(
                interval_s=adapt_interval_s
            )
            if not self._sw:
                self._sw = statewatch.StateWatch(
                    "join",
                    decay_every=statewatch.JOIN_SKETCH_DECAY_ROWS,
                )
                self._sw_right = statewatch.StateWatch(
                    "join",
                    decay_every=statewatch.JOIN_SKETCH_DECAY_ROWS,
                )
                self._sw_sample = 4
        self._obs_rows_out = obs.counter("dnz_op_rows_out_total", op="join")
        # shared-group cost attribution (runtime/multi_query.py): when a
        # join feeds a shared slice pipeline, the doctor apportions the
        # join's MEASURED build/probe/gather time across subscribers by
        # kept-rows share instead of 1/N.  Off by default — the timers
        # cost two perf_counter calls per batch, so the single-query
        # path never pays them.
        self._shared_attr = False
        self._stage_ms = {"build": 0.0, "probe": 0.0, "gather": 0.0}
        self._obs_mq_stage = {
            s: obs.histogram("dnz_mq_join_stage_ms", stage=s)
            for s in ("build", "probe", "gather")
        }
        self._obs_mq_fanout = obs.counter("dnz_mq_join_fanout_rows_total")
        # adaptation counters pre-bound per (action, side) so the policy
        # event path allocates nothing (obs handle convention)
        self._obs_adapt = {
            (a, s): obs.counter(
                "dnz_join_adaptations_total", action=a, side=s
            )
            for a in ("adapt", "fold")
            for s in ("left", "right")
        }
        # re-keying threshold (tests lower it to force the path)
        self._reintern_min = 262_144
        # checkpointing (None = disabled): set by enable_checkpointing
        self._ckpt: tuple | None = None
        # cold tier (state/tiering.py): set by enable_spill
        self._tier: _JoinTier | None = None
        # ONE interner for the join: both sides' keys map to the same dense
        # ids (strings take the native PyObject fast path)
        self._interner = GroupInterner(len(left_keys))
        # output column plan: all left fields, then right fields minus
        # canonical-ts and shared equi-keys (mirrors lp.Join schema logic)
        left_names = set(left.schema.names)
        self._right_out = [
            f.name
            for f in right.schema
            if f.name != CANONICAL_TIMESTAMP_COLUMN and f.name not in left_names
        ]
        # existence joins (LeftSemi/LeftAnti, datastream.rs:129) output
        # LEFT rows only — self.schema is the left schema — but the join
        # FILTER still evaluates over matched pairs, so pair assembly uses
        # this schema (== self.schema for every other kind)
        self._existence = kind in (JoinKind.LEFT_SEMI, JoinKind.LEFT_ANTI)
        if self._existence:
            self._pair_schema = Schema(
                list(left.schema.fields)
                + [right.schema.field(n) for n in self._right_out]
            )
        else:
            self._pair_schema = schema

    @property
    def children(self):
        return [self.left, self.right]

    def metrics(self):
        m = dict(self._metrics)
        sides = self._sides
        if sides is not None:
            m["hot_keys"] = sum(int(s.hot.nslots) for s in sides)
        if self._policy is not None:
            m["adaptations"] = self._policy.adaptations_total
        if self._shared_attr:
            m["shared_cost_ms"] = self.shared_cost_ms()
        return m

    # -- shared-group cost attribution (runtime/multi_query.py) ---------
    def enable_shared_attribution(self) -> None:
        """Turn on the build/probe/gather stage timers so a shared
        pipeline's doctor ledger can apportion the join's measured cost
        across subscribers (slice_exec.shared_fractions)."""
        self._shared_attr = True

    def shared_cost_ms(self) -> float:
        """Total measured join time (build + probe + gather, ms) since
        start — the upstream cost the shared slice operator folds into
        its per-subscriber attribution."""
        return float(sum(self._stage_ms.values()))

    def _label(self):
        on = ", ".join(f"{l}={r}" for l, r in zip(self.left_keys, self.right_keys))
        return f"StreamingJoinExec({self.kind.value} on {on})"

    # -- cold tier (state/tiering.py) -----------------------------------
    def enable_spill(self, node_id: str, controller) -> None:
        self._tier = _JoinTier(self, node_id, controller)

    # -- state observatory (obs/statewatch.py) --------------------------
    def _side_state_info(self, side: "_SideState") -> dict:
        from denormalized_tpu.obs import statewatch as swm

        n = side.count
        per_row = int(
            side.link.itemsize + side.row_bi.itemsize
            + side.row_ri.itemsize + side.row_gid.itemsize + 1  # matched
        )
        if side.row_band is not None:
            per_row += int(side.row_band.itemsize)
        # spilled batches sit as None placeholders: their rows cost the
        # LSM, not RAM — resident accounting skips them
        batch_bytes = sum(
            swm.rb_nbytes(b) for b in side.batches if b is not None
        )
        # hot sub-partitions: counted SEPARATELY (hot_bytes) so the
        # spill controller's coldest-first ordering can see — and never
        # evict — an actively-probed hot block.  Restore-invariant:
        # live hot row ids + each hot row's proportional share of its
        # batch's bytes (batch membership survives restore exactly).
        hot_keys = int(side.hot.nslots)
        hot_rows = side.hot.rows_total()
        hot_bytes = side.hot.nbytes() + hot_rows * per_row
        if hot_rows:
            try:
                # cross-thread read racing an adaptation/relocation on
                # the join thread: row ids may be stale — clip them and
                # degrade to approximate batch attribution, never raise
                ra = np.clip(side.hot.rows_all(), 0, max(n - 1, 0))
                cnt = np.bincount(
                    side.row_bi[ra], minlength=len(side.batches)
                )
                for bi in np.nonzero(cnt)[0]:
                    if bi >= len(side.batches):
                        break
                    b = side.batches[int(bi)]
                    if b is not None and b.num_rows:
                        hot_bytes += int(
                            swm.rb_nbytes(b) * (int(cnt[bi]) / b.num_rows)
                        )
            except Exception:  # dnzlint: allow(broad-except) accounting reads race the join thread by design (single-writer, lock-free) — a torn hot layout degrades to the index-bytes floor, never raises into /state or a gauge export
                pass
        live_k = int(np.count_nonzero(side.head >= 0)) + hot_keys
        oldest = min(side.batch_max_ts) if side.batch_max_ts else None
        return {
            "rows": n,
            "batches": len(side.batches),
            "state_bytes": (
                batch_bytes + n * per_row + live_k * swm.KEY_EST_BYTES
                + side.hot.nbytes()
            ),
            "live_keys": live_k,
            "hot_keys": hot_keys,
            "hot_rows": hot_rows,
            "hot_bytes": hot_bytes,
            "oldest_event_ms": oldest,
            "watermark_ms": side.watermark,
        }

    def state_info(self) -> dict:
        sides = self._sides
        if sides is None:
            return {
                "op": "join", "state_bytes": 0, "live_keys": 0,
                "slot_capacity": 0, "slot_live": 0,
                "retention_unit_ms": self.retention_ms,
            }
        L = self._side_state_info(sides[0])
        R = self._side_state_info(sides[1])
        wms = [s["watermark_ms"] for s in (L, R) if s["watermark_ms"] is not None]
        olds = [s["oldest_event_ms"] for s in (L, R) if s["oldest_event_ms"] is not None]
        info = {
            "op": "join",
            "state_bytes": L["state_bytes"] + R["state_bytes"],
            "live_keys": L["live_keys"] + R["live_keys"],
            "hot_bytes": L["hot_bytes"] + R["hot_bytes"],
            "hot_keys": L["hot_keys"] + R["hot_keys"],
            "interner_keys_total": len(self._interner),
            "slot_capacity": int(len(sides[0].link) + len(sides[1].link)),
            "slot_live": L["rows"] + R["rows"],
            "retention_unit_ms": self.retention_ms,
            "sides": {"left": L, "right": R},
        }
        if self._policy is not None:
            info["adaptations"] = {
                "total": self._policy.adaptations_total,
                "recent": list(self._policy.events)[-8:],
            }
        if wms and olds:
            info["watermark_ms"] = min(wms)
            info["oldest_event_ms"] = min(olds)
            info["oldest_event_lag_ms"] = max(
                0, int(min(wms)) - int(min(olds))
            )
        if self._tier is not None:
            info.update(self._tier.info())
        return info

    def _state_watch_views(self):
        if not self._sw:
            return []
        from denormalized_tpu.ops.interner import display_keys

        resolve = lambda g: display_keys(self._interner, g)  # noqa: E731
        return [
            ("left", self._sw, resolve),
            ("right", self._sw_right, resolve),
        ]

    # ------------------------------------------------------------------
    def _gids_of(self, batch: RecordBatch, names: list[str]) -> np.ndarray:
        return self._interner.intern([batch.column(n) for n in names])

    def _band_vals(self, batch: RecordBatch, is_left: bool) -> np.ndarray:
        """One side's band-expression values for a batch, as float64
        with NaN where the expression reads a null (NaN compares False
        against both bounds, so null band values match nothing)."""
        from denormalized_tpu.common.columns import as_numpy
        from denormalized_tpu.logical.expr import column_validity

        e = self.band.left_expr if is_left else self.band.right_expr
        v = np.asarray(as_numpy(e.eval(batch)), dtype=np.float64)
        m = column_validity(e, batch)
        if m is not None and not m.all():
            v = v.copy()
            v[~np.asarray(m, dtype=bool)] = np.nan
        return v

    def _band_keep(
        self,
        probe_band: np.ndarray,
        p_idx: np.ndarray,
        build: _SideState,
        b_rows: np.ndarray,
        probe_is_left: bool,
    ) -> np.ndarray:
        """Vectorized band filter over equi-probe pairs — pure index
        arithmetic on the cached per-row band values, BEFORE any row
        gather materializes candidates."""
        pv = probe_band[p_idx]
        bv = build.row_band[b_rows]
        diff = pv - bv if probe_is_left else bv - pv
        lo = self.band.lower_ms
        hi = self.band.upper_ms
        if lo is not None and hi is not None:
            return (diff >= lo) & (diff <= hi)
        if lo is not None:
            return diff >= lo
        return diff <= hi

    def _probe(
        self,
        probe_batch: RecordBatch,
        probe_gids: np.ndarray,
        build: _SideState,
        probe_is_left: bool,
        probe_base: int,
        probe_side: _SideState,
        probe_band: np.ndarray | None = None,
    ) -> RecordBatch | None:
        """Join a new batch against the opposite side's table.  Rows are
        marked 'matched' (for outer-join bookkeeping) only AFTER the band
        and the join filter accept the pair — an equi-hit rejected by
        either must still surface as unmatched in an outer join.
        ``probe_base`` is the probe side's row count BEFORE this batch
        inserts (its rows' global ids)."""
        p_idx, b_rows = build.probe(probe_gids)
        if len(p_idx) == 0:
            return None
        if self.band is not None:
            kb = self._band_keep(
                probe_band, p_idx, build, b_rows, probe_is_left
            )
            if not kb.all():
                p_idx = p_idx[kb]
                b_rows = b_rows[kb]
            if len(p_idx) == 0:
                return None
        if self._existence and self.filter_expr is None:
            # no pair materializes downstream and no filter reads one:
            # the index arrays alone decide existence
            return self._existence_probe(
                probe_batch, p_idx, b_rows,
                np.ones(len(p_idx), dtype=bool), probe_is_left,
                probe_base, probe_side, build,
            )
        tg = time.perf_counter() if self._shared_attr else 0.0
        if self._tier is not None:
            # membership pre-probe: any spilled batch a hit landed in
            # reloads before gather (no spilled blocks = attribute check)
            self._tier.ensure_rows_resident(build, b_rows)
        p_take = probe_batch.take(p_idx)
        b_take = build.gather(b_rows)
        probe_cols = {n: p_take.column(n) for n in p_take.schema.names}
        probe_masks = {n: p_take.mask(n) for n in p_take.schema.names}
        build_cols = {n: b_take.column(n) for n in b_take.schema.names}
        build_masks = {n: b_take.mask(n) for n in b_take.schema.names}
        if probe_is_left:
            left_cols, left_masks = probe_cols, probe_masks
            right_cols, right_masks = build_cols, build_masks
        else:
            left_cols, left_masks = build_cols, build_masks
            right_cols, right_masks = probe_cols, probe_masks
        cols = [left_cols[n] for n in self.left.schema.names]
        masks = [left_masks.get(n) for n in self.left.schema.names]
        cols += [right_cols[n] for n in self._right_out]
        masks += [right_masks.get(n) for n in self._right_out]
        out = RecordBatch(self._pair_schema, cols, masks)
        keep = np.ones(out.num_rows, dtype=bool)
        if self.filter_expr is not None:
            keep = np.asarray(self.filter_expr.eval(out), dtype=bool)
        if self._existence:
            res = self._existence_probe(
                probe_batch, p_idx, b_rows, keep, probe_is_left,
                probe_base, probe_side, build,
            )
            if self._shared_attr:
                self._stage_ms["gather"] += (time.perf_counter() - tg) * 1e3
            return res
        if not keep.all():
            out = out.filter(keep)
        # mark matched pairs that survived the filter (vectorized)
        probe_side.matched[probe_base + p_idx[keep]] = True
        build.matched[b_rows[keep]] = True
        if self._shared_attr:
            self._stage_ms["gather"] += (time.perf_counter() - tg) * 1e3
        return out if out.num_rows else None

    def _existence_probe(
        self, probe_batch, p_idx, b_rows, keep, probe_is_left,
        probe_base, probe_side, build,
    ) -> RecordBatch | None:
        """Semi/anti probe: no pair materializes downstream — only the
        LEFT side's matched flags matter.  Semi emits each left row at
        most once: on arrival when it matches retained right rows, or on
        the matched-flag's False→True transition when a later right batch
        probes it.  Anti emits nothing here (unmatched left rows surface
        at eviction/EOS via _emits_unmatched)."""
        pk = p_idx[keep]
        bk = b_rows[keep]
        if probe_is_left:
            # this batch's left rows are new: any filtered match emits now
            probe_side.matched[probe_base + pk] = True
            build.matched[bk] = True
            if self.kind is JoinKind.LEFT_SEMI and len(pk):
                return probe_batch.take(np.unique(pk))
            return None
        # probe is the right side: matching LEFT rows live in `build`
        pre = build.matched[bk].copy()
        build.matched[bk] = True
        probe_side.matched[probe_base + pk] = True
        if self.kind is JoinKind.LEFT_SEMI:
            newly = np.unique(bk[~pre])
            if len(newly):
                if self._tier is not None:
                    self._tier.ensure_rows_resident(build, newly)
                return build.gather(newly)
        return None

    # ------------------------------------------------------------------
    def _evict(
        self,
        side: _SideState,
        is_left: bool,
        horizon: int,
        band_horizon: float | None = None,
    ):
        """Drop batches wholly older than the horizon — or, for interval
        joins, wholly below the band horizon (every retained row's band
        value so far behind the other side's band watermark that no
        future row can land in band) — emit unmatched rows for outer
        joins; rebuild the chained arrays over retained rows.  Batch
        ages come from the cached per-batch max timestamps / band
        maxima — no rescans of retained data on the hot path."""
        if not side.batches:
            return []
        drop_set = band_evict_mask(
            np.asarray(side.batch_max_ts, dtype=np.int64),
            horizon,
            np.asarray(side.batch_band_max, dtype=np.float64)
            if band_horizon is not None and side.batch_band_max else None,
            band_horizon,
        )
        if not drop_set.any():
            return []
        drop_bi = np.nonzero(drop_set)[0]
        n = side.count
        row_dropped = drop_set[side.row_bi[:n]]
        unmatched: list[RecordBatch] = []
        um_rows = (
            row_dropped & ~side.matched[:n]
            if self._emits_unmatched(is_left)
            else None
        )
        if self._tier is not None:
            # dropped spilled batches owing unmatched emissions reload;
            # the rest die in the LSM without ever being read back
            self._tier.evict_prepare(side, is_left, drop_bi, um_rows)
        if self._emits_unmatched(is_left):
            um = um_rows
            for bi in drop_bi:
                sel = um & (side.row_bi[:n] == bi)
                if sel.any():
                    unmatched.append(
                        side.batches[bi].take(
                            side.row_ri[:n][sel].astype(np.int64)
                        )
                    )
        self._metrics["evicted"] += int(row_dropped.sum())

        keep_rows = ~row_dropped
        remap_bi = np.cumsum(~drop_set) - 1  # old bi -> new bi
        hot_gids = side.hot.gids() if side.hot.nslots else None
        side.rebuild(
            [b for bi, b in enumerate(side.batches) if not drop_set[bi]],
            [
                mx
                for bi, mx in enumerate(side.batch_max_ts)
                if not drop_set[bi]
            ],
            side.row_gid[:n][keep_rows].copy(),
            remap_bi[side.row_bi[:n][keep_rows]].astype(np.int32),
            side.row_ri[:n][keep_rows].copy(),
            side.matched[:n][keep_rows].copy(),
            band=(
                side.row_band[:n][keep_rows].copy()
                if side.row_band is not None else None
            ),
        )
        if hot_gids is not None:
            # eviction renumbered rows but not gids: re-adopt each hot
            # key's (possibly now empty) block so it stays hot
            side.rehot(hot_gids)
        if self._tier is not None:
            self._tier.evict_remap(side, drop_set, remap_bi)
        return unmatched

    def _evict_horizon(self, sides) -> "Iterator[RecordBatch]":
        """Evict both sides against the joint watermark horizon (emitting
        null-padded unmatched rows for outer joins) — shared by the
        per-batch path and idle-source WatermarkHint handling."""
        if sides[0].watermark is None or sides[1].watermark is None:
            return
        horizon = (
            min(sides[0].watermark, sides[1].watermark) - self.retention_ms
        )
        # band-aware horizons (docs/joins.md): a pair joins iff
        # left_band - right_band ∈ [lower_ms, upper_ms], so a LEFT row
        # with band value L only ever matches right rows with
        # R ≥ L - upper … R ≤ L - lower.  Future right rows carry band
        # values ≥ right.band_wm - slack, so L is dead once
        # L < right.band_wm + lower_ms - slack (needs lower_ms set —
        # without it, arbitrarily large future R still lands in band);
        # symmetrically a RIGHT row R is dead once
        # R < left.band_wm - upper_ms - slack (needs upper_ms set).
        band_h: list[float | None] = [None, None]
        if self.band is not None and self._band_slack_ms is not None:
            slack = self._band_slack_ms
            if self.band.lower_ms is not None and sides[1].band_wm is not None:
                band_h[0] = sides[1].band_wm + self.band.lower_ms - slack
            if self.band.upper_ms is not None and sides[0].band_wm is not None:
                band_h[1] = sides[0].band_wm - self.band.upper_ms - slack
        for (s, l), bh in zip(
            ((sides[0], True), (sides[1], False)), band_h
        ):
            for ub in self._evict(s, l, horizon, band_horizon=bh):
                padded = self._null_padded(ub, l)
                self._metrics["rows_out"] += padded.num_rows
                yield padded
        # interner growth is keyed by DISTINCT keys ever seen; once it
        # dwarfs the retained rows (UUID-style keys), re-key from scratch
        # so memory stays bounded by retention, not stream lifetime
        retained = sides[0].count + sides[1].count
        if len(self._interner) > max(self._reintern_min, 4 * retained):
            if self._tier is not None and self._tier.any_spilled:
                # re-interning reads every retained batch's key columns —
                # reloading the whole cold tier for it would defeat the
                # spill; defer until the cold set drains (eviction keeps
                # the interner bounded by retention regardless)
                return
            self._reintern(sides)

    def _reintern(self, sides) -> None:
        """Re-key the join when the interner has accumulated far more
        distinct keys than rows remain retained (high-cardinality streams:
        evicted rows free their storage, but interner entries and head
        slots have no per-key eviction path).  Builds a FRESH interner from
        the retained batches and re-chains both sides — amortized O(rows
        retained)."""
        self._interner = GroupInterner(len(self.left_keys))
        # the gid space just reset: old sketch entries name dead ids —
        # restart and re-warm (documented in docs/observability.md)
        self._sw.reset_sketches()
        self._sw_right.reset_sketches()
        for side_id, side in enumerate(sides):
            names = self.left_keys if side_id == 0 else self.right_keys
            n = side.count
            # hot blocks survive a re-intern via representative rows:
            # row ids are stable here (same batches, same order), only
            # gid VALUES change — a rep row's new gid names its key.
            # Empty blocks have no rep and lose hot status (the policy
            # re-adapts them if they warm again).
            hot_reps = side.hot.reps() if side.hot.nslots else None
            if side.batches:
                gids = np.concatenate(
                    [self._gids_of(b, names) for b in side.batches]
                ).astype(np.int32)
            else:
                gids = np.empty(0, dtype=np.int32)
            # rows are stored in (batch, row) insert order, so the
            # concatenated re-interned gids line up with the row arrays
            side.head = np.full(1024, -1, dtype=np.int64)
            side.rebuild(
                side.batches,
                side.batch_max_ts,
                gids,
                side.row_bi[:n].copy(),
                side.row_ri[:n].copy(),
                side.matched[:n].copy(),
                band=(
                    side.row_band[:n].copy()
                    if side.row_band is not None else None
                ),
            )
            if hot_reps:
                side.rehot(np.unique(gids[np.asarray(hot_reps)]))

    def _emits_unmatched(self, is_left: bool) -> bool:
        if self.kind is JoinKind.FULL:
            return True
        if self.kind is JoinKind.LEFT_ANTI:
            # anti = left rows proven matchless: emitted when the eviction
            # horizon passes them still unmatched (or at EOS).  Output is
            # left-schema rows, so _null_padded is a pass-through.
            return is_left
        if self.kind is JoinKind.LEFT_SEMI:
            return False
        return (self.kind is JoinKind.LEFT) == is_left and self.kind in (
            JoinKind.LEFT,
            JoinKind.RIGHT,
        )

    def _null_padded(self, batch: RecordBatch, is_left: bool) -> RecordBatch:
        """Pad the missing side with nulls for outer-join unmatched rows."""
        n = batch.num_rows
        cols, masks = [], []
        for f in self.schema:
            srcs = batch.schema
            if srcs.has(f.name):
                cols.append(batch.column(f.name))
                masks.append(batch.mask(f.name))
            else:
                cols.append(np.zeros(n, dtype=f.dtype.to_numpy()))
                masks.append(np.zeros(n, dtype=bool))
        return RecordBatch(self.schema, cols, masks)

    # -- checkpointing ---------------------------------------------------
    # Snapshot = both sides' retained rows (+matched flags, watermarks) at
    # an ALIGNED marker; keys/gids/chains are re-derived on restore by
    # re-interning, so the interner itself is never serialized.  The
    # reference checkpoints only sources and windows; with a join config
    # in BASELINE.json, a kill during the join bench would otherwise
    # reprocess arbitrary amounts of stream (round-3 VERDICT item 9).
    def enable_checkpointing(self, node_id: str, coord, orch) -> None:
        self._ckpt = (coord, f"join_{node_id}")

    def _snapshot(self, epoch: int, sides) -> None:
        from denormalized_tpu.state.serialization import pack_snapshot

        coord, key = self._ckpt
        spilled = self._tier is not None and self._tier.any_spilled
        meta: dict = {"epoch": epoch, "sides": []}
        arrays: dict[str, np.ndarray] = {}
        if spilled:
            # v2 (cold tier active): spilled blocks are referenced from
            # this snapshot and their payloads committed under the SAME
            # epoch; per-row gids + the shared interner ride along so
            # restore never materializes cold rows to re-intern them
            meta["interner"] = self._interner.snapshot()
            meta["spill"] = {
                "blocks": self._tier.snapshot_refs(coord, key, epoch)
            }
        for sid, (side, schema) in enumerate(
            zip(sides, (self.left.schema, self.right.schema))
        ):
            n = side.count
            resident = [b for b in side.batches if b is not None]
            rows = RecordBatch.concat(resident) if resident else None
            side_meta = {
                "watermark": side.watermark,
                "count": n,
                "strings": {},
                "masked": [],
            }
            if side.band_wm is not None:
                # band watermark rides the snapshot so band-aware
                # eviction resumes exactly; batch band maxima rebuild
                # from the persisted per-row band values
                side_meta["band_wm"] = side.band_wm
            if rows is not None:
                # insert order == row-array order (v2: resident rows only)
                assert spilled or rows.num_rows == n
                self._pack_side_cols(sid, rows, schema, side_meta, arrays)
            if n and (rows is not None or spilled):
                arrays[f"s{sid}_matched"] = side.matched[:n].copy()
                # per-batch boundaries: restore must keep the original
                # batch granularity or whole-batch max-ts eviction would
                # retain (and match) rows far past retention_ms
                arrays[f"s{sid}_row_bi"] = side.row_bi[:n].copy()
                arrays[f"s{sid}_batch_max_ts"] = np.asarray(
                    side.batch_max_ts, dtype=np.int64
                )
                if spilled:
                    arrays[f"s{sid}_row_gid"] = side.row_gid[:n].copy()
                if side.row_band is not None:
                    arrays[f"s{sid}_band"] = side.row_band[:n].copy()
            if side.hot.nslots:
                # hot sub-partitions ride the snapshot as one
                # representative row index per non-empty block: with the
                # ascending-row-id invariant the whole block rebuilds
                # from the rep's gid after restore (epoch-consistent —
                # this runs at the aligned marker on the join thread,
                # never racing an adaptation)
                side_meta["hot_reps"] = side.hot.reps()
            meta["sides"].append(side_meta)
        coord.put_snapshot(key, epoch, pack_snapshot(meta, arrays))

    def _restore(self, sides) -> None:
        from denormalized_tpu.state.serialization import unpack_snapshot

        coord, key = self._ckpt
        blob = coord.get_snapshot(key)
        if blob is None:
            return
        meta, arrays = unpack_snapshot(blob)
        if meta.get("spill") is not None:
            self._restore_v2(coord, key, meta, arrays, sides)
            return
        self._restore_v1(meta, arrays, sides)
        if self._tier is not None:
            # a v1 (nothing-spilled-at-the-cut) snapshot restored into a
            # budgeted run: the tier's per-batch touch/est lists must
            # cover the rebuilt batch lists or the first budget check
            # indexes past them
            self._tier.align_touch(sides)

    @staticmethod
    def _pack_side_cols(sid, rows, schema, side_meta, arrays) -> None:
        """Pack one side's retained-row columns into the snapshot:
        columnar string/nested columns store their RAW buffers (the same
        codec spill blocks use — no Python value round-trip), plain
        object columns keep the legacy JSON ``strings`` lane."""
        from denormalized_tpu.common.columns import Column, column_to_arrays

        for f in schema:
            col = rows.column(f.name)
            if isinstance(col, Column):
                side_meta.setdefault("columnar", {})[f.name] = (
                    column_to_arrays(col, f"s{sid}_cc_{f.name}_", arrays)
                )
            else:
                colv = np.asarray(col)
                if colv.dtype == object:
                    side_meta["strings"][f.name] = [
                        None if v is None else str(v) for v in colv
                    ]
                else:
                    arrays[f"s{sid}_col_{f.name}"] = colv
            mask = rows.mask(f.name)
            # columnar columns already pack their validity — skip the
            # identical batch mask (unpack rebuilds it from the column)
            if mask is not None and mask is not getattr(
                col, "validity", None
            ):
                side_meta["masked"].append(f.name)
                arrays[f"s{sid}_mask_{f.name}"] = np.asarray(
                    mask, dtype=bool
                )

    @staticmethod
    def _unpack_side_cols(sid, schema, side_meta, arrays) -> RecordBatch:
        """Inverse of :meth:`_pack_side_cols` (legacy snapshots — no
        ``columnar`` entry — load unchanged)."""
        from denormalized_tpu.common.columns import column_from_arrays

        colspecs = side_meta.get("columnar", {})
        cols, masks = [], []
        for f in schema:
            if f.name in colspecs:
                cols.append(
                    column_from_arrays(
                        colspecs[f.name], f"s{sid}_cc_{f.name}_", arrays
                    )
                )
            elif f.name in side_meta["strings"]:
                vals = side_meta["strings"][f.name]
                arr = np.empty(len(vals), dtype=object)
                arr[:] = vals
                cols.append(arr)
            else:
                cols.append(arrays[f"s{sid}_col_{f.name}"])
            if f.name in side_meta["masked"]:
                masks.append(arrays.get(f"s{sid}_mask_{f.name}"))
            else:
                masks.append(getattr(cols[-1], "validity", None))
        return RecordBatch(schema, cols, masks)

    def _restore_v1(self, meta, arrays, sides) -> None:
        for sid, (side, schema, names) in enumerate(
            zip(
                sides,
                (self.left.schema, self.right.schema),
                (self.left_keys, self.right_keys),
            )
        ):
            side_meta = meta["sides"][sid]
            side.watermark = side_meta["watermark"]
            # legacy snapshot → band_wm stays None: band-aware eviction
            # holds off until new batches re-establish the watermark
            side.band_wm = side_meta.get("band_wm")
            n = int(side_meta["count"])
            if n == 0:
                continue
            merged = self._unpack_side_cols(sid, schema, side_meta, arrays)
            gids = self._gids_of(merged, names).astype(np.int32)
            # split back into the ORIGINAL batches (rows are stored in
            # (batch, row) insert order, so each bi is one contiguous run)
            bis = arrays[f"s{sid}_row_bi"].astype(np.int32)
            batch_max_ts = [
                int(x) for x in arrays[f"s{sid}_batch_max_ts"]
            ]
            bounds = np.nonzero(
                np.concatenate(([True], bis[1:] != bis[:-1]))
            )[0]
            ends = np.append(bounds[1:], n)
            batches = [
                merged.take(np.arange(b0, b1, dtype=np.int64))
                for b0, b1 in zip(bounds, ends)
            ]
            ris = np.concatenate(
                [np.arange(b1 - b0, dtype=np.int32)
                 for b0, b1 in zip(bounds, ends)]
            )
            # bis values may be sparse (post-eviction remaps keep them
            # dense, but be robust): renumber to positions in `batches`
            new_bi = np.cumsum(
                np.concatenate(([True], bis[1:] != bis[:-1]))
            ) - 1
            band = None
            if self.band is not None:
                band = arrays.get(f"s{sid}_band")
                if band is None:
                    # snapshot predates the band predicate (plan gained
                    # one since the cut): re-derive from the resident
                    # rows — expression eval is deterministic
                    band = np.concatenate(
                        [self._band_vals(b, sid == 0) for b in batches]
                    ) if batches else np.empty(0, dtype=np.float64)
            side.rebuild(
                batches,
                [batch_max_ts[int(bis[b0])] for b0 in bounds],
                gids,
                new_bi.astype(np.int32),
                ris,
                arrays[f"s{sid}_matched"].astype(bool),
                band=band,
            )
            reps = side_meta.get("hot_reps") or []
            if reps:
                side.rehot(
                    np.unique(gids[np.asarray(reps, dtype=np.int64)])
                )

    def _restore_v2(self, coord, key, meta, arrays, sides) -> None:
        """Restore a cold-tier snapshot: the interner and per-row gids
        come from the blob (no re-intern), resident batches rebuild from
        the resident-row concat, and spilled batches re-arm as tier-map
        placeholders — their payloads stream epoch → spill namespace one
        at a time.  Without a tier (budget removed since the checkpoint)
        spilled batches materialize resident instead."""
        from denormalized_tpu.state.tiering import rb_from_blob

        self._interner = GroupInterner.restore(meta["interner"])
        refs = meta["spill"]["blocks"]
        by_side: list[dict[int, dict]] = [{}, {}]
        for ref in refs:
            by_side[int(ref["side"])][int(ref["bi"])] = ref
        for sid, (side, schema) in enumerate(
            zip(sides, (self.left.schema, self.right.schema))
        ):
            side_meta = meta["sides"][sid]
            side.watermark = side_meta["watermark"]
            side.band_wm = side_meta.get("band_wm")
            n = int(side_meta["count"])
            if n == 0:
                continue
            bis = arrays[f"s{sid}_row_bi"].astype(np.int32)
            batch_max_ts = [
                int(x) for x in arrays[f"s{sid}_batch_max_ts"]
            ]
            gids = arrays[f"s{sid}_row_gid"].astype(np.int32)
            # resident-row concat (absent when every batch spilled)
            resident_rows = n - sum(
                int(r["rows"]) for r in by_side[sid].values()
            )
            merged = None
            if resident_rows > 0:
                merged = self._unpack_side_cols(
                    sid, schema, side_meta, arrays
                )
            bounds = np.nonzero(
                np.concatenate(([True], bis[1:] != bis[:-1]))
            )[0]
            ends = np.append(bounds[1:], n)
            batches: list[RecordBatch | None] = []
            cursor = 0
            for new_bi, (b0, b1) in enumerate(zip(bounds, ends)):
                orig_bi = int(bis[b0])
                ref = by_side[sid].get(orig_bi)
                if ref is not None:
                    if self._tier is not None:
                        self._tier.restore_block(
                            coord, key, {**ref, "bi": new_bi}
                        )
                        batches.append(None)
                    else:
                        raw = coord.get_snapshot(
                            f"{key}:spill:{ref['id']}"
                        )
                        if raw is None:
                            from denormalized_tpu.common.errors import (
                                StateError,
                            )

                            raise StateError(
                                "checkpoint references spilled join "
                                f"block {ref['id']!r} but the epoch "
                                "holds no such snapshot"
                            )
                        rb, _extra = rb_from_blob(raw, schema)
                        batches.append(rb)
                else:
                    ln = int(b1 - b0)
                    batches.append(
                        merged.take(
                            np.arange(cursor, cursor + ln, dtype=np.int64)
                        )
                    )
                    cursor += ln
            ris = np.concatenate(
                [np.arange(b1 - b0, dtype=np.int32)
                 for b0, b1 in zip(bounds, ends)]
            )
            run_bi = np.cumsum(
                np.concatenate(([True], bis[1:] != bis[:-1]))
            ) - 1
            band = None
            if self.band is not None:
                band = arrays.get(f"s{sid}_band")
                if band is None:
                    from denormalized_tpu.common.errors import StateError

                    raise StateError(
                        "banded join restoring a cold-tier snapshot "
                        "without band values — the snapshot predates "
                        "the band predicate and spilled rows cannot be "
                        "re-evaluated"
                    )
            side.rebuild(
                batches,
                [batch_max_ts[int(bis[b0])] for b0 in bounds],
                gids,
                run_bi.astype(np.int32),
                ris,
                arrays[f"s{sid}_matched"].astype(bool),
                band=band,
            )
            reps = side_meta.get("hot_reps") or []
            if reps:
                side.rehot(
                    np.unique(gids[np.asarray(reps, dtype=np.int64)])
                )
        if self._tier is not None:
            self._tier.align_touch(sides)
            self._tier._write_manifest()

    # ------------------------------------------------------------------
    def run(self) -> Iterator[StreamItem]:
        from denormalized_tpu.runtime.pump import spawn_pump

        with_band = self.band is not None
        sides = (_SideState(with_band), _SideState(with_band))
        self._sides = sides  # state observatory reads these pull-style
        if self._ckpt is not None:
            self._restore(sides)
        q: queue_mod.Queue = queue_mod.Queue(maxsize=8)
        done = threading.Event()
        for side_id, op in ((0, self.left), (1, self.right)):
            spawn_pump(
                q,
                done,
                op.run,
                sentinel=(side_id, EOS),
                wrap=lambda item, s=side_id: (s, item),
            )
        markers_seen: dict[int, int] = {}
        # TRUE barrier alignment: once one side delivers epoch E's marker,
        # that side's further items are buffered (not folded into state)
        # until the other side's E-marker arrives — otherwise the snapshot
        # taken at alignment would contain the early side's post-marker
        # rows, and the source replay after a restore would re-insert them
        # (duplicated join state, not merely duplicated emission).  Blocking
        # only ever engages when markers flow, i.e. with checkpointing on.
        blocked = [False, False]
        pending: deque[tuple[int, StreamItem]] = deque()
        # downstream event-time contract: joined rows can be as old as
        # the eviction horizon (a retained row matches a fresh probe),
        # so a downstream window advancing on raw batch mins would
        # late-drop legitimate pairs.  When the sources themselves hint
        # (partition mode) the hint-forwarding branch below covers it;
        # for batch-min-driven sides the join ANNOUNCES hint mode before
        # its first output and emits the joint low watermark
        # (min(watermarks) − retention) whenever it advances.
        wm_announced = False
        wm_emitted: int | None = None
        try:
            while not (sides[0].done and sides[1].done):
                if pending and not (blocked[0] or blocked[1]):
                    side_id, item = pending.popleft()
                else:
                    # the merged queue is this operator's upstream
                    # handoff: time blocked here is queue-wait for the
                    # doctor's attribution (both sides produce on their
                    # own pump threads, so this only waits when BOTH
                    # sides are slower than the join)
                    t0_wait = time.perf_counter()
                    side_id, item = q.get()
                    self._note_input_wait(time.perf_counter() - t0_wait)
                    if blocked[side_id] and not isinstance(
                        item, BaseException
                    ):
                        pending.append((side_id, item))
                        continue
                side, other = sides[side_id], sides[1 - side_id]
                is_left = side_id == 0
                if isinstance(item, BaseException):
                    raise item
                if isinstance(item, WatermarkHint):
                    if item.kind == "partition":
                        side.src_watermarks = True
                        if item.is_announcement:
                            yield item  # pure mode announcement
                            continue
                    # watermark advance on this side (idle hint, or the
                    # side's authoritative per-partition watermark) so
                    # the joint horizon (min of both) can move and retained
                    # rows evict.  Downstream must see the JOINT low
                    # watermark — forwarding this side's ts verbatim would
                    # advance downstream event time past the still-active
                    # other side, and its later joined rows (carrying the
                    # other side's timestamps) would drop as late.
                    if side.watermark is None or item.ts_ms > side.watermark:
                        side.watermark = item.ts_ms
                    yield from self._evict_horizon(sides)
                    if (
                        sides[0].watermark is not None
                        and sides[1].watermark is not None
                    ):
                        # clamp by retention too: rows ABOVE the eviction
                        # horizon stay retained and can still match a
                        # resuming side, producing output with their
                        # (older) timestamps — forwarding min_wm verbatim
                        # would let downstream late-drop those matches
                        yield WatermarkHint(
                            min(sides[0].watermark, sides[1].watermark)
                            - self.retention_ms,
                            kind=item.kind,
                        )
                    continue
                if isinstance(item, EndOfStream):
                    if side.done:
                        continue
                    side.done = True
                    if self._ckpt is None:
                        # without checkpointing markers are pure pass-
                        # throughs: flush any the live side(s) delivered
                        live = sum(1 for s in sides if not s.done)
                        for epoch in sorted(
                            e for e, c in markers_seen.items() if c >= live
                        ):
                            markers_seen.pop(epoch, None)
                            yield Marker(epoch)
                    else:
                        # a finished side's source/window stopped
                        # participating in barriers at EOS — no upstream
                        # snapshot exists for any later epoch, so an epoch
                        # committed past this point would be an
                        # INCONSISTENT cut (the finished side would fully
                        # replay on restore while the join re-inserts its
                        # retained rows: duplicated build state).  Drop
                        # pending markers; the last both-live epoch stays
                        # the recovery point.
                        markers_seen.clear()
                    blocked[0] = blocked[1] = False
                    continue
                if isinstance(item, Marker):
                    c = markers_seen.get(item.epoch, 0) + 1
                    if self._ckpt is not None and (
                        sides[0].done or sides[1].done
                    ):
                        # see the EOS branch: no consistent two-input cut
                        # exists once a side finished
                        continue
                    # align markers: forward once both sides delivered it
                    live = sum(1 for s in sides if not s.done)
                    if c >= live:
                        markers_seen.pop(item.epoch, None)
                        if self._ckpt is not None:
                            self._snapshot(item.epoch, sides)
                        yield item
                        blocked[0] = blocked[1] = False
                    else:
                        markers_seen[item.epoch] = c
                        blocked[side_id] = True
                    continue
                batch: RecordBatch = item
                if batch.num_rows == 0:
                    continue
                self._obs_rows_in.add(batch.num_rows)
                if self._dr_lineage is not None:
                    # record-lineage hop (the generic _doctor_input hook
                    # can't see through the join's merged queue)
                    self._dr_lineage.hop(self._dr_node_id, batch)
                t0_batch = time.perf_counter()
                gids = self._gids_of(
                    batch, self.left_keys if is_left else self.right_keys
                )
                nb = self._sw_batches[side_id]
                self._sw_batches[side_id] = nb + 1
                if not self._sw_sample or nb % self._sw_sample == 0:
                    (self._sw if is_left else self._sw_right).update(gids)
                band_vals = (
                    self._band_vals(batch, is_left)
                    if self.band is not None else None
                )
                # insert BEFORE probing: the probe targets the OTHER side
                # (no self-match risk) and the matched[] marks it writes for
                # this batch's rows must not be cleared by a later insert
                probe_base = side.count
                attr = self._shared_attr
                side.insert(batch, gids, band_vals)
                if attr:
                    tb = (time.perf_counter() - t0_batch) * 1e3
                    self._stage_ms["build"] += tb
                    self._obs_mq_stage["build"].observe(tb)
                if self._tier is not None:
                    self._tier.note_insert(side_id, batch)
                if attr:
                    g0 = self._stage_ms["gather"]
                    tp0 = time.perf_counter()
                out = self._probe(
                    batch, gids, other, is_left, probe_base, side,
                    band_vals,
                )
                if attr:
                    # _probe accumulated its gather sub-phase itself;
                    # the remainder of the call is probe-index time
                    gather_d = self._stage_ms["gather"] - g0
                    tp = max(
                        (time.perf_counter() - tp0) * 1e3 - gather_d, 0.0
                    )
                    self._stage_ms["probe"] += tp
                    self._obs_mq_stage["probe"].observe(tp)
                    self._obs_mq_stage["gather"].observe(gather_d)
                    if out is not None:
                        self._obs_mq_fanout.add(out.num_rows)
                self._note_batch(t0_batch, batch.num_rows)
                if out is not None:
                    if not wm_announced:
                        # switch downstream to hint-driven watermarks
                        # BEFORE any joined rows: from here on the join's
                        # own clamped hints are the only advance, so old
                        # (still co-retained) pairs can never late-drop
                        wm_announced = True
                        yield WatermarkHint(WM_ANNOUNCE, kind="partition")
                    self._metrics["rows_out"] += out.num_rows
                    self._obs_rows_out.add(out.num_rows)
                    yield out
                # watermark & eviction
                ts = np.asarray(
                    batch.column(CANONICAL_TIMESTAMP_COLUMN), dtype=np.int64
                )
                if not side.src_watermarks:
                    bmin = int(ts.min())
                    if side.watermark is None or bmin > side.watermark:
                        side.watermark = bmin
                yield from self._evict_horizon(sides)
                if (
                    wm_announced
                    and sides[0].watermark is not None
                    and sides[1].watermark is not None
                ):
                    low = (
                        min(sides[0].watermark, sides[1].watermark)
                        - self.retention_ms
                    )
                    if wm_emitted is None or low > wm_emitted:
                        wm_emitted = low
                        yield WatermarkHint(low, kind="partition")
                if self._tier is not None:
                    self._tier.maybe_spill()
                if self._policy is not None:
                    # closed loop: the adaptation policy runs on the
                    # join's own thread between batches (layout
                    # mutations never race the probe) at its own cadence
                    self._policy.maybe_tick(self, sides)
            # EOS: flush unmatched for outer joins
            for s, l in ((sides[0], True), (sides[1], False)):
                if self._emits_unmatched(l):
                    for ub in self._evict(s, l, np.iinfo(np.int64).max):
                        padded = self._null_padded(ub, l)
                        self._metrics["rows_out"] += padded.num_rows
                        yield padded
            yield EOS
        finally:
            done.set()
