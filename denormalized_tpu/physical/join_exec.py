"""Symmetric streaming hash join.

The reference gets stream-stream joins "for free" from DataFusion's join over
two windowed streams (datastream.rs:126-177; examples/examples/stream_join.rs
joins two windowed aggregates on (sensor, window bounds)).  We implement the
streaming join ourselves: a symmetric hash join that builds a hash table per
side and probes the opposite table as batches arrive from either input.

Memory is bounded by watermark-driven eviction: a row can only match rows
whose event time is within ``retention_ms`` of the join watermark (the min of
both sides' watermarks), after which it is evicted — and, for outer joins,
emitted unmatched at eviction/EOS.  Both children are pumped by threads so a
slow side cannot stall the other (the reference relies on tokio task
scheduling for the same property).
"""

from __future__ import annotations

import queue as queue_mod
import threading
from typing import Iterator

import numpy as np

from denormalized_tpu.common.constants import CANONICAL_TIMESTAMP_COLUMN
from denormalized_tpu.common.errors import PlanError
from denormalized_tpu.common.record_batch import RecordBatch
from denormalized_tpu.common.schema import Schema
from denormalized_tpu.logical.expr import Expr
from denormalized_tpu.logical.plan import JoinKind
from denormalized_tpu.physical.base import (
    EOS,
    EndOfStream,
    ExecOperator,
    Marker,
    StreamItem,
)


class _SideState:
    """Hash table of buffered rows for one join side."""

    __slots__ = ("batches", "table", "matched", "watermark", "done", "rows")

    def __init__(self) -> None:
        self.batches: list[RecordBatch] = []  # retained row storage
        # key tuple -> list of (batch_idx, row_idx)
        self.table: dict[tuple, list[tuple[int, int]]] = {}
        # (batch_idx, row_idx) of rows that found ≥1 match (for outer joins)
        self.matched: set[tuple[int, int]] = set()
        self.watermark: int | None = None
        self.done = False
        self.rows = 0


class StreamingJoinExec(ExecOperator):
    def __init__(
        self,
        left: ExecOperator,
        right: ExecOperator,
        kind: JoinKind,
        left_keys: list[str],
        right_keys: list[str],
        filter_expr: Expr | None,
        schema: Schema,
        *,
        retention_ms: int = 300_000,
    ) -> None:
        if len(left_keys) != len(right_keys) or not left_keys:
            raise PlanError("join requires equal non-empty key lists")
        self.left = left
        self.right = right
        self.kind = kind
        self.left_keys = left_keys
        self.right_keys = right_keys
        self.filter_expr = filter_expr
        self.schema = schema
        self.retention_ms = retention_ms
        self._metrics = {"rows_out": 0, "evicted": 0}
        # output column plan: all left fields, then right fields minus
        # canonical-ts and shared equi-keys (mirrors lp.Join schema logic)
        left_names = set(left.schema.names)
        self._right_out = [
            f.name
            for f in right.schema
            if f.name != CANONICAL_TIMESTAMP_COLUMN and f.name not in left_names
        ]

    @property
    def children(self):
        return [self.left, self.right]

    def metrics(self):
        return dict(self._metrics)

    def _label(self):
        on = ", ".join(f"{l}={r}" for l, r in zip(self.left_keys, self.right_keys))
        return f"StreamingJoinExec({self.kind.value} on {on})"

    # ------------------------------------------------------------------
    @staticmethod
    def _keys_of(batch: RecordBatch, names: list[str]) -> list[tuple]:
        cols = [batch.column(n) for n in names]
        return list(zip(*[c.tolist() for c in cols]))

    def _insert(self, side: _SideState, batch: RecordBatch, keys: list[tuple]):
        bi = len(side.batches)
        side.batches.append(batch)
        side.rows += batch.num_rows
        for ri, k in enumerate(keys):
            side.table.setdefault(k, []).append((bi, ri))

    def _probe(
        self,
        probe_batch: RecordBatch,
        probe_keys: list[tuple],
        build: _SideState,
        probe_is_left: bool,
        probe_bi: int,
        probe_side: _SideState,
    ) -> RecordBatch | None:
        """Join a new batch against the opposite side's table.  Rows are
        marked 'matched' (for outer-join bookkeeping) only AFTER the join
        filter accepts the pair — an equi-hit rejected by the filter must
        still surface as unmatched in an outer join."""
        p_idx: list[int] = []
        b_pos: list[tuple[int, int]] = []
        for ri, k in enumerate(probe_keys):
            hits = build.table.get(k)
            if not hits:
                continue
            for pos in hits:
                p_idx.append(ri)
                b_pos.append(pos)
        if not p_idx:
            return None
        p_take = probe_batch.take(np.asarray(p_idx, dtype=np.int64))
        # gather build rows: per-batch vectorized take, then reassemble in
        # b_pos order (columns AND validity masks)
        build_batches = build.batches
        by_batch_idx: dict[int, list[int]] = {}
        for i, (bi, ri) in enumerate(b_pos):
            by_batch_idx.setdefault(bi, []).append(i)
        gathered: dict[int, RecordBatch] = {}
        for bi, idxs in by_batch_idx.items():
            rows = np.asarray([b_pos[i][1] for i in idxs], dtype=np.int64)
            gathered[bi] = build_batches[bi].take(rows)
        build_cols: dict[str, np.ndarray] = {}
        build_masks: dict[str, np.ndarray | None] = {}
        for name in build_batches[0].schema.names:
            dtype = gathered[next(iter(gathered))].column(name).dtype
            col = np.empty(len(b_pos), dtype=dtype)
            any_mask = any(g.mask(name) is not None for g in gathered.values())
            mask = np.ones(len(b_pos), dtype=bool) if any_mask else None
            for bi, idxs in by_batch_idx.items():
                col[idxs] = gathered[bi].column(name)
                if mask is not None:
                    m = gathered[bi].mask(name)
                    mask[idxs] = m if m is not None else True
            build_cols[name] = col
            build_masks[name] = mask
        probe_cols = {n: p_take.column(n) for n in p_take.schema.names}
        probe_masks = {n: p_take.mask(n) for n in p_take.schema.names}
        if probe_is_left:
            left_cols, left_masks = probe_cols, probe_masks
            right_cols, right_masks = build_cols, build_masks
        else:
            left_cols, left_masks = build_cols, build_masks
            right_cols, right_masks = probe_cols, probe_masks
        cols = [left_cols[n] for n in self.left.schema.names]
        masks = [left_masks.get(n) for n in self.left.schema.names]
        cols += [right_cols[n] for n in self._right_out]
        masks += [right_masks.get(n) for n in self._right_out]
        out = RecordBatch(self.schema, cols, masks)
        keep = np.ones(out.num_rows, dtype=bool)
        if self.filter_expr is not None:
            keep = np.asarray(self.filter_expr.eval(out), dtype=bool)
            if not keep.all():
                out = out.filter(keep)
        # mark matched pairs that survived the filter
        for i in np.nonzero(keep)[0].tolist():
            probe_side.matched.add((probe_bi, p_idx[i]))
            build.matched.add(b_pos[i])
        return out if out.num_rows else None

    # ------------------------------------------------------------------
    def _evict(self, side: _SideState, is_left: bool, horizon: int):
        """Drop rows older than the horizon; emit unmatched for outer joins."""
        unmatched: list[RecordBatch] = []
        keep_batches: list[RecordBatch] = []
        remap: dict[int, int] = {}
        for bi, b in enumerate(side.batches):
            ts = np.asarray(b.column(CANONICAL_TIMESTAMP_COLUMN), dtype=np.int64)
            if ts.max() < horizon:
                if self._emits_unmatched(is_left):
                    rows = [
                        ri
                        for ri in range(b.num_rows)
                        if (bi, ri) not in side.matched
                    ]
                    if rows:
                        unmatched.append(b.take(np.asarray(rows, dtype=np.int64)))
                self._metrics["evicted"] += b.num_rows
            else:
                remap[bi] = len(keep_batches)
                keep_batches.append(b)
        if len(keep_batches) != len(side.batches):
            side.batches = keep_batches
            new_table: dict[tuple, list[tuple[int, int]]] = {}
            for k, poss in side.table.items():
                kept = [(remap[bi], ri) for bi, ri in poss if bi in remap]
                if kept:
                    new_table[k] = kept
            side.table = new_table
            side.matched = {
                (remap[bi], ri) for bi, ri in side.matched if bi in remap
            }
        return unmatched

    def _emits_unmatched(self, is_left: bool) -> bool:
        if self.kind is JoinKind.FULL:
            return True
        return (self.kind is JoinKind.LEFT) == is_left and self.kind in (
            JoinKind.LEFT,
            JoinKind.RIGHT,
        )

    def _null_padded(self, batch: RecordBatch, is_left: bool) -> RecordBatch:
        """Pad the missing side with nulls for outer-join unmatched rows."""
        n = batch.num_rows
        cols, masks = [], []
        for f in self.schema:
            srcs = batch.schema
            if srcs.has(f.name):
                cols.append(batch.column(f.name))
                masks.append(batch.mask(f.name))
            else:
                cols.append(np.zeros(n, dtype=f.dtype.to_numpy()))
                masks.append(np.zeros(n, dtype=bool))
        return RecordBatch(self.schema, cols, masks)

    # ------------------------------------------------------------------
    def run(self) -> Iterator[StreamItem]:
        from denormalized_tpu.runtime.pump import spawn_pump

        q: queue_mod.Queue = queue_mod.Queue(maxsize=8)
        done = threading.Event()
        for side_id, op in ((0, self.left), (1, self.right)):
            spawn_pump(
                q,
                done,
                op.run,
                sentinel=(side_id, EOS),
                wrap=lambda item, s=side_id: (s, item),
            )
        sides = (_SideState(), _SideState())
        markers_seen: dict[int, int] = {}
        try:
            while not (sides[0].done and sides[1].done):
                side_id, item = q.get()
                side, other = sides[side_id], sides[1 - side_id]
                is_left = side_id == 0
                if isinstance(item, BaseException):
                    raise item
                if isinstance(item, EndOfStream):
                    if side.done:
                        continue
                    side.done = True
                    # a finished side no longer gates marker alignment:
                    # flush every pending marker the live side(s) delivered
                    live = sum(1 for s in sides if not s.done)
                    for epoch in sorted(
                        e for e, c in markers_seen.items() if c >= live
                    ):
                        markers_seen.pop(epoch, None)
                        yield Marker(epoch)
                    continue
                if isinstance(item, Marker):
                    # align markers: forward once both live sides delivered
                    # it; a finished side no longer gates alignment
                    c = markers_seen.get(item.epoch, 0) + 1
                    live = sum(1 for s in sides if not s.done)
                    if c >= live:
                        markers_seen.pop(item.epoch, None)
                        yield item
                    else:
                        markers_seen[item.epoch] = c
                    continue
                batch: RecordBatch = item
                if batch.num_rows == 0:
                    continue
                keys = self._keys_of(
                    batch, self.left_keys if is_left else self.right_keys
                )
                out = self._probe(
                    batch, keys, other, is_left, len(side.batches), side
                )
                self._insert(side, batch, keys)
                if out is not None:
                    self._metrics["rows_out"] += out.num_rows
                    yield out
                # watermark & eviction
                ts = np.asarray(
                    batch.column(CANONICAL_TIMESTAMP_COLUMN), dtype=np.int64
                )
                bmin = int(ts.min())
                if side.watermark is None or bmin > side.watermark:
                    side.watermark = bmin
                if sides[0].watermark is not None and sides[1].watermark is not None:
                    horizon = (
                        min(sides[0].watermark, sides[1].watermark)
                        - self.retention_ms
                    )
                    for s, l in ((sides[0], True), (sides[1], False)):
                        for ub in self._evict(s, l, horizon):
                            padded = self._null_padded(ub, l)
                            self._metrics["rows_out"] += padded.num_rows
                            yield padded
            # EOS: flush unmatched for outer joins
            for s, l in ((sides[0], True), (sides[1], False)):
                if self._emits_unmatched(l):
                    for ub in self._evict(s, l, np.iinfo(np.int64).max):
                        padded = self._null_padded(ub, l)
                        self._metrics["rows_out"] += padded.num_rows
                        yield padded
            yield EOS
        finally:
            done.set()
