"""Session windows — per-key gap-separated windows, fully vectorized.

The reference *declares* session windows (``StreamingWindowType::Session``,
logical_plan/streaming_window.rs:69-74) but its operator hits ``todo!()`` at
runtime (streaming_window.rs window-assignment session arm).  This operator
implements them: a session for key k is a maximal run of events where
consecutive timestamps are ≤ ``gap_ms`` apart; the window closes (and emits)
when the watermark passes ``last_ts + gap_ms``.

Sessions are data-dependent (no static window grid), so state lives
host-side — but "host-side" no longer means "Python objects".  The hot path
is zero per-row Python for the built-in aggregates
(count/sum/min/max/avg/stddev):

1. group keys intern to dense gids through
   :class:`~denormalized_tpu.ops.interner.RecyclingGroupInterner` (the same
   native PyObject fast path the tumbling operator and the join use; closed
   keys' gids recycle through a free list).  This also FIXES a correctness
   bug of the pre-vectorization operator: its salted 64-bit ``hash(tuple)``
   composite could collide and silently merge two distinct keys' segments —
   dense interner ids cannot collide.
2. per-batch segmenting is one lexsort by (gid, ts) + boundary scan, and ALL
   segment partials (counts/sums/mins/maxs + masked Chan moment columns)
   come out of single ``np.<ufunc>.reduceat`` passes — no Python loop over
   segments, no per-segment objects.
3. open sessions live in a :class:`~denormalized_tpu.ops.session_table
   .SessionTable`: a StreamBox-HBM-style SoA slot store (flat numpy arrays
   start/last/counts/sums/mins/maxs/means/m2s, per-gid chains like the
   join's ``_SideState``, slot free list).  Merging a batch's boundary
   segments into open sessions — including out-of-order bridges that fuse
   several open sessions — is ONE combined interval-merge sweep: gather the
   touched gids' open sessions, sort the union with the new segments by
   (gid, start), find merged runs with a segmented running max
   (``start − runmax(last) > gap`` starts a run), fold each run with
   reduceat, scatter back.  Watermark close/emit is a vectorized scan of
   the live slots.
4. the late-row salvage path keeps its per-row arrival-order semantics but
   only rows whose KEY has a candidate open interval walk it; every other
   row stays on the vectorized path.

UDAF/collection aggregates keep the accumulator-per-segment contract (user
code is inherently per-segment Python); they ride the same segmenting and
the same SoA store, with their accumulators in a slot-keyed side dict.

The pre-vectorization operator is preserved verbatim as
``physical/session_reference.py`` (``DENORMALIZED_SESSION_REFERENCE=1``
selects it) and serves as the differential oracle.
"""

from __future__ import annotations

import time
from typing import Iterator

import numpy as np

from denormalized_tpu.common.constants import (
    CANONICAL_TIMESTAMP_COLUMN,
    WINDOW_END_COLUMN,
    WINDOW_START_COLUMN,
)
from denormalized_tpu.common.errors import PlanError
from denormalized_tpu.common.record_batch import RecordBatch
from denormalized_tpu.common.schema import DataType, Field, Schema
from denormalized_tpu.logical.expr import AggregateExpr, Expr
from denormalized_tpu.ops.interner import RecyclingGroupInterner
from denormalized_tpu.ops.session_table import SessionTable
from denormalized_tpu.physical.base import (
    EOS,
    EndOfStream,
    ExecOperator,
    Marker,
    StreamItem,
    WatermarkHint,
)


def _segmented_cummax(vals: np.ndarray, seg_start: np.ndarray) -> np.ndarray:
    """Inclusive cumulative max of ``vals`` within segments whose first
    elements are flagged by ``seg_start``.  Offset trick: key each value as
    ``seg_id * stride + (v - min)`` so one ``np.maximum.accumulate`` can
    never carry a maximum across a segment boundary (every later segment's
    keys exceed every earlier segment's).  Falls back to a per-segment loop
    in the (practically unreachable) case the keyed range would overflow
    int64."""
    n = len(vals)
    if n == 0:
        return vals.copy()
    seg_id = np.cumsum(seg_start, dtype=np.int64) - 1
    base = int(vals.min())
    r = vals.astype(np.int64) - base
    stride = int(r.max()) + 1
    if int(seg_id[-1] + 1) * stride < 2**62:
        off = seg_id * stride
        return np.maximum.accumulate(off + r) - off + base
    out = np.empty_like(vals)
    bounds = np.nonzero(seg_start)[0]
    for b0, b1 in zip(bounds, np.append(bounds[1:], n)):
        out[b0:b1] = np.maximum.accumulate(vals[b0:b1])
    return out


class _SessionTier:
    """Cold tier of one session operator: evicts the coldest gids' open
    sessions (whole-gid granularity, blocks of up to
    ``tiering.SPILL_BLOCK_SLOTS`` slots) out of the SoA table into the
    LSM, reloads them when a batch touches their keys, the watermark
    reaches their gap, a checkpoint snapshots, or the stream ends.

    Invariant: a gid is either fully resident or fully spilled — touch
    reloads BEFORE any merge, so the table never holds a partial view of
    a spilled key.  Spilled gids keep their interner entries (the key →
    gid mapping is the membership filter's index), and the operator's
    release sites filter them out so a spilled gid can never be recycled
    out from under its block (reload re-interns key VALUES, so even a
    restore — which rebuilds the gid space — maps blocks back
    correctly)."""

    __slots__ = (
        "op", "node_id", "ctrl", "cold", "any_spilled", "spilled_bytes",
        "spilled_keys", "_block_of", "_blocks", "_next",
    )

    def __init__(self, op: "SessionWindowExec", node_id: str, ctrl) -> None:
        from denormalized_tpu.state import tiering

        self.op = op
        self.node_id = node_id
        self.ctrl = ctrl
        self.cold = tiering.ColdTracker()
        self.any_spilled = False
        self.spilled_bytes = 0
        self.spilled_keys = 0
        self._block_of = np.full(1024, -1, dtype=np.int64)
        self._blocks: dict[int, dict] = {}
        self._next = 0
        ctrl.register(node_id, op, self.resident_bytes)

    def resident_bytes(self) -> int:
        """O(1) resident estimate for the per-batch budget check (live
        slot count x exact per-slot bytes + the documented per-object
        estimates — the same formula as state_info, minus its live-slot
        scans)."""
        from denormalized_tpu.obs import statewatch as swm

        op = self.op
        T = op._table
        return (
            len(T) * T.per_slot_nbytes()
            + len(T.accs) * swm.ACC_EST_BYTES
            + len(op._interner) * swm.KEY_EST_BYTES
        )

    def _ensure_maps(self, n: int) -> None:
        self.cold.ensure(n)
        cap = len(self._block_of)
        if n <= cap:
            return
        while cap < n:
            cap *= 2
        new = np.full(cap, -1, dtype=np.int64)
        new[: len(self._block_of)] = self._block_of
        self._block_of = new

    # -- hot path: membership filter + touch stamp -----------------------
    def touch(self, gids: np.ndarray) -> np.ndarray | None:
        """Stamp the batch's gids hot and return the block ids any of
        them live in (None when the cold set is empty — the common case
        pays one attribute check and one scatter)."""
        self._ensure_maps(self.op._interner.capacity)
        self.cold.touch(gids)
        if not self.any_spilled:
            return None
        b = self._block_of[gids]
        hit = b[b >= 0]
        if len(hit) == 0:
            return None
        return np.unique(hit)

    def touch_and_reload(self, gids: np.ndarray) -> None:
        hits = self.touch(gids)
        if hits is not None:
            for bid in hits.tolist():
                self._reload_block(int(bid))
            self._write_manifest()

    # -- eviction ---------------------------------------------------------
    def maybe_spill(self, protect_gids: np.ndarray) -> None:
        from denormalized_tpu.state import tiering

        need = self.ctrl.over_budget()
        if need <= 0:
            self.ctrl.relax(self.node_id)
            return
        op = self.op
        T = op._table
        live = T.live_slots()
        spilled_any = False
        if len(live):
            per_slot = max(T.per_slot_nbytes(), 1)
            self._ensure_maps(op._interner.capacity)
            protect = np.zeros(len(self._block_of), dtype=bool)
            protect[protect_gids] = True
            live_gids = T.gid[live].astype(np.int64)
            cand = live_gids[~protect[live_gids]]
            if len(cand):
                u, counts = np.unique(cand, return_counts=True)
                order = np.argsort(
                    self.cold.last_touch[u], kind="stable"
                )
                u = u[order]
                counts = counts[order]
                csum = np.cumsum(counts)
                need_slots = -(-need // per_slot)
                k = int(np.searchsorted(csum, need_slots)) + 1
                k = min(k, len(u))
                chosen, chosen_counts = u[:k], counts[:k]
                # chunk the chosen gids into <= SPILL_BLOCK_SLOTS-slot
                # blocks (slow path — spill cadence, never per row)
                from denormalized_tpu.common.errors import StateError
                from denormalized_tpu.runtime.tracing import logger

                start = 0
                acc = 0
                for i in range(len(chosen)):
                    acc += int(chosen_counts[i])
                    if acc >= tiering.SPILL_BLOCK_SLOTS or i == len(chosen) - 1:
                        try:
                            self._spill_chunk(chosen[start : i + 1])
                        except StateError as e:
                            # a failed eviction put leaves the chunk
                            # resident: degrade to backpressure below,
                            # never kill the query over a spill write
                            logger.warning(
                                "spill: session eviction put failed "
                                "(%s) — chunk stays resident", e,
                            )
                            break
                        spilled_any = True
                        start, acc = i + 1, 0
                if spilled_any:
                    self._write_manifest()
                    op._state_info_cache = None
        self.ctrl.check_pressure(self.node_id)

    def _spill_chunk(self, gids_chunk: np.ndarray) -> None:
        from denormalized_tpu.state.checkpoint import jsonable
        from denormalized_tpu.state.serialization import pack_snapshot

        op = self.op
        T = op._table
        slots, owner = T.open_slots_of(gids_chunk)
        if len(slots) == 0:
            return
        fields = T.extract_slots(slots)
        accs_meta = None
        if op._udafs:
            accs_meta = [
                [acc.state() for acc in T.accs[int(s)]]
                if int(s) in T.accs
                else None
                for s in slots.tolist()
            ]
        keys = op._interner.keys_of(gids_chunk)
        meta = {
            "keys": jsonable([list(c) for c in keys]),
            "accs": jsonable(accs_meta),
            "n": int(len(slots)),
            "min_start": int(fields["start"].min()),
            "min_last": int(fields["last"].min()),
            "max_last": int(fields["last"].max()),
        }
        arrays = dict(fields)
        arrays["owner"] = owner.astype(np.int32)
        bid = self._next
        self._next += 1
        blob = pack_snapshot(meta, arrays)
        nbytes = self.ctrl.put_block(self.node_id, f"b{bid}", blob)
        T.remove_slots(slots)  # freed gids stay interned (spilled)
        self._block_of[gids_chunk] = bid
        self._blocks[bid] = {
            "gids": gids_chunk.copy(),
            "bytes": nbytes,
            "min_start": meta["min_start"],
            "min_last": meta["min_last"],
            "max_last": meta["max_last"],
        }
        self.any_spilled = True
        self.spilled_bytes += nbytes
        self.spilled_keys += int(len(gids_chunk))
        self.ctrl.note_spill(self.node_id, 1, nbytes)

    # -- reload -----------------------------------------------------------
    def _reload_block(self, bid: int) -> None:
        from denormalized_tpu.state import tiering
        from denormalized_tpu.state.serialization import unpack_snapshot

        meta = self._blocks.pop(bid)
        op = self.op
        raw = self.ctrl.get_block(self.node_id, f"b{bid}")
        bmeta, arrays = unpack_snapshot(raw)
        key_cols = tiering.key_columns_from_meta(bmeta["keys"])
        chunk_gids = op._interner.intern(key_cols).astype(np.int64)
        self._ensure_maps(op._interner.capacity)
        T = op._table
        T.ensure_gids(op._interner.capacity)
        slot_gids = chunk_gids[arrays["owner"]]
        fields = {k: arrays[k] for k in T.SPILL_FIELDS}
        slots = T.inject_slots(slot_gids, fields)
        if bmeta.get("accs"):
            for s, states in zip(slots.tolist(), bmeta["accs"]):
                if states is None:
                    continue
                accs = op._make_accs()
                for acc, st in zip(accs, states):
                    acc.merge(st)
                T.accs[int(s)] = accs
        self._block_of[meta["gids"]] = -1
        self._block_of[chunk_gids] = -1  # restore path: gids re-assigned
        self.any_spilled = bool(self._blocks)
        self.spilled_bytes -= meta["bytes"]
        self.spilled_keys -= int(len(meta["gids"]))
        self.ctrl.note_reload(self.node_id, 1, len(raw))
        self.ctrl.delete_block(self.node_id, f"b{bid}")
        op._state_info_cache = None

    def reload_for_watermark(self, watermark: int) -> None:
        """Blocks holding ANY gap-expired session reload so the close
        sweep sees them — emission timing (and therefore output) stays
        identical to the unbudgeted run."""
        if not self.any_spilled:
            return
        gap = self.op.gap_ms
        due = [
            bid for bid, m in self._blocks.items()
            if m["min_last"] + gap <= watermark
        ]
        for bid in due:
            self._reload_block(bid)
        if due:
            self._write_manifest()

    def reload_all(self) -> None:
        for bid in list(self._blocks):
            self._reload_block(bid)
        self._write_manifest()

    def _write_manifest(self) -> None:
        self.ctrl.write_manifest(
            self.node_id, [f"b{b}" for b in self._blocks]
        )

    # -- guards + accounting ---------------------------------------------
    def filter_releasable(self, gids: np.ndarray) -> np.ndarray:
        """Never recycle a gid whose sessions live in the cold tier."""
        if not self.any_spilled or len(gids) == 0:
            return gids
        return gids[self._block_of[gids] < 0]

    def min_start(self) -> int | None:
        if not self._blocks:
            return None
        return min(m["min_start"] for m in self._blocks.values())

    def info(self) -> dict:
        return {
            "spilled_bytes": self.spilled_bytes,
            "spilled_keys": self.spilled_keys,
            "spilled_blocks": len(self._blocks),
            "spill": self.ctrl.spill_stats(self.node_id),
        }

    # -- checkpoint integration -------------------------------------------
    def snapshot_refs(self, coord, key: str, epoch: int) -> list[int]:
        bids = sorted(self._blocks)
        for bid in bids:
            self.ctrl.copy_block_to_epoch(
                coord, key, epoch, self.node_id, f"b{bid}"
            )
        return bids

    def restore_refs(self, coord, key: str, bids: list[int]) -> None:
        """Rebuild the tier map from a committed epoch: each block's
        payload streams back into the spill namespace (one at a time),
        its keys re-intern into the fresh gid space, and the membership
        maps re-arm — the cold tier is never materialized in RAM."""
        from denormalized_tpu.state import tiering
        from denormalized_tpu.state.serialization import unpack_snapshot

        op = self.op
        for bid in bids:
            raw = self.ctrl.restore_block_from_epoch(
                coord, key, self.node_id, f"b{bid}"
            )
            bmeta, _arrays = unpack_snapshot(raw)
            key_cols = tiering.key_columns_from_meta(bmeta["keys"])
            chunk_gids = op._interner.intern(key_cols).astype(np.int64)
            self._ensure_maps(op._interner.capacity)
            op._table.ensure_gids(op._interner.capacity)
            self._block_of[chunk_gids] = bid
            self._blocks[bid] = {
                "gids": chunk_gids,
                "bytes": len(raw),
                "min_start": int(bmeta["min_start"]),
                "min_last": int(bmeta["min_last"]),
                "max_last": int(bmeta["max_last"]),
            }
            self.spilled_bytes += len(raw)
            self.spilled_keys += int(len(chunk_gids))
            self._next = max(self._next, bid + 1)
        self.any_spilled = bool(self._blocks)
        self._write_manifest()


class SessionWindowExec(ExecOperator):
    def __init__(
        self,
        input_op: ExecOperator,
        group_exprs: list[Expr],
        aggr_exprs: list[AggregateExpr],
        gap_ms: int,
        *,
        emit_on_close: bool = True,
        name: str = "session_window",
    ) -> None:
        if not group_exprs:
            raise PlanError("session windows require at least one group key")
        self.input_op = input_op
        self.group_exprs = list(group_exprs)
        self.aggr_exprs = list(aggr_exprs)
        self.gap_ms = int(gap_ms)
        self.emit_on_close = emit_on_close
        self.name = name

        in_schema = input_op.schema
        self._value_exprs: list[Expr] = []
        keys: dict[str, int] = {}

        def value_idx(e: Expr) -> int:
            k = repr(e)
            if k not in keys:
                keys[k] = len(self._value_exprs)
                self._value_exprs.append(e)
            return keys[k]

        # accumulator (UDAF/collection) aggregates ride their own per-
        # session Accumulator instances; their args never enter the float
        # value matrix (they may be strings)
        self._udafs = []  # list of AggregateExpr with kind == "udaf"
        self._agg_specs: list[tuple] = []
        for a in self.aggr_exprs:
            if a.kind == "udaf":
                self._agg_specs.append(("udaf", len(self._udafs)))
                self._udafs.append(a)
                continue
            if a.arg is None:
                self._agg_specs.append((a.kind, None))
                continue
            self._agg_specs.append((a.kind, value_idx(a.arg)))

        fields = [g.out_field(in_schema) for g in self.group_exprs]
        fields += [a.out_field(in_schema) for a in self.aggr_exprs]
        fields += [
            Field(WINDOW_START_COLUMN, DataType.TIMESTAMP_MS, nullable=False),
            Field(WINDOW_END_COLUMN, DataType.TIMESTAMP_MS, nullable=False),
            Field(CANONICAL_TIMESTAMP_COLUMN, DataType.TIMESTAMP_MS, nullable=False),
        ]
        self.schema = Schema(fields)

        self._interner = RecyclingGroupInterner(len(self.group_exprs))
        self._table = SessionTable(len(self._value_exprs))
        self._watermark: int | None = None
        # True once a kind="partition" hint arrived: batch min-ts no
        # longer advances the watermark (replay-skew safety)
        self._src_watermarks = False
        self._ckpt: tuple | None = None
        # cold tier (state/tiering.py): installed by enable_spill when a
        # state budget + backend are configured; None = all-resident
        self._tier: _SessionTier | None = None
        self._metrics = {
            "rows_in": 0,
            "sessions_emitted": 0,
            "late_rows": 0,
            "salvage_rows_scanned": 0,
        }
        from denormalized_tpu import obs
        from denormalized_tpu.obs import statewatch

        self.bind_obs("session")
        # state observatory: heavy-hitter/cardinality sketches fed dense
        # gids per batch (falsy null when metrics are disabled)
        self._sw = statewatch.make_watch("session")
        self._obs_late = obs.counter("dnz_late_rows_total", op="session")
        self._obs_windows = obs.counter(
            "dnz_windows_emitted_total", op="session"
        )
        self._obs_emit_lag = obs.histogram(
            "dnz_emit_event_lag_ms", op="session"
        )
        self._obs_wm_lag = obs.gauge("dnz_watermark_lag_ms", op="session")
        self._obs_wm_lag_hist = obs.histogram(
            "dnz_watermark_lag_hist_ms", op="session"
        )

    @property
    def children(self):
        return [self.input_op]

    def metrics(self):
        return dict(self._metrics)

    def _label(self):
        return (
            f"SessionWindowExec(gap={self.gap_ms}ms, "
            f"groups=[{', '.join(g.name for g in self.group_exprs)}])"
        )

    # -- cold tier (state/tiering.py) -----------------------------------
    def enable_spill(self, node_id: str, controller) -> None:
        self._tier = _SessionTier(self, node_id, controller)

    # -- state observatory (obs/statewatch.py) --------------------------
    def state_info(self) -> dict:
        from denormalized_tpu.obs import statewatch as swm
        from denormalized_tpu.ops.interner import interner_accounting

        T = self._table
        live = T.live_slots()
        n_live = int(len(live))
        acc_objs = (
            sum(len(v) for v in T.accs.values()) if T.accs else 0
        )
        keys = interner_accounting(self._interner)
        wm = self._watermark
        oldest = int(T.start[live].min()) if n_live else None
        if self._tier is not None:
            tmin = self._tier.min_start()
            if tmin is not None:
                oldest = tmin if oldest is None else min(oldest, tmin)
        info = {
            "op": "session",
            # live accounting only (restore-invariant by construction):
            # exact numpy storage per live slot + documented per-object
            # estimates for interned keys and accumulator objects
            "state_bytes": (
                n_live * T.per_slot_nbytes()
                + keys["live_keys"] * swm.KEY_EST_BYTES
                + acc_objs * swm.ACC_EST_BYTES
            ),
            # the portion the cold tier can actually evict: slot storage
            # + accumulators.  The interned-key index stays resident by
            # design (it IS the spill membership filter) — the documented
            # resident floor of a budgeted run (docs/state_spill.md)
            "evictable_bytes": (
                n_live * T.per_slot_nbytes()
                + acc_objs * swm.ACC_EST_BYTES
            ),
            "capacity_bytes": T.capacity_nbytes(),
            "slot_capacity": int(len(T.start)),
            "slot_live": n_live,
            "acc_objects": acc_objs,
            "oldest_event_ms": oldest,
            "watermark_ms": wm,
            "retention_unit_ms": self.gap_ms,
            **keys,
        }
        if wm is not None and oldest is not None:
            info["oldest_event_lag_ms"] = max(0, int(wm) - oldest)
        if self._tier is not None:
            info.update(self._tier.info())
        return info

    def _state_watch_views(self):
        if not self._sw:
            return []
        from denormalized_tpu.ops.interner import display_keys

        return [
            (None, self._sw, lambda g: display_keys(self._interner, g))
        ]

    # ------------------------------------------------------------------
    def _make_accs(self) -> list | None:
        if not self._udafs:
            return None
        return [a.udaf.make() for a in self._udafs]

    # -- late-row salvage (the ONLY per-row path; scoped to keys with a
    # -- candidate open interval) --------------------------------------
    def _salvage_late(
        self, ts: np.ndarray, gids: np.ndarray, late: np.ndarray
    ) -> np.ndarray:
        """Decide per-row, in ARRIVAL order, which late rows merge into a
        still-open (or this-batch-created) session of their key — exactly
        as row-at-a-time processing would (Flink event-time session
        semantics: a late row within gap of an open session belongs to it;
        only true closed singletons drop).  Returns the updated ``late``
        mask.  Only rows whose key has at least one late row this batch
        walk the loop; all other rows never leave the vectorized path."""
        gap_ms = self.gap_ms
        T = self._table
        aff_gids = np.unique(gids[late])
        # interval views of the affected keys' open sessions
        views: dict[int, list[list[int]]] = {int(g): [] for g in aff_gids}
        slots, owner = T.open_slots_of(aff_gids)
        starts = T.start[slots]
        lasts = T.last[slots]
        for i, pos in enumerate(owner.tolist()):
            views[int(aff_gids[pos])].append([int(starts[i]), int(lasts[i])])
        aff_mask = np.zeros(self._interner.capacity, dtype=bool)
        aff_mask[aff_gids] = True
        rows = np.nonzero(aff_mask[gids])[0]
        self._metrics["salvage_rows_scanned"] += len(rows)
        late = late.copy()
        for i in rows.tolist():
            iv_list = views[int(gids[i])]
            t = int(ts[i])
            hit = [
                iv
                for iv in iv_list
                if t - iv[1] <= gap_ms and iv[0] - t <= gap_ms
            ]
            if late[i]:
                if not hit:
                    continue  # true closed singleton: stays dropped
                late[i] = False
            merged = [
                min([t] + [iv[0] for iv in hit]),
                max([t] + [iv[1] for iv in hit]),
            ]
            views[int(gids[i])] = [
                iv for iv in iv_list if iv not in hit
            ] + [merged]
        return late

    # -- vectorized batch path ------------------------------------------
    def _process_batch(self, batch: RecordBatch) -> Iterator[RecordBatch]:
        n = batch.num_rows
        if n == 0:
            return
        self._metrics["rows_in"] += n
        self._obs_rows_in.add(n)
        ts = np.asarray(batch.column(CANONICAL_TIMESTAMP_COLUMN), dtype=np.int64)
        key_cols = [g.eval(batch) for g in self.group_exprs]
        gids = self._interner.intern(key_cols)
        self._sw.update(gids)
        if self._tier is not None:
            # membership pre-probe + reload-on-touch: any spilled gid in
            # this batch comes back resident BEFORE merging (costs one
            # scatter + one gather when the cold set is empty)
            self._tier.touch_and_reload(gids)
        self._table.ensure_gids(self._interner.capacity)
        vals = (
            np.stack(
                [np.asarray(e.eval(batch), dtype=np.float64) for e in self._value_exprs],
                axis=1,
            )
            if self._value_exprs
            else np.zeros((n, 0))
        )
        from denormalized_tpu.logical.expr import column_validity

        valid = np.ones_like(vals, dtype=bool)
        for ci, e in enumerate(self._value_exprs):
            m = column_validity(e, batch)
            if m is not None:
                valid[:, ci] = m

        # accumulator-aggregate argument columns (raw dtypes) + masks
        udaf_cols: list[list[np.ndarray]] = []
        udaf_masks: list[np.ndarray | None] = []
        for a in self._udafs:
            udaf_cols.append([np.asarray(e.eval(batch)) for e in a.udaf.args])
            udaf_masks.append(
                column_validity(a.udaf.args[0], batch) if a.udaf.args else None
            )
        # watermark advances from the RAW batch min (late rows included —
        # they only keep the min lower, and the reference's
        # RecordBatchWatermark is computed over the whole batch); computing
        # it after the late-filter would let a dropped row inflate the
        # watermark and mis-drop later on-time rows
        raw_min = int(ts.min())

        dropped_gids: np.ndarray | None = None
        if self._watermark is not None:
            late = ts + self.gap_ms <= self._watermark
            if late.any():
                late = self._salvage_late(ts, gids, late)
            n_late = int(late.sum())
            if n_late:
                self._metrics["late_rows"] += n_late
                self._obs_late.add(n_late)
                dropped_gids = np.unique(gids[late])
                keep = ~late
                ts = ts[keep]
                gids = gids[keep]
                vals = vals[keep]
                valid = valid[keep]
                udaf_cols = [[c[keep] for c in cols] for cols in udaf_cols]
                udaf_masks = [
                    m[keep] if m is not None else None for m in udaf_masks
                ]
                n = len(ts)

        if n:
            # vectorized per-key segmenting: sort by (gid, ts), then one
            # reduceat per aggregate primitive over key-run + intra-batch
            # gap boundaries
            order = np.lexsort((ts, gids))
            ts_s = ts[order]
            g_s = gids[order]
            vals_s = vals[order]
            valid_s = valid[order]
            boundary = np.empty(n, dtype=bool)
            boundary[0] = True
            boundary[1:] = (g_s[1:] != g_s[:-1]) | (
                (ts_s[1:] - ts_s[:-1]) > self.gap_ms
            )
            bounds = np.nonzero(boundary)[0]
            lens = np.diff(np.append(bounds, n))
            seg_gid = g_s[bounds].astype(np.int64)
            seg_first = ts_s[bounds]
            seg_last = ts_s[np.append(bounds[1:], n) - 1]
            seg_rows = lens.astype(np.int64)
            # null-neutralize per aggregate kind (same semantics as the
            # device kernel: nulls excluded from count/sum/min/max)
            seg_counts = np.add.reduceat(
                valid_s.astype(np.int64), bounds, axis=0
            )
            seg_sums = np.add.reduceat(
                np.where(valid_s, vals_s, 0.0), bounds, axis=0
            )
            seg_mins = np.minimum.reduceat(
                np.where(valid_s, vals_s, np.inf), bounds, axis=0
            )
            seg_maxs = np.maximum.reduceat(
                np.where(valid_s, vals_s, -np.inf), bounds, axis=0
            )
            with np.errstate(invalid="ignore", divide="ignore"):
                seg_means = np.where(
                    seg_counts > 0,
                    seg_sums / np.maximum(seg_counts, 1),
                    0.0,
                )
            centered = vals_s - np.repeat(seg_means, lens, axis=0)
            seg_m2s = np.add.reduceat(
                np.where(valid_s, centered * centered, 0.0), bounds, axis=0
            )
            seg_accs = None
            if self._udafs:
                # accumulator-per-segment contract: user code runs once per
                # (key, segment) — inherently Python, and only here
                seg_accs = []
                for b0, b1 in zip(bounds.tolist(), np.append(bounds[1:], n).tolist()):
                    accs = self._make_accs()
                    seg_idx = order[b0:b1]
                    for acc, cols, am in zip(accs, udaf_cols, udaf_masks):
                        chunk = [c[seg_idx] for c in cols]
                        if am is not None:
                            ok = am[seg_idx]
                            chunk = [c[ok] for c in chunk]
                        acc.update(*chunk)
                    seg_accs.append(accs)
            self._merge_segments(
                seg_gid, seg_first, seg_last, seg_rows, seg_counts,
                seg_sums, seg_mins, seg_maxs, seg_means, seg_m2s, seg_accs,
            )

        # watermark advance + close expired sessions — skipped under
        # per-partition watermarks: the authoritative advance arrives as
        # a kind="partition" hint right after this batch
        if not self._src_watermarks:
            yield from self._advance_and_close(raw_min)
        if dropped_gids is not None:
            # a key whose only-ever rows were dropped-late holds no state:
            # recycle its gid immediately instead of leaking it
            idle = dropped_gids[self._table.head[dropped_gids] == -1]
            if self._tier is not None:
                idle = self._tier.filter_releasable(idle)
            if len(idle):
                self._interner.release(idle)
        if self._tier is not None:
            self._tier.maybe_spill(gids)

    def _merge_segments(
        self,
        seg_gid: np.ndarray,
        seg_first: np.ndarray,
        seg_last: np.ndarray,
        seg_rows: np.ndarray,
        seg_counts: np.ndarray,
        seg_sums: np.ndarray,
        seg_mins: np.ndarray,
        seg_maxs: np.ndarray,
        seg_means: np.ndarray,
        seg_m2s: np.ndarray,
        seg_accs: list | None,
    ) -> None:
        """One combined interval-merge sweep: union the touched gids' open
        sessions with the batch segments, sort by (gid, start), split into
        merged runs where ``start − running_max(last) > gap`` (sessions
        stay open until the watermark passes ``last + gap`` — closing on
        gap-at-arrival would mis-split out-of-order data, so a segment may
        bridge several open sessions), fold every run with reduceat, and
        scatter the merged sessions back into the SoA table."""
        T = self._table
        S = len(seg_gid)
        touched = np.unique(seg_gid)
        ex_slots, ex_owner = T.open_slots_of(touched)
        E = len(ex_slots)
        M = E + S
        cg = np.concatenate([touched[ex_owner], seg_gid])
        cstart = np.concatenate([T.start[ex_slots], seg_first])
        clast = np.concatenate([T.last[ex_slots], seg_last])
        cnew = np.zeros(M, dtype=bool)
        cnew[E:] = True
        # tie-break (cnew last): at equal start the EXISTING session sorts
        # first — order-sensitive accumulator folds keep arrival order
        order = np.lexsort((cnew, cstart, cg))
        g2 = cg[order]
        st2 = cstart[order]
        la2 = clast[order]
        newg = np.empty(M, dtype=bool)
        newg[0] = True
        newg[1:] = g2[1:] != g2[:-1]
        runmax = _segmented_cummax(la2, newg)
        boundary = newg.copy()
        boundary[1:] |= (st2[1:] - runmax[:-1]) > self.gap_ms
        rb = np.nonzero(boundary)[0]
        runlens = np.diff(np.append(rb, M))
        crow = np.concatenate([T.row_count[ex_slots], seg_rows])[order]
        ccnt = np.concatenate([T.counts[ex_slots], seg_counts], axis=0)[order]
        csum = np.concatenate([T.sums[ex_slots], seg_sums], axis=0)[order]
        cmin = np.concatenate([T.mins[ex_slots], seg_mins], axis=0)[order]
        cmax = np.concatenate([T.maxs[ex_slots], seg_maxs], axis=0)[order]
        cmean = np.concatenate([T.means[ex_slots], seg_means], axis=0)[order]
        cm2 = np.concatenate([T.m2s[ex_slots], seg_m2s], axis=0)[order]
        out_gid = g2[rb]
        out_start = st2[rb]
        out_last = np.maximum.reduceat(la2, rb)
        out_row = np.add.reduceat(crow, rb)
        out_cnt = np.add.reduceat(ccnt, rb, axis=0)
        out_sum = np.add.reduceat(csum, rb, axis=0)
        out_min = np.minimum.reduceat(cmin, rb, axis=0)
        out_max = np.maximum.reduceat(cmax, rb, axis=0)
        # k-way Chan moment combine (exact algebra of chan_merge):
        # M2 = Σ m2_i + Σ n_i (μ_i − μ)²  with  μ = Σ n_i μ_i / Σ n_i
        cntf = ccnt.astype(np.float64)
        wmean = np.add.reduceat(cntf * cmean, rb, axis=0)
        with np.errstate(invalid="ignore", divide="ignore"):
            out_mean = np.where(
                out_cnt > 0, wmean / np.maximum(out_cnt, 1), 0.0
            )
        centered = cmean - np.repeat(out_mean, runlens, axis=0)
        out_m2 = np.add.reduceat(cm2 + cntf * centered * centered, rb, axis=0)
        single = runlens == 1
        if single.any():
            # identity folds must not re-round a stored moment pair
            out_mean[single] = cmean[rb[single]]
            out_m2[single] = cm2[rb[single]]
        new_accs = None
        if self._udafs:
            # per-RUN accumulator fold (runs only; Python is unavoidable —
            # accumulator state is opaque user code).  Order-sensitive
            # accumulators (first/last_value, array_agg) must see EXACTLY
            # the fold order of sequential processing, including the quirk
            # that a mid-batch merge can lower a session's start and change
            # which member is the next merge's base — so replay the
            # reference algorithm per run: for each new segment in ts
            # order, merge its within-gap hits base-oldest-first, then the
            # segment's own partial last.
            cref = np.concatenate(
                [ex_slots, -np.arange(1, S + 1, dtype=np.int64)]
            )[order]
            cnew2 = cnew[order]
            new_accs = []
            for b0, b1 in zip(rb.tolist(), np.append(rb[1:], M).tolist()):
                refs = cref[b0:b1]
                news = cnew2[b0:b1]
                # live mini-set of [start, last, accs] for this run;
                # existing sessions seed it (they are pairwise >gap apart)
                sess = [
                    [int(st2[b0 + i]), int(la2[b0 + i]),
                     T.accs.pop(int(refs[i]))]
                    for i in range(b1 - b0)
                    if not news[i]
                ]
                for i in range(b1 - b0):
                    if not news[i]:
                        continue
                    first = int(st2[b0 + i])
                    last = int(la2[b0 + i])
                    part = seg_accs[-int(refs[i]) - 1]
                    hits = [
                        s for s in sess
                        if first - s[1] <= self.gap_ms
                        and s[0] - last <= self.gap_ms
                    ]
                    if not hits:
                        sess.append([first, last, part])
                        continue
                    hits.sort(key=lambda s: s[0])
                    base = hits[0]
                    for s in hits[1:]:
                        for acc, other in zip(base[2], s[2]):
                            acc.merge(other.state())
                    for acc, p in zip(base[2], part):
                        acc.merge(p.state())
                    base[0] = min(base[0], first)
                    base[1] = max([last] + [s[1] for s in hits])
                    sess = [s for s in sess if s not in hits[1:]]
                # the run IS one merged session (transitive closure), so
                # exactly one survivor remains; fold defensively if not
                accs = sess[0][2]
                for s in sess[1:]:  # pragma: no cover — unreachable
                    for acc, other in zip(accs, s[2]):
                        acc.merge(other.state())
                new_accs.append(accs)
        # scatter back: every touched gid's open set is rewritten wholesale
        T.free(ex_slots)
        T.head[touched] = -1
        slots = T.alloc(len(rb))
        T.start[slots] = out_start
        T.last[slots] = out_last
        T.row_count[slots] = out_row
        T.counts[slots] = out_cnt
        T.sums[slots] = out_sum
        T.mins[slots] = out_min
        T.maxs[slots] = out_max
        T.means[slots] = out_mean
        T.m2s[slots] = out_m2
        T.gid[slots] = out_gid
        T.live[slots] = True
        T.chain(out_gid, slots)
        if new_accs is not None:
            for s, a in zip(slots.tolist(), new_accs):
                T.accs[int(s)] = a

    # -- close + emit ----------------------------------------------------
    def _advance_and_close(self, candidate_wm: int) -> Iterator[RecordBatch]:
        """Monotonic watermark advance, then emit every session whose gap
        has expired — shared by the per-batch path and idle-source
        WatermarkHint handling.  One vectorized scan of the live slots."""
        if self._watermark is None or candidate_wm > self._watermark:
            self._watermark = candidate_wm
        if self._obs_wm_lag:
            lag = time.time() * 1000.0 - self._watermark
            self._obs_wm_lag.set(lag)
            self._obs_wm_lag_hist.observe(lag)
        if self._tier is not None:
            # gap-expired cold blocks come back resident so this sweep
            # closes them on the same watermark the all-resident run does
            self._tier.reload_for_watermark(self._watermark)
        expired = self._table.expired_slots(self.gap_ms, self._watermark)
        if len(expired) == 0:
            return
        order = np.lexsort(
            (self._table.gid[expired], self._table.start[expired])
        )
        expired = expired[order]
        out = self._emit_slots(expired)
        freed = self._table.remove_slots(expired)
        if self._tier is not None:
            freed = self._tier.filter_releasable(freed)
        if len(freed):
            # closed keys' dense ids go back to the interner free list
            self._interner.release(freed)
        yield out

    def _emit_slots(self, slots: np.ndarray) -> RecordBatch:
        T = self._table
        m = len(slots)
        self._metrics["sessions_emitted"] += m
        self._obs_windows.add(m)
        if self._obs_emit_lag:
            # one sample per emission sweep, at the OLDEST session's end
            # (start-of-last-row + gap) — the conservative bound on this
            # sweep's event-time emission latency
            self._obs_emit_lag.observe(
                time.time() * 1000.0
                - (float(T.last[slots].min()) + self.gap_ms)
            )
        if self._dr_lineage is not None:
            # lineage close: a sampled row belongs to the session whose
            # [start, last + gap) interval contains its event time
            self._dr_lineage.emitted(
                self._dr_node_id,
                np.asarray(T.start[slots], dtype=np.int64),
                np.asarray(T.last[slots], dtype=np.int64) + self.gap_ms,
            )
        in_schema = self.input_op.schema
        key_vals = self._interner.keys_of(T.gid[slots])
        cols: list[np.ndarray] = []
        for ci, g in enumerate(self.group_exprs):
            f = g.out_field(in_schema)
            vals = np.asarray(key_vals[ci], dtype=object)
            if f.dtype.is_numeric:
                vals = vals.astype(f.dtype.to_numpy())
            cols.append(vals)
        from denormalized_tpu.ops.segment_agg import VAR_KINDS, variance_from_m2

        with np.errstate(invalid="ignore", divide="ignore"):
            for ai, spec in enumerate(self._agg_specs):
                kind, col_i = spec[0], spec[1]
                if kind == "udaf":
                    vals_out = [
                        T.accs[int(s)][col_i].evaluate() for s in slots.tolist()
                    ]
                    arr = np.empty(m, dtype=object)
                    for vi, v in enumerate(vals_out):
                        arr[vi] = v
                    f = self.aggr_exprs[ai].out_field(in_schema)
                    if f.dtype.is_numeric:
                        arr = arr.astype(f.dtype.to_numpy())
                    cols.append(arr)
                elif kind in VAR_KINDS:
                    cols.append(
                        variance_from_m2(
                            kind, T.counts[slots, col_i], T.m2s[slots, col_i]
                        )
                    )
                elif kind == "count":
                    cols.append(
                        (
                            T.row_count[slots]
                            if col_i is None
                            else T.counts[slots, col_i]
                        ).astype(np.int64)
                    )
                elif kind == "sum":
                    cols.append(T.sums[slots, col_i].copy())
                elif kind == "avg":
                    c = T.counts[slots, col_i]
                    cols.append(
                        np.where(
                            c > 0,
                            T.sums[slots, col_i] / np.maximum(c, 1),
                            np.nan,
                        )
                    )
                elif kind == "min":
                    v = T.mins[slots, col_i]
                    cols.append(np.where(np.isposinf(v), np.nan, v))
                elif kind == "max":
                    v = T.maxs[slots, col_i]
                    cols.append(np.where(np.isneginf(v), np.nan, v))
                else:
                    raise PlanError(f"session window does not support {kind}")
        starts = T.start[slots].astype(np.int64)
        ends = (T.last[slots] + self.gap_ms).astype(np.int64)
        # cast agg outputs to declared dtypes
        out_cols = []
        for f, c in zip(self.schema.fields[: len(cols)], cols):
            out_cols.append(
                c if c.dtype == object else c.astype(f.dtype.to_numpy())
            )
        out_cols += [starts, ends, starts.copy()]
        return RecordBatch(self.schema, out_cols)

    # -- checkpointing (SoA store → the dict-era JSON blob, unchanged
    # -- format: snapshots interoperate with the reference operator) ------
    def enable_checkpointing(self, node_id: str, coord, orch) -> None:
        from denormalized_tpu.state.checkpoint import get_json

        self._ckpt = (coord, f"session_{node_id}")
        snap = get_json(coord, self._ckpt[1])
        if snap is None:
            return
        self._watermark = snap["watermark"]
        self._restore_sessions(snap["sessions"])
        bids = snap.get("spill_blocks") or []
        if bids:
            if self._tier is not None:
                # rebuild the tier map (blocks stream epoch → spill
                # namespace one at a time; cold state stays cold)
                self._tier.restore_refs(coord, self._ckpt[1], bids)
            else:
                # budget removed since the checkpoint: degrade gracefully
                # by loading the cold tier back resident
                self._restore_spilled_resident(coord, self._ckpt[1], bids)

    def _restore_spilled_resident(self, coord, key: str, bids: list) -> None:
        from denormalized_tpu.state import tiering
        from denormalized_tpu.common.errors import StateError
        from denormalized_tpu.state.serialization import unpack_snapshot

        T = self._table
        for bid in bids:
            raw = coord.get_snapshot(f"{key}:spill:b{bid}")
            if raw is None:
                raise StateError(
                    f"checkpoint references spilled session block b{bid} "
                    "but the epoch holds no such snapshot"
                )
            bmeta, arrays = unpack_snapshot(raw)
            key_cols = tiering.key_columns_from_meta(bmeta["keys"])
            chunk_gids = self._interner.intern(key_cols).astype(np.int64)
            T.ensure_gids(self._interner.capacity)
            slot_gids = chunk_gids[arrays["owner"]]
            fields = {k: arrays[k] for k in T.SPILL_FIELDS}
            slots = T.inject_slots(slot_gids, fields)
            if bmeta.get("accs"):
                for s, states in zip(slots.tolist(), bmeta["accs"]):
                    if states is None:
                        continue
                    accs = self._make_accs()
                    for acc, st in zip(accs, states):
                        acc.merge(st)
                    T.accs[int(s)] = accs

    def _restore_sessions(self, entries: list) -> None:
        self._interner = RecyclingGroupInterner(len(self.group_exprs))
        self._table = SessionTable(len(self._value_exprs))
        # sketches do NOT ride the snapshot: the gid space is reassigned
        # here, so they restart and re-warm from live traffic (accuracy
        # note in docs/observability.md); exact accounting is recomputed
        # from the restored table and matches pre-kill immediately
        self._sw.reset_sketches()
        if not entries:
            return
        key_cols = []
        for c in range(len(self.group_exprs)):
            lst = [e[0][c] for e in entries]
            arr = np.asarray(lst)
            if arr.dtype.kind not in "ifbM":
                # strings (or mixed objects): rebuild from the ORIGINAL
                # values — np.asarray may have stringified them
                arr = np.empty(len(lst), dtype=object)
                arr[:] = lst
            key_cols.append(arr)
        gids = self._interner.intern(key_cols)
        T = self._table
        T.ensure_gids(self._interner.capacity)
        slots = T.alloc(len(entries))
        V = len(self._value_exprs)
        for i, entry in enumerate(entries):
            slot = int(slots[i])
            key_list, start, last, agg = entry[:4]
            acc_states = entry[4] if len(entry) > 4 else None
            T.start[slot] = start
            T.last[slot] = last
            T.row_count[slot] = agg["count"]
            T.counts[slot] = agg["counts"]
            T.sums[slot] = agg["sums"]
            T.mins[slot] = agg["mins"]
            T.maxs[slot] = agg["maxs"]
            T.means[slot] = agg.get("means", [0.0] * V)
            T.m2s[slot] = agg.get("m2s", [0.0] * V)
            T.gid[slot] = gids[i]
            T.live[slot] = True
            accs = self._make_accs()
            if accs is not None:
                if acc_states is not None:
                    for acc, st in zip(accs, acc_states):
                        acc.merge(st)
                T.accs[slot] = accs
        T.chain(gids.astype(np.int64), slots)

    def _snapshot(self, epoch: int) -> None:
        from denormalized_tpu.state.checkpoint import put_json

        coord, key = self._ckpt
        T = self._table
        live = T.live_slots()
        order = np.lexsort((T.gid[live], T.start[live]))
        live = live[order]
        key_cols = self._interner.keys_of(T.gid[live])
        sessions = []
        for i, s in enumerate(live.tolist()):
            sessions.append(
                [
                    [key_cols[c][i] for c in range(len(key_cols))],
                    int(T.start[s]),
                    int(T.last[s]),
                    {
                        "count": int(T.row_count[s]),
                        "counts": [int(x) for x in T.counts[s]],
                        "sums": [float(x) for x in T.sums[s]],
                        "mins": [float(x) for x in T.mins[s]],
                        "maxs": [float(x) for x in T.maxs[s]],
                        "means": [float(x) for x in T.means[s]],
                        "m2s": [float(x) for x in T.m2s[s]],
                    },
                    [acc.state() for acc in T.accs[s]]
                    if s in T.accs
                    else None,
                ]
            )
        snap = {
            "epoch": epoch, "watermark": self._watermark,
            "sessions": sessions,
        }
        if self._tier is not None and self._tier.any_spilled:
            # spilled + resident state commit under ONE epoch: block
            # payloads re-put (CRC-framed, manifest-listed) under
            # epoch-suffixed keys, referenced here by id
            snap["spill_blocks"] = self._tier.snapshot_refs(
                coord, key, epoch
            )
        put_json(coord, key, epoch, snap)

    def run(self) -> Iterator[StreamItem]:
        for item in self._doctor_input():
            if isinstance(item, RecordBatch):
                # materialized inside the timing bracket: the histogram
                # measures this operator's work, not downstream's
                t0 = time.perf_counter()
                out = list(self._process_batch(item))
                self._note_batch(t0, item.num_rows)
                yield from out
            elif isinstance(item, WatermarkHint):
                if item.kind == "partition":
                    self._src_watermarks = True
                    if item.is_announcement:
                        yield item  # pure mode announcement
                        continue
                yield from self._advance_and_close(item.ts_ms)
                # emissions stamp canonical ts with the session START:
                # forward clamped below every still-open session's start
                # AND below watermark - gap — the lateness rule accepts
                # out-of-order rows down to watermark - gap + 1, and such
                # a row can START (or merge a session down to) exactly
                # there, so that is the true output low bound
                live = self._table.live_slots()
                floor = (
                    self._watermark - self.gap_ms
                    if self._watermark is not None
                    else item.ts_ms
                )
                lows = [item.ts_ms, floor]
                if len(live):
                    lows.append(int(self._table.start[live].min()) - 1)
                if self._tier is not None:
                    tmin = self._tier.min_start()
                    if tmin is not None:
                        # spilled sessions are still open sessions: the
                        # forward promise must stay below their starts too
                        lows.append(tmin - 1)
                yield WatermarkHint(min(lows), kind=item.kind)
            elif isinstance(item, Marker):
                if self._ckpt is not None:
                    self._snapshot(item.epoch)
                yield item
            elif isinstance(item, EndOfStream):
                if self._tier is not None:
                    # the final flush emits EVERY open session, cold ones
                    # included
                    self._tier.reload_all()
                live = self._table.live_slots()
                if self.emit_on_close and len(live):
                    order = np.lexsort(
                        (self._table.gid[live], self._table.start[live])
                    )
                    yield self._emit_slots(live[order])
                yield EOS
                return
