"""Windowed aggregation with user-defined (Python) accumulators.

The reference evaluates Python UDAFs through its vendored datafusion-python
layer — each group's accumulator is a Python object called under the GIL
(py-denormalized python/denormalized/datafusion/udf.py).  That shape cannot
live on the TPU (arbitrary Python state), so this operator keeps the same
windowing semantics as :class:`StreamingWindowExec` (slide-index windows,
monotonic min-ts watermark, late-data drop) but maintains per-(window, group)
``Accumulator`` instances host-side.  Built-in aggregates mixed into the same
window() call still decompose into device components via the main exec; the
planner routes a window with ANY udaf here.
"""

from __future__ import annotations

import time
from typing import Iterator

import numpy as np

from denormalized_tpu.common.constants import (
    CANONICAL_TIMESTAMP_COLUMN,
    WINDOW_END_COLUMN,
    WINDOW_START_COLUMN,
)
from denormalized_tpu.common.record_batch import RecordBatch
from denormalized_tpu.common.schema import DataType, Field, Schema
from denormalized_tpu.logical.expr import AggregateExpr, Expr
from denormalized_tpu.logical.plan import WindowType
from denormalized_tpu.physical.base import (
    EOS,
    EndOfStream,
    ExecOperator,
    Marker,
    StreamItem,
    WatermarkHint,
)


class _BuiltinAcc:
    """numpy running aggregate for builtin kinds inside the UDAF exec.
    Variance keeps Welford/Chan moments (mean, M2) — stable at any value
    magnitude — merged via ``segment_agg.chan_merge``."""

    __slots__ = ("kind", "count", "sum", "mean", "m2", "min", "max")

    def __init__(self, kind: str):
        self.kind = kind
        self.count = 0
        self.sum = 0.0
        self.mean = 0.0
        self.m2 = 0.0
        self.min = np.inf
        self.max = -np.inf

    def update(self, v: np.ndarray):
        from denormalized_tpu.ops.segment_agg import VAR_KINDS, chan_merge

        self.count += len(v)
        if self.kind in ("sum", "avg") or self.kind in VAR_KINDS:
            self.sum += float(v.sum())
            if self.kind in VAR_KINDS and len(v):
                x = v.astype(np.float64)
                cm = float(x.mean())
                cm2 = float(((x - cm) ** 2).sum())
                n_prev = self.count - len(v)
                _, self.mean, self.m2 = chan_merge(
                    n_prev, self.mean, self.m2, len(v), cm, cm2
                )
        elif self.kind == "min" and len(v):
            self.min = min(self.min, float(v.min()))
        elif self.kind == "max" and len(v):
            self.max = max(self.max, float(v.max()))

    def evaluate(self):
        from denormalized_tpu.ops.segment_agg import VAR_KINDS, variance_from_m2

        if self.kind in VAR_KINDS:
            return float(variance_from_m2(self.kind, self.count, self.m2))
        return {
            "count": self.count,
            "sum": self.sum,
            "avg": self.sum / self.count if self.count else np.nan,
            "min": self.min if np.isfinite(self.min) else np.nan,
            "max": self.max if np.isfinite(self.max) else np.nan,
        }[self.kind]

    def state(self):
        return [
            self.count, self.sum, float(self.min), float(self.max),
            self.mean, self.m2,
        ]

    def merge(self, s):
        from denormalized_tpu.ops.segment_agg import chan_merge

        _, self.mean, self.m2 = chan_merge(
            self.count, self.mean, self.m2,
            s[0], s[4] if len(s) > 4 else 0.0, s[5] if len(s) > 5 else 0.0,
        )
        self.count += s[0]
        self.sum += s[1]
        self.min = min(self.min, s[2])
        self.max = max(self.max, s[3])


class _Spilled:
    """In-place marker for a frame group whose accumulators live in the
    cold tier.  The dict ENTRY stays (so reload restores the group at
    its original position and emission row order matches the
    all-resident run); only the heavy accumulator objects leave RAM."""

    __slots__ = ()

    def __repr__(self) -> str:  # pragma: no cover — debugging aid
        return "<spilled>"


SPILLED = _Spilled()


class _UdafTier:
    """Cold tier of one UDAF window operator: evicts the coldest gids'
    accumulator states (across every open window they appear in) to the
    LSM, leaving order-preserving markers in the frames; reloads when a
    batch touches the key or the window emits."""

    __slots__ = (
        "op", "node_id", "ctrl", "cold", "any_spilled", "spilled_bytes",
        "spilled_groups", "_block_of", "_blocks", "_next",
    )

    def __init__(self, op: "UdafWindowExec", node_id: str, ctrl) -> None:
        from denormalized_tpu.state import tiering

        self.op = op
        self.node_id = node_id
        self.ctrl = ctrl
        self.cold = tiering.ColdTracker()
        self.any_spilled = False
        self.spilled_bytes = 0
        self.spilled_groups = 0  # (window, gid) entries in the cold tier
        self._block_of = np.full(1024, -1, dtype=np.int64)
        self._blocks: dict[int, dict] = {}
        self._next = 0
        ctrl.register(node_id, op, self.resident_bytes)

    def resident_bytes(self) -> int:
        from denormalized_tpu.obs import statewatch as swm

        op = self.op
        # real accumulator sizes (state_nbytes where implemented —
        # unbounded collectors like exact median/count-distinct report
        # their TRUE growth, so spill pressure tracks reality instead
        # of a flat 512-byte estimate); list() copies because this may
        # run on another operator's thread while the udaf thread
        # inserts/pops frames (controller-summed)
        acc_bytes = 0
        try:
            for f in list(op._frames.values()):
                for accs in list(f.values()):
                    if accs is SPILLED:
                        continue
                    for acc in accs:
                        acc_bytes += swm.acc_nbytes(acc)
        except RuntimeError:
            # torn read mid-mutation: fall back to the flat estimate
            # for this sample — the next controller tick re-reads
            groups = sum(len(f) for f in list(op._frames.values()))
            acc_bytes = (
                (groups - self.spilled_groups)
                * max(len(op.aggr_exprs), 1)
                * swm.ACC_EST_BYTES
            )
        keys = len(op._interner) if op._interner is not None else 0
        return (
            acc_bytes
            + keys * swm.KEY_EST_BYTES
            + len(op._frames) * 64
        )

    def _ensure_maps(self, n: int) -> None:
        self.cold.ensure(n)
        cap = len(self._block_of)
        if n <= cap:
            return
        while cap < n:
            cap *= 2
        new = np.full(cap, -1, dtype=np.int64)
        new[: len(self._block_of)] = self._block_of
        self._block_of = new

    def _capacity(self) -> int:
        return len(self.op._interner) if self.op._interner is not None else 1

    # -- hot path ---------------------------------------------------------
    def touch_and_reload(self, gids: np.ndarray) -> None:
        self._ensure_maps(self._capacity())
        self.cold.touch(gids)
        if not self.any_spilled:
            return
        b = self._block_of[gids]
        hit = b[b >= 0]
        if len(hit) == 0:
            return
        for bid in np.unique(hit).tolist():
            self._reload_block(int(bid))
        self._write_manifest()

    def reload_gid(self, gid: int) -> None:
        """Defensive lazy reload for a marker encountered outside the
        batched touch path."""
        bid = int(self._block_of[gid]) if gid < len(self._block_of) else -1
        if bid >= 0:
            self._reload_block(bid)
            self._write_manifest()

    def reload_for_window(self, j: int) -> None:
        """Reload every block holding entries of window ``j`` before it
        emits — emission content and row order match the all-resident
        run exactly."""
        if not self.any_spilled:
            return
        due = [
            bid for bid, m in self._blocks.items() if j in m["windows"]
        ]
        for bid in due:
            self._reload_block(bid)
        if due:
            self._write_manifest()

    # -- eviction ---------------------------------------------------------
    def maybe_spill(self, protect_gids: np.ndarray) -> None:
        from denormalized_tpu.obs import statewatch as swm
        from denormalized_tpu.state import tiering

        need = self.ctrl.over_budget()
        if need <= 0:
            self.ctrl.relax(self.node_id)
            return
        op = self.op
        # live resident groups + REAL bytes per gid (slow path: spill
        # cadence only) — evicting by true size frees the budget in as
        # few blocks as possible when accumulator growth is skewed
        per_gid: dict[int, int] = {}
        per_gid_bytes: dict[int, int] = {}
        for frame in op._frames.values():
            for g, accs in frame.items():
                if accs is not SPILLED:
                    per_gid[g] = per_gid.get(g, 0) + 1
                    per_gid_bytes[g] = per_gid_bytes.get(g, 0) + sum(
                        swm.acc_nbytes(a) for a in accs
                    )
        self._ensure_maps(self._capacity())
        protect = np.zeros(len(self._block_of), dtype=bool)
        protect[protect_gids] = True
        cand = np.asarray(
            [g for g in per_gid if not protect[g]], dtype=np.int64
        )
        spilled_any = False
        if len(cand):
            cand = self.cold.order_cold(cand)
            counts = np.asarray([per_gid[int(g)] for g in cand])
            csum = np.cumsum(
                np.asarray([per_gid_bytes[int(g)] for g in cand])
            )
            k = int(np.searchsorted(csum, need)) + 1
            k = min(k, len(cand))
            # chunk into blocks of <= SPILL_BLOCK_SLOTS entries
            from denormalized_tpu.common.errors import StateError
            from denormalized_tpu.runtime.tracing import logger

            start = 0
            acc = 0
            for i in range(k):
                acc += int(counts[i])
                if acc >= tiering.SPILL_BLOCK_SLOTS or i == k - 1:
                    try:
                        self._spill_chunk(cand[start : i + 1])
                    except StateError as e:
                        # failed eviction put: accumulators stay
                        # resident; degrade to backpressure, never kill
                        # the query over a spill write
                        logger.warning(
                            "spill: udaf eviction put failed (%s) — "
                            "chunk stays resident", e,
                        )
                        break
                    spilled_any = True
                    start, acc = i + 1, 0
        if spilled_any:
            self._write_manifest()
            op._state_info_cache = None
        self.ctrl.check_pressure(self.node_id)

    def _spill_chunk(self, gids_chunk: np.ndarray) -> None:
        from denormalized_tpu.state.checkpoint import jsonable
        from denormalized_tpu.state.serialization import pack_snapshot

        op = self.op
        chunk_set = set(int(g) for g in gids_chunk)
        entries: dict[str, list] = {}
        to_mark: list[tuple[dict, int]] = []
        windows: set[int] = set()
        n_groups = 0
        for j, frame in op._frames.items():
            row = []
            for g in frame:
                if int(g) in chunk_set and frame[g] is not SPILLED:
                    row.append(
                        [int(g), [acc.state() for acc in frame[g]]]
                    )
                    to_mark.append((frame, int(g)))
            if row:
                entries[str(j)] = row
                windows.add(int(j))
                n_groups += len(row)
        if n_groups == 0:
            return
        if op._interner is not None:
            keys = op._interner.keys_of(
                np.asarray(gids_chunk, dtype=np.int64)
            )
            keys_meta = jsonable([list(c) for c in keys])
        else:
            keys_meta = None
        # entries reference gids by CHUNK POSITION so a restore (fresh
        # gid space) maps them through the re-interned keys
        pos = {int(g): i for i, g in enumerate(gids_chunk)}
        for row in entries.values():
            for e in row:
                e[0] = pos[e[0]]
        meta = {
            "keys": keys_meta,
            "entries": jsonable(entries),
            "windows": sorted(windows),
            "groups": n_groups,
        }
        bid = self._next
        blob = pack_snapshot(meta, {})
        # durable FIRST: the accumulators are only marker-replaced once
        # their states are safely in the LSM
        nbytes = self.ctrl.put_block(self.node_id, f"b{bid}", blob)
        self._next += 1
        for frame, g in to_mark:
            frame[g] = SPILLED
        self._block_of[gids_chunk] = bid
        self._blocks[bid] = {
            "gids": np.asarray(gids_chunk, dtype=np.int64).copy(),
            "windows": windows,
            "bytes": nbytes,
            "groups": n_groups,
        }
        self.any_spilled = True
        self.spilled_bytes += nbytes
        self.spilled_groups += n_groups
        self.ctrl.note_spill(self.node_id, 1, nbytes)

    # -- reload -----------------------------------------------------------
    def _reload_block(self, bid: int) -> None:
        from denormalized_tpu.state import tiering
        from denormalized_tpu.state.serialization import unpack_snapshot

        meta = self._blocks.pop(bid)
        op = self.op
        raw = self.ctrl.get_block(self.node_id, f"b{bid}")
        bmeta, _arrays = unpack_snapshot(raw)
        if bmeta["keys"] is not None and op._interner is not None:
            key_cols = tiering.key_columns_from_meta(bmeta["keys"])
            chunk_gids = op._interner.intern(key_cols).astype(np.int64)
        else:
            chunk_gids = np.zeros(1, dtype=np.int64)
        self._ensure_maps(self._capacity())
        for j_str, row in bmeta["entries"].items():
            frame = op._frames.setdefault(int(j_str), {})
            for posi, states in row:
                gid = int(chunk_gids[int(posi)])
                accs = op._make_accs()
                for acc, st in zip(accs, states):
                    acc.merge(st)
                # marker replaced IN PLACE: dict order (and therefore
                # emission row order) is exactly the all-resident run's
                frame[gid] = accs
        self._block_of[meta["gids"]] = -1
        self._block_of[chunk_gids] = -1  # restore path: fresh gid space
        self.any_spilled = bool(self._blocks)
        self.spilled_bytes -= meta["bytes"]
        self.spilled_groups -= meta["groups"]
        self.ctrl.note_reload(self.node_id, 1, len(raw))
        self.ctrl.delete_block(self.node_id, f"b{bid}")
        op._state_info_cache = None

    def _write_manifest(self) -> None:
        self.ctrl.write_manifest(
            self.node_id, [f"b{b}" for b in self._blocks]
        )

    def info(self) -> dict:
        return {
            "spilled_bytes": self.spilled_bytes,
            "spilled_keys": self.spilled_groups,
            "spilled_blocks": len(self._blocks),
            "spill": self.ctrl.spill_stats(self.node_id),
        }

    # -- checkpoint integration -------------------------------------------
    def snapshot_refs(self, coord, key: str, epoch: int) -> list[int]:
        bids = sorted(self._blocks)
        for bid in bids:
            self.ctrl.copy_block_to_epoch(
                coord, key, epoch, self.node_id, f"b{bid}"
            )
        return bids

    def restore_refs(self, coord, key: str, bids: list[int]) -> None:
        from denormalized_tpu.state import tiering
        from denormalized_tpu.state.serialization import unpack_snapshot

        op = self.op
        for bid in bids:
            raw = self.ctrl.restore_block_from_epoch(
                coord, key, self.node_id, f"b{bid}"
            )
            bmeta, _arrays = unpack_snapshot(raw)
            if bmeta["keys"] is not None and op._interner is not None:
                key_cols = tiering.key_columns_from_meta(bmeta["keys"])
                chunk_gids = op._interner.intern(key_cols).astype(
                    np.int64
                )
            else:
                chunk_gids = np.zeros(1, dtype=np.int64)
            self._ensure_maps(self._capacity())
            windows: set[int] = set()
            groups = 0
            for j_str, row in bmeta["entries"].items():
                frame = op._frames.setdefault(int(j_str), {})
                windows.add(int(j_str))
                for posi, _states in row:
                    frame[int(chunk_gids[int(posi)])] = SPILLED
                    groups += 1
            self._block_of[chunk_gids] = bid
            self._blocks[bid] = {
                "gids": chunk_gids.copy(),
                "windows": windows,
                "bytes": len(raw),
                "groups": groups,
            }
            self.spilled_bytes += len(raw)
            self.spilled_groups += groups
            self._next = max(self._next, bid + 1)
        self.any_spilled = bool(self._blocks)
        self._write_manifest()


class UdafWindowExec(ExecOperator):
    def __init__(
        self,
        input_op: ExecOperator,
        group_exprs: list[Expr],
        aggr_exprs: list[AggregateExpr],
        window_type: WindowType,
        length_ms: int,
        slide_ms: int | None,
        *,
        emit_on_close: bool = True,
        name: str = "udaf_window",
    ) -> None:
        if window_type is WindowType.SESSION:
            from denormalized_tpu.common.errors import PlanError

            raise PlanError(
                "session windows route to SessionWindowExec (which handles "
                "accumulator aggregates directly)"
            )
        self.input_op = input_op
        self.group_exprs = list(group_exprs)
        self.aggr_exprs = list(aggr_exprs)
        self.window_type = window_type
        self.length_ms = int(length_ms)
        self.slide_ms = int(slide_ms) if slide_ms else self.length_ms
        self.emit_on_close = emit_on_close
        self.name = name
        self._k = -(-self.length_ms // self.slide_ms)

        in_schema = input_op.schema
        fields = [g.out_field(in_schema) for g in self.group_exprs]
        fields += [a.out_field(in_schema) for a in self.aggr_exprs]
        fields += [
            Field(WINDOW_START_COLUMN, DataType.TIMESTAMP_MS, nullable=False),
            Field(WINDOW_END_COLUMN, DataType.TIMESTAMP_MS, nullable=False),
            Field(CANONICAL_TIMESTAMP_COLUMN, DataType.TIMESTAMP_MS, nullable=False),
        ]
        self.schema = Schema(fields)

        # frames: window index j -> { dense group id -> [acc per agg] }.
        # Keys intern through a GroupInterner (the same machinery the device
        # window uses) so per-batch grouping is one lexsort over int arrays
        # instead of per-row Python tuple comparisons; checkpoints store the
        # actual key VALUES (stable across restarts), re-interned on restore.
        from denormalized_tpu.ops.interner import GroupInterner

        self._interner = (
            GroupInterner(len(self.group_exprs)) if self.group_exprs else None
        )
        self._frames: dict[int, dict[int, list]] = {}
        self._ckpt: tuple | None = None
        # cold tier (state/tiering.py): set by enable_spill
        self._tier: _UdafTier | None = None
        self._first_open: int | None = None
        self._max_win_seen = -1
        self._watermark: int | None = None
        # True once a kind="partition" hint arrived: batch min-ts no
        # longer advances the watermark (replay-skew safety)
        self._src_watermarks = False
        self._metrics = {"rows_in": 0, "windows_emitted": 0, "late_rows": 0}
        from denormalized_tpu import obs
        from denormalized_tpu.obs import statewatch

        self.bind_obs("udaf")
        # state observatory sketches, fed dense gids per batch
        self._sw = statewatch.make_watch("udaf")
        self._obs_late = obs.counter("dnz_late_rows_total", op="udaf")
        self._obs_windows = obs.counter(
            "dnz_windows_emitted_total", op="udaf"
        )
        self._obs_emit_lag = obs.histogram(
            "dnz_emit_event_lag_ms", op="udaf"
        )
        self._obs_wm_lag = obs.gauge("dnz_watermark_lag_ms", op="udaf")
        self._obs_wm_lag_hist = obs.histogram(
            "dnz_watermark_lag_hist_ms", op="udaf"
        )

    @property
    def children(self):
        return [self.input_op]

    def metrics(self):
        return dict(self._metrics)

    def _label(self):
        return f"UdafWindowExec({self.window_type.value} {self.length_ms}ms)"

    # -- cold tier (state/tiering.py) -----------------------------------
    def enable_spill(self, node_id: str, controller) -> None:
        self._tier = _UdafTier(self, node_id, controller)

    # -- state observatory (obs/statewatch.py) --------------------------
    def state_info(self) -> dict:
        from denormalized_tpu.obs import statewatch as swm

        frames = self._frames
        groups_total = 0
        acc_bytes = 0
        live_gids: set[int] = set()
        for f in list(frames.values()):
            # spilled markers keep their dict entries but their
            # accumulators live in the LSM — resident accounting skips
            # them (reported separately as spilled_keys/bytes)
            for g, accs in list(f.items()):
                if accs is SPILLED:
                    continue
                groups_total += 1
                live_gids.add(g)
                for acc in accs:
                    acc_bytes += swm.acc_nbytes(acc)
        n_aggs = len(self.aggr_exprs)
        live_keys = len(live_gids)
        acc_objs = groups_total * n_aggs
        oldest = (
            self._first_open * self.slide_ms
            if self._first_open is not None and frames
            else None
        )
        wm = self._watermark
        info = {
            "op": "udaf",
            # frames hold opaque Python accumulators: counts are exact,
            # bytes come from each accumulator's own state_nbytes()
            # (restore-invariant, derived from element counts — see
            # docs/observability.md); accumulators without one fall
            # back to the documented flat estimate.  Unbounded exact
            # collectors (median, count_distinct) therefore report
            # REAL growth — the doctor's state verdicts and the spill
            # controller's pressure act on it.
            "state_bytes": (
                acc_bytes
                + live_keys * swm.KEY_EST_BYTES
                + len(frames) * 64
            ),
            "live_keys": live_keys,
            "slot_capacity": groups_total,
            "slot_live": groups_total,
            "open_windows": len(frames),
            "acc_objects": acc_objs,
            "retention_unit_ms": self.length_ms,
            "oldest_event_ms": oldest,
            "watermark_ms": wm,
        }
        if self._interner is not None:
            info["interner_keys_total"] = len(self._interner)
        if wm is not None and oldest is not None:
            info["oldest_event_lag_ms"] = max(0, int(wm) - int(oldest))
        if self._tier is not None:
            info.update(self._tier.info())
        return info

    def _state_watch_views(self):
        if not self._sw:
            return []
        if self._interner is None:
            return [(None, self._sw, None)]
        from denormalized_tpu.ops.interner import display_keys

        return [
            (None, self._sw, lambda g: display_keys(self._interner, g))
        ]

    def _make_accs(self) -> list:
        accs = []
        for a in self.aggr_exprs:
            if a.kind == "udaf":
                accs.append(a.udaf.make())
            else:
                accs.append(_BuiltinAcc(a.kind))
        return accs

    def _process_batch(self, batch: RecordBatch) -> Iterator[RecordBatch]:
        n = batch.num_rows
        if n == 0:
            return
        self._metrics["rows_in"] += n
        self._obs_rows_in.add(n)
        S = self.slide_ms
        ts = np.asarray(batch.column(CANONICAL_TIMESTAMP_COLUMN), dtype=np.int64)
        units = ts // S
        anchor = int(units.min()) - self._k + 1
        if self._first_open is None:
            self._first_open = anchor
        elif self._src_watermarks and anchor < self._first_open:
            # per-partition watermarks: a slower partition's earlier
            # windows stay legitimate until the min-driven watermark
            # closes them (frames are host dicts keyed by absolute
            # window index, so lowering the cursor just re-admits them);
            # triggers advance first_open exactly to the wm floor, so
            # anything below it was genuinely closed and stays late
            from denormalized_tpu.physical.window_exec import (
                watermark_floor,
            )

            wm_floor = (
                watermark_floor(self._watermark, self.length_ms, self.slide_ms)
                if self._watermark is not None
                else anchor
            )
            self._first_open = max(anchor, int(wm_floor))
        self._max_win_seen = max(self._max_win_seen, int(units.max()))

        if self._interner is not None:
            # raw dtypes (same calling convention as the device window):
            # numeric/bool keys take the interner's exact-value path —
            # forcing object would str()-normalize them (False → 'True'
            # on emission re-cast)
            from denormalized_tpu.common.columns import as_key_column

            gids = self._interner.intern(
                [as_key_column(g.eval(batch)) for g in self.group_exprs]
            ).astype(np.int64)
        else:
            gids = np.zeros(n, dtype=np.int64)
        self._sw.update(gids)
        if self._tier is not None:
            # membership pre-probe + reload-on-touch BEFORE the frame
            # loop: touched markers come back resident
            self._tier.touch_and_reload(gids)
        from denormalized_tpu.logical.expr import column_validity

        def mask_of(e) -> np.ndarray | None:
            return column_validity(e, batch)

        arg_cols: list[list[np.ndarray]] = []
        arg_masks: list[np.ndarray | None] = []
        for a in self.aggr_exprs:
            if a.kind == "udaf":
                arg_cols.append([np.asarray(e.eval(batch)) for e in a.udaf.args])
                arg_masks.append(mask_of(a.udaf.args[0]) if a.udaf.args else None)
            elif a.arg is not None:
                arg_cols.append([np.asarray(a.arg.eval(batch), dtype=np.float64)])
                arg_masks.append(mask_of(a.arg))
            else:
                arg_cols.append([np.zeros(n)])
                arg_masks.append(None)

        # group rows by (window fan-out, dense gid): one lexsort per
        # fan-out step, runs found by boundary diff — no per-row Python
        for i in range(self._k):
            win = units - i
            in_window = (win >= self._first_open) & (
                (ts - win * S) < self.length_ms
            )
            late = (win < self._first_open) & ((ts - win * S) < self.length_ms)
            if i == 0:
                n_late = int(late.sum())
                self._metrics["late_rows"] += n_late
                if n_late:
                    self._obs_late.add(n_late)
            idx = np.nonzero(in_window)[0]
            if len(idx) == 0:
                continue
            wsel = win[idx]
            gsel = gids[idx]
            order = np.lexsort((gsel, wsel))
            ws = wsel[order]
            gs = gsel[order]
            m = len(order)
            bounds = np.nonzero(
                np.concatenate(
                    ([True], (ws[1:] != ws[:-1]) | (gs[1:] != gs[:-1]))
                )
            )[0]
            ends = np.append(bounds[1:], m)
            for b0, b1 in zip(bounds, ends):
                rows = idx[order[b0:b1]]
                j = int(ws[b0])
                gid = int(gs[b0])
                frame = self._frames.setdefault(j, {})
                accs = frame.get(gid)
                if accs is SPILLED:
                    # defensive: touch-time reload covers every batch
                    # gid; a marker here means the block map missed it
                    self._tier.reload_gid(gid)
                    accs = frame.get(gid)
                if accs is None:
                    accs = self._make_accs()
                    frame[gid] = accs
                for a, acc, cols, am in zip(
                    self.aggr_exprs, accs, arg_cols, arg_masks
                ):
                    chunk = [c[rows] for c in cols]
                    if am is not None:
                        valid = am[rows]
                        chunk = [c[valid] for c in chunk]
                    if a.kind == "udaf":
                        acc.update(*chunk)
                    else:
                        acc.update(chunk[0])

        if not self._src_watermarks:
            bmin = int(ts.min())
            if self._watermark is None or bmin > self._watermark:
                self._watermark = bmin
        yield from self._trigger()
        if self._tier is not None:
            self._tier.maybe_spill(gids)

    def _trigger(self) -> Iterator[RecordBatch]:
        if self._watermark is None or self._first_open is None:
            return
        if self._obs_wm_lag:
            lag = time.time() * 1000.0 - self._watermark
            self._obs_wm_lag.set(lag)
            self._obs_wm_lag_hist.observe(lag)
        while self._first_open * self.slide_ms + self.length_ms <= self._watermark:
            b = self._emit(self._first_open)
            self._first_open += 1
            if b is not None:
                yield b
        self._maybe_reintern()

    # re-keying threshold (tests lower it to force the path)
    _reintern_min = 262_144

    def _maybe_reintern(self) -> None:
        """Frames free their accumulators when windows emit, but the
        interner only ever grows — re-key from the LIVE groups when
        distinct-keys-ever-seen dwarfs them, so host memory follows open
        windows, not stream lifetime (same policy as the join)."""
        if self._interner is None:
            return
        if self._tier is not None and self._tier.any_spilled:
            # re-keying would strand the blocks' gid maps; deferred
            # until the cold set drains (emission drains it steadily)
            return
        # cheap threshold first: don't build the live set (O(open groups))
        # on every trigger just to no-op
        if len(self._interner) <= self._reintern_min:
            return
        live: set[int] = set()
        for frame in self._frames.values():
            live.update(frame.keys())
        if len(self._interner) <= 4 * max(len(live), 1):
            return
        from denormalized_tpu.ops.interner import GroupInterner

        # the gid space is about to reset: sketch entries name dead ids
        # after this — restart and re-warm (docs/observability.md)
        self._sw.reset_sketches()
        old = self._interner
        new = GroupInterner(len(self.group_exprs))
        gids_sorted = sorted(live)
        if gids_sorted:
            key_arrays = old.keys_of(np.asarray(gids_sorted, dtype=np.int64))
            in_schema = self.input_op.schema
            cols = []
            for g, arr in zip(self.group_exprs, key_arrays):
                f = g.out_field(in_schema)
                # keys_of yields object arrays; restore the column's real
                # dtype so numeric keys re-enter the exact-value path
                cols.append(
                    np.asarray(arr.tolist(), dtype=f.dtype.to_numpy())
                    if f.dtype.is_numeric
                    else arr
                )
            new_gids = new.intern(cols)
            remap = dict(zip(gids_sorted, (int(x) for x in new_gids)))
            self._frames = {
                j: {remap[g]: accs for g, accs in fr.items()}
                for j, fr in self._frames.items()
            }
        self._interner = new

    def _emit(self, j: int) -> RecordBatch | None:
        if self._tier is not None:
            # any block holding entries of this window reloads first —
            # markers resolve in place, emission order is preserved
            self._tier.reload_for_window(j)
        frame = self._frames.pop(j, None)
        if not frame:
            return None
        self._metrics["windows_emitted"] += 1
        self._obs_windows.add(1)
        if self._obs_emit_lag:
            self._obs_emit_lag.observe(
                time.time() * 1000.0 - (j * self.slide_ms + self.length_ms)
            )
        if self._dr_lineage is not None:
            self._dr_lineage.emitted(
                self._dr_node_id,
                j * self.slide_ms,
                j * self.slide_ms + self.length_ms,
            )
        m = len(frame)
        items = list(frame.items())
        cols: list[np.ndarray] = []
        in_schema = self.input_op.schema
        if self.group_exprs:
            key_arrays = self._interner.keys_of(
                np.asarray([g for g, _ in items], dtype=np.int64)
            )
            for g, vals in zip(self.group_exprs, key_arrays):
                f = g.out_field(in_schema)
                if f.dtype.is_numeric:
                    vals = np.asarray(vals.tolist(), dtype=f.dtype.to_numpy())
                cols.append(vals)
        for ai, a in enumerate(self.aggr_exprs):
            f = a.out_field(in_schema)
            vals = [accs[ai].evaluate() for _, accs in items]
            # element-wise fill: np.array(list_of_lists, dtype=object) would
            # build a 2-D array when every list has the same length
            arr = np.empty(len(vals), dtype=object)
            for vi, v in enumerate(vals):
                arr[vi] = v
            if f.dtype.is_numeric:
                arr = arr.astype(f.dtype.to_numpy())
            cols.append(arr)
        start = np.full(m, j * self.slide_ms, dtype=np.int64)
        end = np.full(m, j * self.slide_ms + self.length_ms, dtype=np.int64)
        cols += [start, end, start.copy()]
        return RecordBatch(self.schema, cols)

    # -- checkpointing: accumulator state() lists, the capability the
    # reference prototypes in SerializableAccumulator
    # (accumulators/serializable_accumulator.rs:10-68) ------------------
    def enable_checkpointing(self, node_id: str, coord, orch) -> None:
        from denormalized_tpu.state.checkpoint import get_json

        self._ckpt = (coord, f"udafwin_{node_id}")
        snap = get_json(coord, self._ckpt[1])
        if snap is None:
            return
        self._first_open = snap["first_open"]
        self._max_win_seen = snap["max_win_seen"]
        self._watermark = snap["watermark"]
        self._frames = {}
        for j_str, groups in snap["frames"].items():
            frame: dict[int, list] = {}
            for key_list, states in groups:
                if self._interner is not None:
                    gid = int(
                        self._interner.intern(
                            [np.asarray([v]) for v in key_list]
                        )[0]
                    )
                else:
                    gid = 0
                if states is None:
                    # spilled-at-the-cut group: seed the marker at its
                    # recorded position (the tier restore / resident
                    # degrade below overwrites it IN PLACE, so emission
                    # row order matches the uninterrupted run)
                    frame[gid] = SPILLED
                    continue
                accs = self._make_accs()
                for acc, st in zip(accs, states):
                    acc.merge(st)
                frame[gid] = accs
            self._frames[int(j_str)] = frame
        bids = snap.get("spill_blocks") or []
        if bids:
            if self._tier is not None:
                self._tier.restore_refs(coord, self._ckpt[1], bids)
            else:
                self._restore_spilled_resident(coord, self._ckpt[1], bids)

    def _restore_spilled_resident(self, coord, key: str, bids: list) -> None:
        """Budget removed since the checkpoint: the cold tier's blocks
        load back resident."""
        from denormalized_tpu.common.errors import StateError
        from denormalized_tpu.state import tiering
        from denormalized_tpu.state.serialization import unpack_snapshot

        for bid in bids:
            raw = coord.get_snapshot(f"{key}:spill:b{bid}")
            if raw is None:
                raise StateError(
                    f"checkpoint references spilled UDAF block b{bid} "
                    "but the epoch holds no such snapshot"
                )
            bmeta, _arrays = unpack_snapshot(raw)
            if bmeta["keys"] is not None and self._interner is not None:
                key_cols = tiering.key_columns_from_meta(bmeta["keys"])
                chunk_gids = self._interner.intern(key_cols).astype(
                    np.int64
                )
            else:
                chunk_gids = np.zeros(1, dtype=np.int64)
            for j_str, row in bmeta["entries"].items():
                frame = self._frames.setdefault(int(j_str), {})
                for posi, states in row:
                    accs = self._make_accs()
                    for acc, st in zip(accs, states):
                        acc.merge(st)
                    frame[int(chunk_gids[int(posi)])] = accs

    def _snapshot(self, epoch: int) -> None:
        # put_json's `jsonable` recursively converts numpy scalars/arrays in
        # both keys and user accumulator state() payloads
        from denormalized_tpu.state.checkpoint import put_json

        coord, key = self._ckpt

        # frames persist key VALUES (stable across restarts), not gids —
        # a restored process re-interns them.  Reverse lookups are batched
        # per frame (one keys_of call), not per group.
        frames = {}
        for j, frame in self._frames.items():
            # dict order IS emission row order, so the snapshot records
            # every group IN POSITION: spilled markers persist as
            # states=None placeholders (their accumulator states are
            # committed under this SAME epoch as referenced blocks, and
            # restore re-marks/overwrites them at the recorded position)
            gids = list(frame.keys())
            if self._interner is not None and gids:
                key_arrays = self._interner.keys_of(
                    np.asarray(gids, dtype=np.int64)
                )
                keys_per_gid = [
                    [col[i] for col in key_arrays] for i in range(len(gids))
                ]
            else:
                keys_per_gid = [[] for _ in gids]
            frames[str(j)] = [
                [
                    kv,
                    None if frame[g] is SPILLED
                    else [acc.state() for acc in frame[g]],
                ]
                for g, kv in zip(gids, keys_per_gid)
            ]
        snap = {
            "epoch": epoch,
            "first_open": self._first_open,
            "max_win_seen": self._max_win_seen,
            "watermark": self._watermark,
            "frames": frames,
        }
        if self._tier is not None and self._tier.any_spilled:
            snap["spill_blocks"] = self._tier.snapshot_refs(
                coord, key, epoch
            )
        put_json(coord, key, epoch, snap)

    def run(self) -> Iterator[StreamItem]:
        for item in self._doctor_input():
            if isinstance(item, RecordBatch):
                # materialized inside the timing bracket: the histogram
                # measures this operator's work, not downstream's
                t0 = time.perf_counter()
                out = list(self._process_batch(item))
                self._note_batch(t0, item.num_rows)
                yield from out
            elif isinstance(item, WatermarkHint):
                if item.kind == "partition":
                    self._src_watermarks = True
                    if item.is_announcement:
                        yield item  # pure mode announcement
                        continue
                if self._watermark is None or item.ts_ms > self._watermark:
                    self._watermark = item.ts_ms
                    yield from self._trigger()
                # emissions stamp canonical ts with the window START:
                # forward clamped below the lowest still-emittable start
                # (open frames, or the earliest window a future row could
                # land in) so downstream never late-drops our output
                from denormalized_tpu.physical.window_exec import (
                    window_output_low_watermark,
                )

                low = window_output_low_watermark(
                    self._first_open, self.slide_ms, self.length_ms,
                    item.ts_ms,
                    wm_ms=self._watermark if self._src_watermarks else None,
                )
                yield WatermarkHint(min(item.ts_ms, low), kind=item.kind)
            elif isinstance(item, Marker):
                if self._ckpt is not None:
                    self._snapshot(item.epoch)
                yield item
            elif isinstance(item, EndOfStream):
                if self.emit_on_close and self._first_open is not None:
                    for j in range(self._first_open, self._max_win_seen + 1):
                        b = self._emit(j)
                        if b is not None:
                            yield b
                    self._first_open = self._max_win_seen + 1
                yield EOS
                return
