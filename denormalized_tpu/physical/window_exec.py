"""Streaming windowed-aggregation operator.

The TPU re-design of the reference's ``StreamingWindowExec`` + its three
stream implementations (``WindowAggStream`` ungrouped-partial,
``FullWindowAggStream`` final, ``GroupedWindowAggStream`` grouped —
streaming_window.rs:421-482, grouped_window_agg_stream.rs).  One operator
covers grouped and ungrouped: ungrouped is the G=1 degenerate case, and the
partial/final split (a cross-CPU-partition merge in the reference) becomes a
cross-device ``psum`` in the sharded variant (see
:mod:`denormalized_tpu.parallel`), not a separate operator pair.

Per input batch (host side, all vectorized):
1. evaluate group-key and value expressions;
2. intern keys → dense int32 group ids (:class:`GroupInterner`);
3. compute each row's slide-index and rebase against ``first_open``;
4. pad to a power-of-two bucket and dispatch the jitted device step
   (async — the host immediately continues decoding the next batch);
5. advance the watermark (monotonic min-timestamp, mirroring
   ``process_watermark`` at streaming_window.rs:732-744) and emit every
   window whose end ≤ watermark: fetch that ring slot's G-sized accumulator
   rows to host, finalize, reset the slot.

Capacity is elastic by recompilation: group capacity G and ring size W double
when the interner or the event-time skew outgrow them (bucketed static shapes
— the XLA-friendly answer to the reference's unbounded BTreeMap of frames).
"""

from __future__ import annotations

import time
from typing import Iterator

import jax
import jax.numpy as jnp
import numpy as np

from denormalized_tpu.common.constants import (
    CANONICAL_TIMESTAMP_COLUMN,
    WINDOW_END_COLUMN,
    WINDOW_START_COLUMN,
)
from denormalized_tpu.common.errors import PlanError
from denormalized_tpu.common.record_batch import RecordBatch
from denormalized_tpu.common.schema import DataType, Field, Schema
from denormalized_tpu.logical.expr import AggregateExpr, Expr
from denormalized_tpu.logical.plan import WindowType
from denormalized_tpu.ops import segment_agg as sa
from denormalized_tpu.ops.interner import GroupInterner
from denormalized_tpu.physical.base import (
    EOS,
    WM_ANNOUNCE,
    EndOfStream,
    ExecOperator,
    Marker,
    StreamItem,
    WatermarkHint,
)


def _next_pow2(n: int) -> int:
    return 1 << max(0, (n - 1)).bit_length()


def _round_capacity(g: int, n_dev: int) -> int:
    """Round a group capacity up so every device shard is a multiple of 128
    lanes (and the total divides evenly over the mesh)."""
    unit = 128 * n_dev
    return -(-g // unit) * unit


def watermark_floor(wm_ms: int, length_ms: int, slide_ms: int) -> int:
    """First slide index NOT closed by watermark ``wm_ms`` — the exact
    point triggers advance ``first_open`` to, the floor the per-partition
    rebase may lower it back to, and the basis of ``_closable``.  One
    definition for all three so the trigger/rebase parity invariant is
    enforced by code, not comments."""
    return (wm_ms - length_ms) // slide_ms + 1


def window_output_low_watermark(
    first_open: int | None, slide_ms: int, length_ms: int, hint_ts: int,
    wm_ms: int | None = None,
) -> int:
    """Strict lower bound (minus one) on the start of any window a
    slide/length windowed operator can still emit, given no further input
    rows at or before ``hint_ts``.  With open windows that is the first
    open slot's start; with none, the earliest window a future row
    (> hint_ts) could land in.  Shared by StreamingWindowExec and
    UdafWindowExec — the forwarded WatermarkHint clamp must stay
    identical in both.

    Under per-partition watermarks ``first_open`` is NOT monotone: a
    slower partition's earlier windows may rebase it down to the
    watermark floor later, so the promise must already account for that
    — pass ``wm_ms`` and the bound uses min(first_open, floor)."""
    if first_open is not None:
        low_first = first_open
        if wm_ms is not None:
            low_first = min(
                low_first, watermark_floor(wm_ms, length_ms, slide_ms)
            )
        return low_first * slide_ms - 1
    min_future_start = ((hint_ts + 1 - length_ms) // slide_ms + 1) * slide_ms
    return min_future_start - 1


class _WindowTier:
    """Cold tier of one device-ring window operator: spills the OLDEST
    contiguous prefix of open-but-not-closable window slots (the
    window-frame spilling of the PAPERS.md spilling design — watermark-
    deferred frames whose rows have stopped arriving) out of the device
    ring into the LSM, then advances ``first_open`` past them so the
    ring stops reserving capacity for the skew span.  A spilled window

    - emits straight from its stored component planes when the
      watermark closes it (same finalize as the ring path);
    - reloads into the ring — lowering ``first_open`` back, exactly the
      per-partition rebase machinery — when a late-ish batch lands rows
      in it (touch), so drop semantics match the all-resident run;
    - rides checkpoints as an epoch-referenced block like every tier.

    Memory wins two ways: a spilled prefix stops ``_ensure_capacity``
    growing W for event-time skew, and when the resident span shrinks
    far enough the ring rebuilds at a smaller W (true allocation
    shrink)."""

    __slots__ = (
        "op", "node_id", "ctrl", "any_spilled", "spilled_bytes",
        "_blocks", "_next",
    )

    def __init__(self, op: "StreamingWindowExec", node_id: str, ctrl) -> None:
        self.op = op
        self.node_id = node_id
        self.ctrl = ctrl
        self.any_spilled = False
        self.spilled_bytes = 0
        self._blocks: dict[int, dict] = {}  # window index -> meta
        self._next = 0
        ctrl.register(node_id, op, self.resident_bytes)

    def resident_bytes(self) -> int:
        from denormalized_tpu.obs import statewatch as swm

        op = self.op
        spec = op._spec
        try:
            itemsize = int(np.dtype(spec.accum_dtype).itemsize)
        except TypeError:
            itemsize = 4
        keys = len(op._interner) if op._interner is not None else 1
        return (
            len(spec.components)
            * spec.window_slots
            * spec.group_capacity
            * itemsize
            + keys * swm.KEY_EST_BYTES
        )

    # -- touch / reload ---------------------------------------------------
    def touch_and_reload(self, lo_win: int, hi_win: int) -> None:
        """Reload every spilled window the incoming batch's rows can
        land in (windows [lo_win, hi_win]) BEFORE the operator computes
        win_rel — otherwise those rows would read as late and drop."""
        if not self.any_spilled:
            return
        due = sorted(j for j in self._blocks if lo_win <= j <= hi_win)
        if not due:
            return
        # INVARIANT: every spilled window stays strictly below
        # first_open.  Reloading lowers first_open to the lowest touched
        # window, so every spilled window ABOVE it must come back too —
        # left spilled, the ring's emission loop would reach its reset
        # slot and emit nothing where the all-resident run emits a window
        lo = due[0]
        due = sorted(j for j in self._blocks if j >= lo)
        self._reload(due)
        self._write_manifest()

    def _reload(self, js: list[int]) -> None:
        from denormalized_tpu.state.serialization import unpack_snapshot

        op = self.op
        op._flush()
        new_first = min(js)
        # ring capacity must cover [new_first, max_win_seen] BEFORE the
        # base lowers (the _grow-before-rebase aliasing rule the
        # per-partition watermark path documents)
        op._ensure_capacity(op._max_win_seen - new_first)
        op._first_open = new_first
        # export may hand back read-only device views — copy to mutate
        host = {
            label: np.array(buf) for label, buf in op._backend.export().items()
        }
        W = op._spec.window_slots
        for j in js:
            meta = self._blocks.pop(j)
            raw = self.ctrl.get_block(self.node_id, meta["id"])
            _bmeta, arrays = unpack_snapshot(raw)
            slot = j % W
            for label, arr in arrays.items():
                g = arr.shape[0]
                host[label][slot, :g] = arr
            self.spilled_bytes -= meta["bytes"]
            self.ctrl.note_reload(self.node_id, 1, len(raw))
            self.ctrl.delete_block(self.node_id, meta["id"])
        op._backend.import_(host)
        self.any_spilled = bool(self._blocks)
        op._state_info_cache = None

    # -- eviction ---------------------------------------------------------
    def maybe_spill(self, hot_lo_win: int) -> None:
        """Spill the prefix [first_open, min(hot_lo_win, …)) when over
        budget — the windows old enough that the current batch no longer
        feeds them.  Runs AFTER the trigger, so closable windows have
        already emitted and the prefix is genuinely deferred-open."""
        from denormalized_tpu.state.serialization import pack_snapshot

        need = self.ctrl.over_budget()
        if need <= 0:
            self.ctrl.relax(self.node_id)
            return
        op = self.op
        spec = op._spec
        spilled_any = False
        if op._first_open is not None:
            try:
                itemsize = int(np.dtype(spec.accum_dtype).itemsize)
            except TypeError:
                itemsize = 4
            per_window = max(
                len(spec.components) * spec.group_capacity * itemsize, 1
            )
            hi = min(int(hot_lo_win), op._max_win_seen + 1)
            want = -(-need // per_window)
            cut = min(op._first_open + want, hi)
            if cut > op._first_open:
                op._flush()
                W = spec.window_slots
                from denormalized_tpu.common.errors import StateError

                for j in range(op._first_open, cut):
                    rows = op._backend.read_slot(j % W)
                    arrays = {
                        label: np.asarray(arr)
                        for label, arr in rows.items()
                    }
                    block_id = f"w{self._next}"
                    blob = pack_snapshot({"window": int(j)}, arrays)
                    try:
                        # durable FIRST, reset after — a failed put must
                        # leave the slot's data in the ring
                        nbytes = self.ctrl.put_block(
                            self.node_id, block_id, blob
                        )
                    except StateError as e:
                        from denormalized_tpu.runtime.tracing import logger

                        logger.warning(
                            "spill: window eviction put failed (%s) — "
                            "window %d stays resident this pass", e, j,
                        )
                        break
                    self._next += 1
                    op._backend.reset_slot(j % W)
                    self._blocks[j] = {"id": block_id, "bytes": nbytes}
                    self.spilled_bytes += nbytes
                    self.ctrl.note_spill(self.node_id, 1, nbytes)
                    op._first_open = j + 1
                    self.any_spilled = True
                    spilled_any = True
                if spilled_any:
                    self._write_manifest()
                    self._maybe_shrink()
                    op._state_info_cache = None
        self.ctrl.check_pressure(self.node_id)

    def _maybe_shrink(self) -> None:
        """Rebuild the ring at a smaller W once the resident span allows
        it — the actual allocation shrink (spilling alone only frees the
        slots logically)."""
        op = self.op
        span = max(op._max_win_seen - op._first_open + 2, 1)
        new_w = max(_next_pow2(span), 16)
        if new_w < op._spec.window_slots:
            op._grow(window_slots=new_w)

    # -- emission ---------------------------------------------------------
    def due_windows(self, wm_floor: int) -> list[int]:
        """Spilled windows the watermark has closed, ascending — they
        emit from their stored planes before any ring emission of the
        same trigger (preserving ascending-window output order)."""
        if not self.any_spilled:
            return []
        return sorted(j for j in self._blocks if j < wm_floor)

    def emit_rows(self, j: int) -> dict:
        """Load + drop one due window's component planes."""
        from denormalized_tpu.state.serialization import unpack_snapshot

        meta = self._blocks.pop(j)
        raw = self.ctrl.get_block(self.node_id, meta["id"])
        _bmeta, arrays = unpack_snapshot(raw)
        self.spilled_bytes -= meta["bytes"]
        self.any_spilled = bool(self._blocks)
        self.ctrl.note_reload(self.node_id, 1, len(raw))
        self.ctrl.delete_block(self.node_id, meta["id"])
        self._write_manifest()
        return arrays

    def _write_manifest(self) -> None:
        self.ctrl.write_manifest(
            self.node_id, [m["id"] for m in self._blocks.values()]
        )

    def info(self) -> dict:
        return {
            "spilled_bytes": self.spilled_bytes,
            "spilled_keys": 0,
            "spilled_blocks": len(self._blocks),
            "spilled_windows": sorted(self._blocks),
            "spill": self.ctrl.spill_stats(self.node_id),
        }

    # -- checkpoint integration -------------------------------------------
    def snapshot_refs(self, coord, key: str, epoch: int) -> dict:
        refs = {}
        for j in sorted(self._blocks):
            meta = self._blocks[j]
            self.ctrl.copy_block_to_epoch(
                coord, key, epoch, self.node_id, meta["id"]
            )
            refs[str(j)] = meta["id"]
        return refs

    def restore_refs(self, coord, key: str, refs: dict) -> None:
        for j_str, block_id in refs.items():
            raw = self.ctrl.restore_block_from_epoch(
                coord, key, self.node_id, block_id
            )
            self._blocks[int(j_str)] = {
                "id": block_id, "bytes": len(raw),
            }
            self.spilled_bytes += len(raw)
            seq = int(block_id[1:])
            self._next = max(self._next, seq + 1)
        self.any_spilled = bool(self._blocks)
        self._write_manifest()


class StreamingWindowExec(ExecOperator):
    def __init__(
        self,
        input_op: ExecOperator,
        group_exprs: list[Expr],
        aggr_exprs: list[AggregateExpr],
        window_type: WindowType,
        length_ms: int,
        slide_ms: int | None,
        *,
        accum_dtype=jnp.float32,
        compensated_sums: bool = False,
        emission_compaction: bool = False,
        device_finalize: bool = True,
        min_group_capacity: int = 128,
        min_window_slots: int = 16,
        min_batch_bucket: int = 256,
        emit_on_close: bool = True,
        mesh=None,
        shard_strategy: str = "auto",
        device_strategy: str = "scatter",
        partial_merge_rows: int = 4_000_000,
        emit_lag_ms: int | None = None,
        host_pipeline: bool = False,
        name: str = "window",
    ) -> None:
        if window_type is WindowType.SESSION:
            raise PlanError(
                "session windows are handled by SessionWindowExec"
            )
        self.input_op = input_op
        self.group_exprs = list(group_exprs)
        self.aggr_exprs = list(aggr_exprs)
        self.window_type = window_type
        self.length_ms = int(length_ms)
        self.slide_ms = int(slide_ms) if slide_ms else self.length_ms
        self.emit_on_close = emit_on_close
        self.name = name
        self._min_batch_bucket = min_batch_bucket

        in_schema = input_op.schema
        # deduped value columns: one device column per distinct agg argument
        self._value_exprs: list[Expr] = []
        keys = {}

        def value_idx(e: Expr) -> int:
            k = repr(e)
            if k not in keys:
                keys[k] = len(self._value_exprs)
                self._value_exprs.append(e)
                self._value_transforms.append(None)
            return keys[k]

        # variance columns are SHIFTED on host by a pivot K picked from the
        # first data (see segment_agg.variance_result): transforms[j] is
        # None | "shift" | "shift_sq", and _var_shift maps the source
        # expression's repr to its pivot (checkpointed with the operator)
        self._value_transforms: list[str | None] = []
        self._var_shift: dict[str, float] = {}

        def shifted_idx(e: Expr, transform: str) -> int:
            k = (transform, repr(e))
            if k not in keys:
                keys[k] = len(self._value_exprs)
                self._value_exprs.append(e)
                self._value_transforms.append(transform)
            return keys[k]

        self._agg_specs: list[tuple] = []
        for a in self.aggr_exprs:
            if a.kind == "udaf":
                raise PlanError("UDAF aggregates run in UdafWindowExec")
            if a.arg is None:
                self._agg_specs.append((a.kind, None))
                continue
            if a.kind in sa.VAR_KINDS:
                self._agg_specs.append(
                    (
                        a.kind,
                        shifted_idx(a.arg, "shift"),
                        shifted_idx(a.arg, "shift_sq"),
                    )
                )
            else:
                self._agg_specs.append((a.kind, value_idx(a.arg)))
        if accum_dtype == jnp.float64 and not jax.config.jax_enable_x64:
            raise PlanError(
                "accum_dtype=float64 requires jax.config.update("
                "'jax_enable_x64', True) — without it JAX silently "
                "accumulates in float32; either enable x64 or use "
                "compensated_sums=True for near-f64 sums in f32 storage"
            )
        comps = sa.components_for(self._agg_specs)
        if compensated_sums:
            comps = sa.with_compensation(comps)
        components = tuple(comps)
        self._compensated = compensated_sums
        self._emission_compaction = emission_compaction

        self._grouped = len(self.group_exprs) > 0
        self._interner = GroupInterner(len(self.group_exprs)) if self._grouped else None
        self._mesh = mesh
        self._shard_strategy = shard_strategy
        self._device_strategy = device_strategy
        n_dev = 1 if mesh is None else mesh.devices.size
        self._spec = sa.WindowKernelSpec(
            components=components,
            num_value_cols=len(self._value_exprs),
            window_slots=min_window_slots,
            group_capacity=_round_capacity(
                min_group_capacity if self._grouped else 128, n_dev
            ),
            length_ms=self.length_ms,
            slide_ms=self.slide_ms,
            accum_dtype=accum_dtype,
            compensated=compensated_sums,
        )
        from denormalized_tpu.parallel.sharded_state import make_sharded_state

        self._backend = make_sharded_state(
            self._spec, mesh, shard_strategy, device_strategy
        )
        # on-device finalization: emission ships final output planes + an
        # active bitmask instead of raw component planes (see
        # segment_agg._finals_and_reset).  Only when every aggregate is
        # finalizable and the backend layout supports it (it returns None
        # from read_reset_block_finals_start otherwise).  Compaction takes
        # a different trigger branch entirely — preparing finals under it
        # would compile programs that never run.
        self._finals_specs = (
            tuple(self._agg_specs)
            if device_finalize
            and not emission_compaction
            and sa.finals_possible(tuple(self._agg_specs))
            else None
        )
        if self._finals_specs is not None:
            self._backend.prepare_finals(self._finals_specs)

        # schema: group cols + agg cols + window bounds (+ canonical ts)
        fields = [g.out_field(in_schema) for g in self.group_exprs]
        fields += [a.out_field(in_schema) for a in self.aggr_exprs]
        fields += [
            Field(WINDOW_START_COLUMN, DataType.TIMESTAMP_MS, nullable=False),
            Field(WINDOW_END_COLUMN, DataType.TIMESTAMP_MS, nullable=False),
            Field(CANONICAL_TIMESTAMP_COLUMN, DataType.TIMESTAMP_MS, nullable=False),
        ]
        self.schema = Schema(fields)

        # streaming state
        self._ckpt: tuple | None = None
        # cold tier (state/tiering.py): set by enable_spill
        self._tier: _WindowTier | None = None
        self._first_open: int | None = None  # lowest non-emitted slide index
        self._max_win_seen: int = -1
        self._watermark_ms: int | None = None
        # True once a kind="partition" hint arrived: the source computes
        # per-partition watermarks, so raw batch min-ts must NOT advance
        # the operator watermark (it races ahead on replay skew)
        self._src_watermarks = False
        # monotone: True once any value column carried a null.  While
        # False, emission gathers skip per-column count planes (they equal
        # the row-count plane) — see _gather_and_reset(lean=True)
        self._any_nulls_seen = False
        # host pipelining for accumulating backends: backend.accumulate
        # (the native C++ stripe reduction — it releases the GIL) runs on
        # a single worker thread so batch N's reduction overlaps batch
        # N+1's eval/intern on the main thread.  The single worker keeps
        # stripe mutation serialized; _join_acc() fences before any other
        # backend access (flush/emission/export/growth)
        self._host_pipeline = host_pipeline
        self._acc_exec = None
        self._acc_future = None
        self._acc_error: BaseException | None = None
        # partial_merge flush/emission pacing: emission is deferred up to
        # emit_lag_s after a window becomes closable so replay-speed runs
        # batch several windows per device round-trip; paced (real-time)
        # feeds always exceed the lag and emit promptly.  Backend-default
        # (None): 0 on CPU — merges are memcpy-cheap, and the deferral
        # only re-checks on rowful batches, so it would hold a paused
        # live stream's final windows until the next batch arrives; 200ms
        # on every accelerator backend (TPU, GPU, ...) — a remote merge
        # round-trip over the host↔device link is worth amortizing.
        if emit_lag_ms is None:
            emit_lag_ms = 0 if jax.default_backend() == "cpu" else 200
        self._emit_lag_s = emit_lag_ms / 1000.0
        self._merge_rows = partial_merge_rows
        self._stripe_wall: float | None = None
        # dispatched-but-unmaterialized emission blocks:
        # (j0, n, handle, is_finals)
        self._pending_emit: list[tuple] = []
        # async checkpoint in flight: (epoch, meta, backend, handle), plus
        # the barrier marker held until the snapshot is durable
        self._pending_snapshot: tuple | None = None
        self._held_marker = None
        self._metrics = {
            "rows_in": 0,
            "batches_in": 0,
            "late_rows": 0,
            "windows_emitted": 0,
            "device_steps": 0,
            "partial_merges": 0,
            "grow_events": 0,
            "host_prep_s": 0.0,
        }
        # registry instruments (obs subsystem), pre-bound so the per-
        # batch path is attribute adds only
        from denormalized_tpu import obs
        from denormalized_tpu.obs import statewatch

        self.bind_obs("window")
        # state observatory sketches, fed dense gids per batch
        self._sw = statewatch.make_watch("window")
        self._obs_late = obs.counter("dnz_late_rows_total", op="window")
        self._obs_windows = obs.counter(
            "dnz_windows_emitted_total", op="window"
        )
        self._obs_emit_lag = obs.histogram(
            "dnz_emit_event_lag_ms", op="window"
        )
        self._obs_wm_lag = obs.gauge("dnz_watermark_lag_ms", op="window")
        self._obs_wm_lag_hist = obs.histogram(
            "dnz_watermark_lag_hist_ms", op="window"
        )

    # ------------------------------------------------------------------
    @property
    def children(self):
        return [self.input_op]

    def metrics(self):
        m = dict(self._metrics)
        if self._backend.accumulates_host:
            # reconcile from the backend counter: flushes can also happen
            # inside accumulate() (stripe-span overflow), which per-call
            # deltas in _flush would miss
            m["partial_merges"] = self._backend.merges
            m["device_steps"] = self._backend.merges
        m["bytes_h2d"] = self._backend.bytes_h2d
        m["bytes_d2h"] = self._backend.bytes_d2h
        # what 'auto' actually chose AND what actually dispatched (round-3
        # VERDICT weak-7: the report must RECORD the resolved strategy,
        # not just the request) — each backend labels itself
        m["strategy_resolved"] = self._backend.strategy_name
        return m

    def _label(self):
        w = f"{self.window_type.value} {self.length_ms}ms"
        if self.slide_ms != self.length_ms:
            w += f"/{self.slide_ms}ms"
        return (
            f"StreamingWindowExec({w}, groups=[{', '.join(g.name for g in self.group_exprs)}], "
            f"aggs=[{', '.join(a.name for a in self.aggr_exprs)}])"
        )

    # -- cold tier (state/tiering.py) -----------------------------------
    def enable_spill(self, node_id: str, controller) -> None:
        self._tier = _WindowTier(self, node_id, controller)

    # -- state observatory (obs/statewatch.py) --------------------------
    def state_info(self) -> dict:
        from denormalized_tpu.obs import statewatch as swm

        spec = self._spec
        try:
            itemsize = int(np.dtype(spec.accum_dtype).itemsize)
        except TypeError:
            itemsize = 4
        # the device ring is a DENSE allocation: its footprint IS the
        # component-plane volume, independent of occupancy
        device_bytes = (
            len(spec.components)
            * spec.window_slots
            * spec.group_capacity
            * itemsize
        )
        live_keys = (
            len(self._interner) if self._interner is not None
            else (1 if self._first_open is not None else 0)
        )
        open_windows = (
            max(0, self._max_win_seen - self._first_open + 1)
            if self._first_open is not None
            else 0
        )
        oldest = (
            self._first_open * self.slide_ms
            if self._first_open is not None and open_windows
            else None
        )
        wm = self._watermark_ms
        info = {
            "op": "window",
            "state_bytes": device_bytes + live_keys * swm.KEY_EST_BYTES,
            "device_state_bytes": device_bytes,
            "live_keys": live_keys,
            "slot_capacity": int(spec.group_capacity),
            "slot_live": live_keys,
            "open_windows": open_windows,
            "window_slots": int(spec.window_slots),
            "retention_unit_ms": self.length_ms,
            "oldest_event_ms": oldest,
            "watermark_ms": wm,
        }
        if wm is not None and oldest is not None:
            info["oldest_event_lag_ms"] = max(0, int(wm) - int(oldest))
        if self._tier is not None:
            info.update(self._tier.info())
        return info

    def _state_watch_views(self):
        if not self._sw:
            return []
        if self._interner is None:
            return [(None, self._sw, None)]
        from denormalized_tpu.ops.interner import display_keys

        return [
            (None, self._sw, lambda g: display_keys(self._interner, g))
        ]

    # -- capacity management --------------------------------------------
    def _grow(self, *, window_slots: int | None = None, group_capacity: int | None = None):
        from denormalized_tpu.parallel.sharded_state import make_sharded_state

        # host-accumulated partials are bound to the old G/W layout —
        # merge them into device state before exporting it
        self._join_acc()
        self._backend.flush_pending()
        host = self._backend.export()
        old = self._spec
        self._spec = sa.WindowKernelSpec(
            components=old.components,
            num_value_cols=old.num_value_cols,
            window_slots=window_slots or old.window_slots,
            group_capacity=group_capacity or old.group_capacity,
            length_ms=old.length_ms,
            slide_ms=old.slide_ms,
            accum_dtype=old.accum_dtype,
            compensated=old.compensated,
        )
        if window_slots and self._first_open is not None:
            # ring phase changes with W: re-lay out slots by absolute window
            # index.  Only windows the old ring could actually hold are live.
            hi = min(self._max_win_seen, self._first_open + old.window_slots - 1)
            init_scalars = {
                c.label: np.asarray(self._spec.init_value(c))
                for c in self._spec.components
            }
            remapped = {}
            for label, buf in host.items():
                nbuf = np.full(
                    (self._spec.window_slots, self._spec.group_capacity),
                    init_scalars[label],
                    dtype=buf.dtype,
                )
                for j in range(self._first_open, hi + 1):
                    nbuf[j % self._spec.window_slots, : buf.shape[1]] = buf[
                        j % old.window_slots
                    ]
                remapped[label] = nbuf
            host = remapped
        old_backend = self._backend
        self._backend = make_sharded_state(
            self._spec, self._mesh, self._shard_strategy, self._device_strategy
        )
        self._carry_counters(old_backend)
        if self._finals_specs is not None:
            self._backend.prepare_finals(self._finals_specs)
        self._backend.import_(host)
        self._metrics["grow_events"] += 1

    def _carry_counters(self, old_backend) -> None:
        """Link-traffic and merge counters live on the backend instance;
        a grow/restore replacement must carry them or the bench's
        bytes_h2d/bytes_d2h reflect only the post-last-growth tail —
        exactly wrong for high-cardinality runs that grow repeatedly."""
        self._backend.bytes_h2d += old_backend.bytes_h2d
        self._backend.bytes_d2h += old_backend.bytes_d2h
        if hasattr(self._backend, "merges") and hasattr(old_backend, "merges"):
            self._backend.merges += old_backend.merges

    def _ensure_capacity(self, max_win_rel: int):
        cap = self._backend.group_capacity
        if self._grouped and len(self._interner) > 0.9 * cap:
            n_dev = 1 if self._mesh is None else self._mesh.devices.size
            self._grow(
                group_capacity=_round_capacity(
                    _next_pow2(int(len(self._interner) * 2)), n_dev
                )
            )
        if max_win_rel >= self._spec.window_slots:
            self._grow(window_slots=_next_pow2(max_win_rel + 2))

    # -- per-batch processing -------------------------------------------
    def _process_batch(self, batch: RecordBatch) -> Iterator[RecordBatch]:
        t0 = time.perf_counter()
        n = batch.num_rows
        if n == 0:
            return
        self._metrics["rows_in"] += n
        self._metrics["batches_in"] += 1
        self._obs_rows_in.add(n)
        S = self.slide_ms
        ts = np.asarray(batch.column(CANONICAL_TIMESTAMP_COLUMN), dtype=np.int64)
        units, rem64 = np.divmod(ts, S)  # one pass for quotient+remainder
        rem = rem64.astype(np.int32)

        anchor = int(units.min()) - self._spec.length_units + 1
        if self._first_open is None:
            # windows overlapping the first data: back to units.min() - k + 1
            self._first_open = anchor
        elif self._src_watermarks and anchor < self._first_open:
            # per-partition watermarks: the first batch anchored first_open
            # to ITS partition's windows, but a slower partition's earlier
            # windows are still legitimate until the (min-driven) watermark
            # closes them.  Rebase down to the watermark floor — the ring
            # addresses slots by absolute window index, so this only
            # widens the logical span (capacity grows below).  Triggers
            # advance first_open exactly to the wm floor, so anything
            # below it was genuinely closed and stays late.
            wm_floor = (
                watermark_floor(
                    self._watermark_ms, self.length_ms, self.slide_ms
                )
                if self._watermark_ms is not None
                else anchor
            )
            new_first = max(anchor, int(wm_floor))
            if new_first < self._first_open:
                if self._backend.accumulates_host:
                    # the pending stripe's units are relative to the OLD
                    # first_open (via its captured base_mod) — fold it
                    # into the device ring before the base moves
                    self._flush()
                # the widened span (new_first.._max_win_seen) needs ring
                # capacity, and the grow must run BEFORE the base moves:
                # _grow attributes old ring slots to windows
                # first_open..first_open+old_W-1, so lowering first would
                # alias a re-admitted low window with a live high one and
                # the remap would credit the high window's accumulators
                # to the low one (found by hypothesis: L=1000/S=100,
                # span 17 over a 16-slot ring lost window 7's content).
                # No sentinel guard: reaching this branch means a batch
                # was seen, and _max_win_seen's -1 floor (negative
                # event-time streams pin it there) only OVERestimates
                # the span — a larger-than-needed grow is safe, a
                # skipped one aliases slots.
                self._ensure_capacity(self._max_win_seen - new_first)
                self._first_open = new_first
        if self._tier is not None:
            # reload-on-touch BEFORE win_rel is computed: a spilled
            # window this batch's rows can land in comes back into the
            # ring (first_open lowers with it), so nothing reads as late
            # that the all-resident run would have accepted
            self._tier.touch_and_reload(
                int(units.min()) - self._spec.length_units + 1,
                int(units.max()),
            )
        first = self._first_open
        win_rel64 = units - first
        self._max_win_seen = max(self._max_win_seen, int(units.max()))
        late = int((win_rel64 < 0).sum())
        if late:
            self._metrics["late_rows"] += late
            self._obs_late.add(late)

        # group ids — intern BEFORE the capacity check so G always covers
        # every id this batch scatters
        if self._grouped:
            key_cols = [g.eval(batch) for g in self.group_exprs]
            gid = self._interner.intern(key_cols)
        else:
            gid = np.zeros(n, dtype=np.int32)
        self._sw.update(gid)
        self._ensure_capacity(int(win_rel64.max()))

        # value matrix + per-column validity: f64 only when the backend
        # accumulates on host (partial_merge keeps f64 precision); the
        # row-shipping paths fill f32 directly — no second full-matrix copy
        V = self._spec.num_value_cols
        from denormalized_tpu.logical.expr import column_validity

        host_dtype = (
            np.float64 if self._backend.accumulates_host else np.float32
        )
        single_untransformed = (
            V == 1 and self._value_transforms[0] is None
        )
        if single_untransformed:
            # single untransformed value column (the common case): the
            # evaluated column IS the value matrix — skip the zeros
            # allocation and the per-column copy.  The host reducer and
            # the device paths only read it, so aliasing the batch
            # column (host path, already f64) is safe.
            e = self._value_exprs[0]
            values64 = np.asarray(e.eval(batch), dtype=host_dtype).reshape(
                n, 1
            )
            colvalid = np.ones((n, 1), dtype=bool)
            m = column_validity(e, batch)
            any_invalid = False
            if m is not None:
                colvalid[:, 0] = m
                any_invalid = not m.all()
        else:
            values64 = np.zeros((n, max(V, 1)), dtype=host_dtype)
            colvalid = np.ones((n, max(V, 1)), dtype=bool)
            any_invalid = False
            for j, e in enumerate(self._value_exprs):
                raw = np.asarray(e.eval(batch), dtype=np.float64)
                m = column_validity(e, batch)
                if m is not None:
                    colvalid[:, j] = m
                    any_invalid = any_invalid or not colvalid[:, j].all()
                tr = self._value_transforms[j]
                if tr is not None:
                    # variance moment columns: shift by a pivot K taken
                    # from the first valid value ever seen for this
                    # expression, so the s2 − s²/c finalize never
                    # catastrophically cancels (exact for any constant K)
                    key = repr(e)
                    K = self._var_shift.get(key)
                    if K is None:
                        valid_vals = (
                            raw[colvalid[:, j]] if m is not None else raw
                        )
                        finite = valid_vals[np.isfinite(valid_vals)]
                        if len(finite):
                            K = float(finite[0])
                            self._var_shift[key] = K
                        else:
                            # no finite value yet (all-null warm-up
                            # batch): use 0 transiently but do NOT cache
                            # it — a later batch with real data must
                            # still set a magnitude-matched pivot, or
                            # the cancellation guard is lost
                            K = 0.0
                    raw = raw - K
                    if tr == "shift_sq":
                        raw = raw * raw
                values64[:, j] = raw

        if any_invalid:
            self._any_nulls_seen = True

        if self._backend.accumulates_host:
            # partial_merge: reduce the batch on host; the device sees a
            # merged stripe later (flush on trigger/growth/snapshot).
            # Late-drop against the WATERMARK (windows already closable),
            # not first_open: emission deferral must not make drop
            # semantics wall-clock-dependent — this is exactly where the
            # scatter path's first_open would sit, since it emits every
            # closable window immediately.
            closable_pre = self._closable()
            if late or closable_pre:
                keep = win_rel64 >= closable_pre
                if closable_pre and self._spec.length_units > 1:
                    # A kept row's unit partial feeds EVERY window
                    # containing that unit — including closable windows
                    # whose emission is merely deferred.  The stripe is
                    # per-unit, so that stale contribution cannot be
                    # subtracted per-window later; the only sound order is
                    # freeze-then-accumulate: emit every closable window
                    # now, then rebase against the advanced first_open.
                    # Only rows strictly BEHIND the watermark can straddle
                    # (a row at ts ≥ wm has no closable window), so a
                    # sorted feed never takes this path.
                    lows = win_rel64 - (self._spec.length_units - 1)
                    if bool((keep & (lows < closable_pre)).any()):
                        yield from self._trigger(force=True)
                        first = self._first_open
                        win_rel64 = units - first
                        closable_pre = self._closable()  # 0 post-emission
                        keep = win_rel64 >= closable_pre
                n_drop = int((~keep).sum())
                if n_drop:
                    self._metrics["late_rows"] += n_drop - late
                    self._obs_late.add(n_drop - late)
                else:
                    keep = None
            else:
                keep = None
            if (
                self._acc_future is None or self._acc_future.done()
            ) and self._backend.pending_rows == 0:
                self._stripe_wall = time.perf_counter()
            acc_args = (
                win_rel64,
                rem,
                gid,
                values64,
                colvalid if any_invalid else None,
                keep,
                first % self._spec.window_slots,
            )
            if self._host_pipeline:
                self._submit_acc(*acc_args)
            else:
                self._backend.accumulate(*acc_args)
            self._metrics["host_prep_s"] += time.perf_counter() - t0
        else:
            values = values64  # already f32 (see allocation above)
            win_rel = np.clip(
                win_rel64, -1, self._spec.window_slots
            ).astype(np.int32)
            # pad to bucket (divisible by the mesh so row-sharding splits
            # evenly)
            Bp = max(self._min_batch_bucket, _next_pow2(n))
            n_dev = 1 if self._mesh is None else self._mesh.devices.size
            Bp = -(-Bp // n_dev) * n_dev
            row_valid = np.zeros(Bp, dtype=bool)
            row_valid[:n] = True

            def pad(a, fill=0):
                if a.shape[0] == Bp:
                    return a
                out = np.full((Bp,) + a.shape[1:], fill, dtype=a.dtype)
                out[:n] = a
                return out

            self._metrics["host_prep_s"] += time.perf_counter() - t0
            self._backend.update(
                pad(values),
                pad(colvalid),
                pad(win_rel, fill=-1),
                pad(rem),
                pad(gid),
                row_valid,
                first % self._spec.window_slots,
                # span of the ON-TIME rows only: late rows (win_rel < 0)
                # are dropped by both kernels and must not widen the
                # dense-path span
                min_win_rel=int(
                    win_rel64[win_rel64 >= 0].min()
                    if (win_rel64 >= 0).any()
                    else 0
                ),
                max_win_rel=int(win_rel64.max()),
            )
            self._metrics["device_steps"] += 1

        # watermark: monotonic max of batch min-ts (reference semantics) —
        # unless the source supplies per-partition watermarks, which
        # arrive as kind="partition" hints right after their batch
        if not self._src_watermarks:
            bmin = int(ts.min())
            if self._watermark_ms is None or bmin > self._watermark_ms:
                self._watermark_ms = bmin
        yield from self._trigger()
        if self._tier is not None:
            # after the trigger: closable windows have emitted, so the
            # [first_open, this batch's lowest window) prefix is the
            # watermark-deferred cold span
            self._tier.maybe_spill(
                int(units.min()) - self._spec.length_units + 1
            )

    # -- host pipeline fence --------------------------------------------
    def _join_acc(self) -> None:
        """Wait for any in-flight host accumulation.  Every backend access
        other than pacing reads (pending_rows) must fence through here —
        the stripe and the device merge stream are only consistent between
        worker tasks."""
        f, self._acc_future = self._acc_future, None
        err = None
        if f is not None:
            try:
                f.result()  # re-raises a worker failure on this thread
            finally:
                # read the flag only AFTER the wait: an EARLIER task
                # (future superseded by a later submission) may set it
                # while we block on the latest one.  Clearing it here also
                # prevents f's own failure from being raised a second time
                # by a later, unrelated fence.
                err, self._acc_error = self._acc_error, None
        else:
            err, self._acc_error = self._acc_error, None
        if err is not None:
            # a superseded task failed even though the latest one
            # succeeded; the stream must not keep running on a
            # half-updated stripe
            raise err

    def _submit_acc(self, *args) -> None:
        if self._acc_error is not None:
            err, self._acc_error = self._acc_error, None
            raise err
        if self._acc_exec is None:
            from concurrent.futures import ThreadPoolExecutor

            self._acc_exec = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix=f"{self.name}-acc"
            )

        backend = self._backend

        def run():
            try:
                backend.accumulate(*args)
            except BaseException as e:  # surfaced via _join_acc/_submit_acc
                self._acc_error = e
                raise

        self._acc_future = self._acc_exec.submit(run)

    def _output_low_watermark(self, hint_ts: int) -> int:
        return window_output_low_watermark(
            self._first_open, self.slide_ms, self.length_ms, hint_ts,
            wm_ms=self._watermark_ms if self._src_watermarks else None,
        )

    # -- emission --------------------------------------------------------
    def _closable(self) -> int:
        if self._watermark_ms is None or self._first_open is None:
            return 0
        wm_win = watermark_floor(
            self._watermark_ms, self.length_ms, self.slide_ms
        )
        return max(0, int(wm_win) - self._first_open)

    def _drain_pending(self) -> Iterator[RecordBatch]:
        """Materialize previously dispatched emission blocks (their
        device→host transfers have been running in the background)."""
        if not self._pending_emit:
            return
        pending, self._pending_emit = self._pending_emit, []
        ngroups = len(self._interner) if self._grouped else 1
        for j0, n, handle, is_finals in pending:
            block = self._backend.read_reset_block_finish(handle)
            if is_finals:
                # finals block: one plane per output aggregate + packed
                # active bitmask; no host-side finalize needed
                bits = np.unpackbits(block[sa.ACTIVE_BITS], axis=1)
                for i in range(n):
                    active = bits[i].astype(bool)
                    active[ngroups:] = False
                    if not active.any():
                        continue
                    gids = np.nonzero(active)[0].astype(np.int32)
                    finals = [
                        block[f"__final_{k}__"][i][gids]
                        for k in range(len(self.aggr_exprs))
                    ]
                    self._metrics["windows_emitted"] += 1
                    yield self._build_emission_finals(j0 + i, gids, finals)
                continue
            # lean gathers omit per-column count planes (null-free stream:
            # they equal the row-count plane) — alias them back
            for c in self._spec.components:
                if c.kind == "count" and c.label not in block:
                    block[c.label] = block[sa.ROW_COUNT.label]
            for i in range(n):
                rows = {label: arr[i] for label, arr in block.items()}
                counts = rows[sa.ROW_COUNT.label]
                active = counts > 0
                active[ngroups:] = False
                if not active.any():
                    continue
                self._metrics["windows_emitted"] += 1
                gids = np.nonzero(active)[0].astype(np.int32)
                yield self._build_emission(j0 + i, gids, rows, active)

    def _trigger(self, force: bool = False) -> Iterator[RecordBatch]:
        """Emit every window whose end ≤ watermark (trigger_windows,
        grouped_window_agg_stream.rs:220-253).

        With a host-accumulating backend, emission is deferred up to
        ``_emit_lag_s`` after the first window becomes closable: a
        replay-speed feed then closes several windows per device
        round-trip (merge + block gather amortized), while a real-time
        feed — whose stripe is necessarily older than the lag when its
        window closes — emits immediately.  ``force`` bypasses the
        deferral: ingest uses it to freeze closable windows before a
        batch whose rows would otherwise leak late units into them."""
        yield from self._drain_pending()
        if (
            self._tier is not None
            and self._tier.any_spilled
            and self._watermark_ms is not None
            and self._first_open is not None
        ):
            # spilled windows the watermark closed emit straight from
            # their stored planes — they are all below first_open, so
            # ascending-window output order is preserved
            wmf = int(
                watermark_floor(
                    self._watermark_ms, self.length_ms, self.slide_ms
                )
            )
            for j in self._tier.due_windows(wmf):
                b = self._finalize_rows(j, self._tier.emit_rows(j))
                if b is not None:
                    yield b
        if self._obs_wm_lag and self._watermark_ms is not None:
            # watermark lag (wall − watermark): how far event time trails
            # real time at this trigger.  Gauge = latest, histogram =
            # distribution (its max is the run's peak lag).
            lag = time.time() * 1000.0 - self._watermark_ms
            self._obs_wm_lag.set(lag)
            self._obs_wm_lag_hist.observe(lag)
        n_close = self._closable()
        if n_close == 0:
            if (
                self._backend.accumulates_host
                and self._backend.pending_rows >= self._merge_rows
            ):
                self._flush()
            return
        if self._backend.accumulates_host:
            age = time.perf_counter() - (self._stripe_wall or 0.0)
            if (
                not force
                and age < self._emit_lag_s
                and self._backend.pending_rows < self._merge_rows
                and self._stripe_fits_more()
            ):
                return
            self._flush()
        if self._emission_compaction:
            while self._first_open * self.slide_ms + self.length_ms <= self._watermark_ms:
                b = self._emit_window(self._first_open)
                self._first_open += 1
                if b is not None:
                    yield b
            return
        while n_close > 0:
            # pow2 block sizes bound the compiled gather variants
            n = 1 << min(3, (n_close).bit_length() - 1)
            n = min(n, self._spec.window_slots)
            live = len(self._interner) if self._grouped else 1
            handle = None
            if self._finals_specs is not None:
                handle = self._backend.read_reset_block_finals_start(
                    self._first_open % self._spec.window_slots, n,
                    live_groups=live,
                )
            if handle is not None:
                self._pending_emit.append((self._first_open, n, handle, True))
            else:
                handle = self._backend.read_reset_block_start(
                    self._first_open % self._spec.window_slots, n,
                    live_groups=live,
                    # only when the lean layout actually differs — else the
                    # lean=True program would be a duplicate compilation of
                    # the full one
                    lean=(
                        not self._any_nulls_seen
                        and sa.lean_possible(self._spec)
                    ),
                )
                self._pending_emit.append((self._first_open, n, handle, False))
            self._first_open += n
            n_close -= n
        if not self._backend.accumulates_host or self._emit_lag_s == 0:
            # row-shipping backends emit synchronously (prompt, in the
            # same trigger); the async pipeline — drain on the NEXT
            # trigger so the device→host transfer overlaps ingest — is
            # reserved for the deferred partial_merge path where remote
            # round-trips dominate.  With a zero emit lag (the CPU
            # default) there is nothing to overlap, and deferring the
            # drain would hold a paused live stream's output until the
            # next rowful batch arrives.
            yield from self._drain_pending()

    def _stripe_fits_more(self) -> bool:
        """Can the stripe still absorb the next slide unit without
        overflowing its span? (else defer no further — flush and emit)"""
        from denormalized_tpu.ops.host_partial import HostPartialStripe

        span_now = self._max_win_seen - self._first_open + 1
        return span_now + 1 < HostPartialStripe.U_MAX

    def _flush(self) -> None:
        # counters reconcile from backend.merges in metrics()
        self._join_acc()
        self._backend.flush_pending()

    def _emit_window(self, j: int) -> RecordBatch | None:
        from denormalized_tpu.runtime.tracing import span

        slot = j % self._spec.window_slots
        compacted = None
        with span("window.emit", op=self.name, window=j * self.slide_ms):
            if self._emission_compaction:
                compacted = self._backend.read_slot_compact(slot)
            if compacted is not None:
                gids32, rows = compacted
                self._backend.reset_slot(slot)
            else:
                rows = self._backend.read_slot(slot)
                self._backend.reset_slot(slot)
        if compacted is not None:
            # rows hold ONLY the active groups, already in ascending gid
            # order (read_slot_compact's contract).  Apply the same
            # interner-bound guard the full path applies before keys_of.
            ngroups = len(self._interner) if self._grouped else 1
            in_bounds = gids32 < ngroups
            if not in_bounds.all():
                gids32 = gids32[in_bounds]
                rows = {label: arr[in_bounds] for label, arr in rows.items()}
            if len(gids32) == 0:
                return None
            gids = gids32.astype(np.int32)
            active = np.ones(len(gids), dtype=bool)
            self._metrics["windows_emitted"] += 1
            return self._build_emission(j, gids, rows, active)
        return self._finalize_rows(j, rows)

    def _finalize_rows(self, j: int, rows: dict) -> RecordBatch | None:
        """Finalize one window's component planes into an emission batch
        — shared by the ring slot path and the cold tier's emit-from-
        store path (identical output either way)."""
        counts = rows[sa.ROW_COUNT.label]
        ngroups = len(self._interner) if self._grouped else 1
        active = counts > 0
        active[ngroups:] = False
        if not active.any():
            return None
        self._metrics["windows_emitted"] += 1
        gids = np.nonzero(active)[0].astype(np.int32)
        return self._build_emission(j, gids, rows, active)

    def _assemble_emission(
        self, j: int, gids: np.ndarray, finals: list
    ) -> RecordBatch:
        """Shared emission assembly: group-key columns from the interner,
        finalized aggregate columns (cast to output dtypes), window
        bounds + canonical timestamp."""
        cols: list[np.ndarray] = []
        if self._grouped:
            key_vals = self._interner.keys_of(gids)
            for g, kv in zip(self.group_exprs, key_vals):
                f = g.out_field(self.input_op.schema)
                if f.dtype.is_numeric:
                    kv = np.asarray(kv.tolist(), dtype=f.dtype.to_numpy())
                cols.append(kv)
        for a, arr in zip(self.aggr_exprs, finals):
            f = a.out_field(self.input_op.schema)
            cols.append(np.asarray(arr).astype(f.dtype.to_numpy()))
        m = len(gids)
        start = np.full(m, j * self.slide_ms, dtype=np.int64)
        end = np.full(m, j * self.slide_ms + self.length_ms, dtype=np.int64)
        cols += [start, end, start.copy()]
        self._obs_windows.add(1)
        if self._obs_emit_lag:
            # end-to-end event-time emission latency, stamped at the one
            # place every emission path funnels through
            self._obs_emit_lag.observe(
                time.time() * 1000.0 - (j * self.slide_ms + self.length_ms)
            )
        if self._dr_lineage is not None:
            # sampled record lineage: close every chain whose tagged row
            # fell inside this window (same funnel point as emit lag)
            self._dr_lineage.emitted(
                self._dr_node_id,
                j * self.slide_ms,
                j * self.slide_ms + self.length_ms,
            )
        return RecordBatch(self.schema, cols)

    def _build_emission_finals(
        self, j: int, gids: np.ndarray, finals: list
    ) -> RecordBatch:
        """Emission from device-finalized output planes (already masked to
        the active gids, in aggr_exprs order)."""
        return self._assemble_emission(j, gids, finals)

    def _build_emission(
        self, j: int, gids: np.ndarray, rows: dict, active: np.ndarray
    ) -> RecordBatch:
        return self._assemble_emission(
            j, gids, sa.finalize(self._agg_specs, rows, active)
        )

    # -- checkpointing ----------------------------------------------------
    # Snapshot = device state buffers + interner + watermark scalars, the
    # analog of CheckpointedGroupedWindowAggStream
    # (grouped_window_agg_stream.rs:84-102,355-418) — but taken from an
    # ALIGNED in-band marker, and without the reference's drain-then-reseed
    # trick (:379-394): export_state reads buffers without mutating them.
    def enable_checkpointing(self, node_id: str, coord, orch) -> None:
        self._ckpt = (coord, f"window_{node_id}")
        self._restore()

    def _snapshot(self, epoch: int) -> None:
        """Dispatch an epoch snapshot WITHOUT blocking on the device→host
        transfer: flush host partials, clone the ring on device, start its
        async host copy, and capture the host-side meta NOW (it mutates
        with the very next batch).  ``_release_snapshot`` materializes and
        persists it — and only then releases the held barrier marker, so
        the commit protocol (snapshot durable before the marker reaches
        the root) is preserved while the transfer overlaps downstream
        work and the next source read."""
        # device state must include everything the stripe holds — the
        # snapshot is the recovery point
        self._flush()
        meta = {
            "epoch": epoch,
            "first_open": self._first_open,
            "max_win_seen": self._max_win_seen,
            "watermark_ms": self._watermark_ms,
            "window_slots": self._spec.window_slots,
            "group_capacity": self._backend.group_capacity,
            "interner": self._interner.snapshot() if self._grouped else None,
            # variance pivots: shifted sums are only comparable under the
            # same K, so K must survive restart with the state it shifted
            "var_shift": dict(self._var_shift),
            "any_nulls_seen": self._any_nulls_seen,
        }
        if self._tier is not None and self._tier.any_spilled:
            coord, key = self._ckpt
            # spilled window planes commit under this SAME epoch; the
            # ring export below holds only the resident windows
            meta["spill_windows"] = self._tier.snapshot_refs(
                coord, key, epoch
            )
        self._pending_snapshot = (
            epoch, meta, self._backend, self._backend.export_start()
        )

    def _release_snapshot(self) -> Iterator:
        """Persist a pending snapshot and release its held marker.  MUST
        run before any output derived from post-marker input leaves this
        operator — a downstream operator that saw post-marker emissions
        before the marker would snapshot state AHEAD of ours, and a
        restore would double-apply those windows."""
        if self._pending_snapshot is not None:
            from denormalized_tpu.state.serialization import pack_snapshot

            epoch, meta, backend, handle = self._pending_snapshot
            self._pending_snapshot = None
            coord, key = self._ckpt
            coord.put_snapshot(
                key, epoch, pack_snapshot(meta, backend.export_finish(handle))
            )
        if self._held_marker is not None:
            marker, self._held_marker = self._held_marker, None
            yield marker

    def _restore(self) -> None:
        from denormalized_tpu.state.serialization import unpack_snapshot
        from denormalized_tpu.parallel.sharded_state import make_sharded_state

        coord, key = self._ckpt
        blob = coord.get_snapshot(key)
        if blob is None:
            return
        meta, arrays = unpack_snapshot(blob)
        n_dev = 1 if self._mesh is None else self._mesh.devices.size
        old = self._spec
        self._spec = sa.WindowKernelSpec(
            components=old.components,
            num_value_cols=old.num_value_cols,
            window_slots=int(meta["window_slots"]),
            group_capacity=_round_capacity(int(meta["group_capacity"]), n_dev),
            length_ms=old.length_ms,
            slide_ms=old.slide_ms,
            accum_dtype=old.accum_dtype,
            compensated=old.compensated,
        )
        old_backend = self._backend
        self._backend = make_sharded_state(
            self._spec, self._mesh, self._shard_strategy, self._device_strategy
        )
        self._carry_counters(old_backend)
        if self._finals_specs is not None:
            self._backend.prepare_finals(self._finals_specs)
        self._backend.import_(arrays)
        self._first_open = meta["first_open"]
        self._max_win_seen = meta["max_win_seen"]
        self._watermark_ms = meta["watermark_ms"]
        # restored state may hold counts < row counts (nulls before the
        # kill); unless the snapshot says otherwise, stay on full gathers
        self._any_nulls_seen = bool(meta.get("any_nulls_seen", True))
        self._var_shift = dict(meta.get("var_shift") or {})
        if self._grouped and meta["interner"] is not None:
            self._interner = GroupInterner.restore(meta["interner"])
        refs = meta.get("spill_windows")
        if refs:
            coord, key = self._ckpt
            if self._tier is not None:
                self._tier.restore_refs(coord, key, refs)
            else:
                self._restore_spilled_resident(coord, key, refs)

    def _restore_spilled_resident(self, coord, key: str, refs: dict) -> None:
        """Budget removed since the checkpoint: spilled window planes
        merge back into the ring (first_open lowers to cover them)."""
        from denormalized_tpu.common.errors import StateError
        from denormalized_tpu.state.serialization import unpack_snapshot

        js = sorted(int(k) for k in refs)
        new_first = min(js + ([self._first_open] if self._first_open is not None else []))
        self._ensure_capacity(self._max_win_seen - new_first)
        self._first_open = new_first
        host = {
            label: np.array(buf)
            for label, buf in self._backend.export().items()
        }
        W = self._spec.window_slots
        for j in js:
            raw = coord.get_snapshot(f"{key}:spill:{refs[str(j)]}")
            if raw is None:
                raise StateError(
                    f"checkpoint references spilled window {j} but the "
                    "epoch holds no such snapshot"
                )
            _bmeta, arrays = unpack_snapshot(raw)
            for label, arr in arrays.items():
                host[label][j % W, : arr.shape[0]] = arr
        self._backend.import_(host)

    # -- stream loop -----------------------------------------------------
    def run(self) -> Iterator[StreamItem]:
        try:
            yield from self._run_inner()
        finally:
            self._shutdown_acc()

    def _shutdown_acc(self) -> None:
        """Stop the host-pipeline worker (if any).  Joins the in-flight
        task so a failure in the stream's final batches still surfaces,
        and releases the thread — one leaked worker per finished stream
        otherwise."""
        ex, self._acc_exec = self._acc_exec, None
        if ex is not None:
            try:
                self._join_acc()
            finally:
                ex.shutdown(wait=True)

    def _run_inner(self) -> Iterator[StreamItem]:
        from denormalized_tpu.runtime.tracing import span

        for item in self._doctor_input():
            if isinstance(item, RecordBatch):
                # materialize any in-flight snapshot and release its
                # marker BEFORE producing output from post-marker input
                # (alignment invariant, see _release_snapshot)
                yield from self._release_snapshot()
                # emissions are materialized INSIDE the timing bracket so
                # the span and the batch-time histogram measure this
                # operator's own work, not time spent suspended while
                # downstream consumed the yielded windows
                t0 = time.perf_counter()
                with span(
                    "window.process_batch", op=self.name, rows=item.num_rows
                ):
                    out = list(self._process_batch(item))
                self._note_batch(t0, item.num_rows)
                yield from out
            elif isinstance(item, WatermarkHint):
                if item.kind == "partition":
                    # authoritative per-partition watermark: from now on
                    # batch min-ts must not advance the watermark
                    self._src_watermarks = True
                    if item.is_announcement:
                        yield item  # pure mode announcement
                        continue
                    # barrier alignment: a held marker must reach
                    # downstream before any trigger output this hint
                    # produces (same invariant as the batch path)
                    yield from self._release_snapshot()
                    if (
                        self._watermark_ms is None
                        or item.ts_ms > self._watermark_ms
                    ):
                        self._watermark_ms = item.ts_ms
                        # normal trigger: these hints arrive continuously
                        # (one per advancing batch), so the emit-lag
                        # deferral keeps working — no force, no drain
                        yield from self._trigger()
                    yield WatermarkHint(
                        min(item.ts_ms, self._output_low_watermark(item.ts_ms)),
                        kind="partition",
                    )
                    continue
                # idle source: advance event time and close what's ready,
                # then forward the hint for downstream stateful operators —
                # CLAMPED below this operator's lowest possible future
                # emission timestamp (emissions are stamped with the
                # window START, so an unclamped forward would make a
                # downstream operator drop our later closed windows as
                # late)
                yield from self._release_snapshot()
                if self._watermark_ms is None or item.ts_ms > self._watermark_ms:
                    self._watermark_ms = item.ts_ms
                    # force: the emit-lag deferral assumes another batch
                    # (or hint) will follow, but an idle period delivers
                    # exactly ONE hint — a deferred emission would never
                    # run and the final windows would sit closed-but-
                    # unemitted, defeating the feature.  Likewise drain
                    # the async emission pipeline NOW: blocks dispatched
                    # by this trigger normally materialize on the next
                    # item, and there is no next item.
                    yield from self._trigger(force=True)
                    yield from self._drain_pending()
                yield WatermarkHint(
                    min(item.ts_ms, self._output_low_watermark(item.ts_ms))
                )
            elif isinstance(item, Marker):
                yield from self._drain_pending()
                yield from self._release_snapshot()  # an earlier epoch
                if self._ckpt is not None:
                    self._snapshot(item.epoch)
                    self._held_marker = item
                else:
                    yield item
            elif isinstance(item, EndOfStream):
                # pending blocks are watermark-CLOSED windows: they emit
                # even when the unclosed-window flush is disabled
                yield from self._drain_pending()
                yield from self._release_snapshot()
                if self.emit_on_close and self._first_open is not None:
                    self._flush()
                    if self._tier is not None and self._tier.any_spilled:
                        # spilled windows all sit below first_open:
                        # flushing them first keeps ascending order
                        for j in self._tier.due_windows(
                            self._max_win_seen + 1
                        ):
                            b = self._finalize_rows(
                                j, self._tier.emit_rows(j)
                            )
                            if b is not None:
                                yield b
                    for j in range(self._first_open, self._max_win_seen + 1):
                        b = self._emit_window(j)
                        if b is not None:
                            yield b
                    self._first_open = self._max_win_seen + 1
                else:
                    # no final flush ran — still fence the worker so an
                    # async accumulate failure cannot be swallowed
                    self._join_acc()
                yield EOS
                return
