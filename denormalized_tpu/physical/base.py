"""Physical operator protocol.

The reference's physical layer is DataFusion ``ExecutionPlan`` objects
producing ``RecordBatchStream``s (stream_table.rs, streaming_window.rs).  Ours
is a pull-based pipeline of Python generators flowing :class:`StreamItem`s:

- ``RecordBatch`` — data;
- :class:`Marker` — a checkpoint barrier.  Unlike the reference, which
  delivers barriers out-of-band per stream (orchestrator.rs:55-78, an
  *approximate* Chandy-Lamport — see SURVEY.md §3.4), markers here flow
  **in-band and aligned** through the dataflow, so a checkpoint is a
  consistent cut for free;
- :class:`EndOfStream` — bounded input exhausted (replay/test sources); the
  windowed operator flushes open windows on receipt.

Heavy compute happens inside operators (device steps in the window exec);
the generator plumbing between them moves only batch references.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Iterator, Union

from denormalized_tpu.common.record_batch import RecordBatch
from denormalized_tpu.common.schema import Schema
from denormalized_tpu.obs.registry import NULL as _OBS_NULL


@dataclass(frozen=True)
class WatermarkHint:
    """Event-time advance from the source.  Two kinds:

    - ``"idle"`` — advisory one-shot from a quiet source: no further rows
      at or before ``ts_ms`` are expected, so stateful operators may
      close windows/sessions up to it.  Emitted by SourceExec when every
      partition has been idle for ``EngineConfig.source_idle_timeout_ms``
      (the reference — like Kafka consumers generally — simply never
      closes the last windows of a quiet topic; this is the Flink-style
      idleness escape hatch, default off).
    - ``"partition"`` — AUTHORITATIVE per-partition watermark: the min
      over each partition's own max-of-batch-min-ts (idle partitions
      excluded).  Operators that see one stop advancing their watermark
      from raw batch min-ts: the merged stream's global max-of-min races
      ahead on whichever partition drains fastest and drops the slower
      partitions' backlog as late (replay/catch-up skew — the reference
      shares this flaw).  A hint with ``ts_ms <= WM_ANNOUNCE`` is a pure
      mode announcement carrying no timestamp.

    Stateless operators pass both kinds through."""

    ts_ms: int
    kind: str = "idle"

    @property
    def is_announcement(self) -> bool:
        """Pure mode announcement: switches operators to hint-driven
        watermarks without advancing anything.  Every stateful operator
        must use THIS check (not its own sentinel comparison) so the
        rule cannot drift between call sites."""
        return self.ts_ms <= WM_ANNOUNCE


#: mode-announcement sentinel: a kind="partition" hint at or below this
#: value switches operators to hint-driven watermarks without advancing
#: anything (emitted before the first batch, closing the startup window
#: where batch-driven advance could already race ahead)
WM_ANNOUNCE = -(2**62)


@dataclass(frozen=True)
class Marker:
    """Checkpoint barrier (reference OrchestrationMessage::CheckpointBarrier,
    orchestrator.rs:12-16)."""

    epoch: int


@dataclass(frozen=True)
class EndOfStream:
    pass


EOS = EndOfStream()

StreamItem = Union[RecordBatch, Marker, WatermarkHint, EndOfStream]


class ExecOperator:
    """One node of the physical plan."""

    #: output schema
    schema: Schema

    #: registry handles (no-op defaults so an operator that never calls
    #: bind_obs — test doubles subclassing ExecOperator directly — still
    #: runs; real operators bind in their constructors)
    _obs_rows_in = _OBS_NULL
    _obs_batch_ms = _OBS_NULL
    _obs_input_wait = _OBS_NULL

    #: doctor per-node stats (obs/doctor): plain single-writer attribute
    #: adds — one float/int add per batch or item, independent of the
    #: registry so attribution works even with metrics disabled.  Class
    #: defaults keep un-doctored operator instances (test doubles, direct
    #: build_physical callers) inert.
    _dr_busy_ms = 0.0
    _dr_batches = 0
    _dr_rows_in = 0
    _dr_input_wait_s = 0.0
    _dr_node_id: str | None = None
    _dr_lineage = None  # obs.doctor.lineage.LineageTracker when sampling

    def bind_obs(self, op: str) -> None:
        """Bind this operator's registry instruments (obs subsystem):
        rows-in counter, per-batch processing-time histogram, and the
        doctor's upstream-wait histogram, labeled ``op=<label>``.
        Called once from each operator's constructor; with metrics
        disabled the handles are shared no-op nulls, so the hot path
        stays allocation-free."""
        from denormalized_tpu import obs

        self._obs_rows_in = obs.counter("dnz_op_rows_in_total", op=op)
        self._obs_batch_ms = obs.histogram("dnz_op_batch_ms", op=op)
        self._obs_input_wait = obs.histogram(
            "dnz_op_input_wait_ms", op=op
        )

    # -- doctor handoff instrumentation (obs/doctor, DNZ-M002) -----------
    def _note_batch(self, t0: float, rows: int) -> None:
        """Close a batch-processing bracket opened at ``perf_counter()``
        ``t0``: feeds both the registry histogram and the doctor's
        per-node busy accounting.  Emissions must be materialized before
        calling (time suspended in downstream operators is never this
        operator's busy time — the PR-6 bracket contract)."""
        dt_ms = (time.perf_counter() - t0) * 1e3
        self._obs_batch_ms.observe(dt_ms)
        self._dr_busy_ms += dt_ms
        self._dr_batches += 1
        self._dr_rows_in += rows

    def _note_input_wait(self, dt_s: float) -> None:
        """Record one upstream-handoff wait (time this operator spent
        suspended before the next stream item arrived).  Multi-input
        operators (the join's merged queue) call this directly; single-
        input operators get it via :meth:`_doctor_input`."""
        self._dr_input_wait_s += dt_s
        if self._obs_input_wait:
            self._obs_input_wait.observe(dt_s * 1e3)

    def _doctor_input(self, input_op: "ExecOperator | None" = None
                      ) -> Iterator[StreamItem]:
        """Iterate the upstream operator with the doctor's handoff
        instrumentation: every pull is timed (queue-wait attribution)
        and, when record lineage is sampling, rowful batches covering a
        sampled record register a hop at this node.  Every operator that
        overrides the batch-processing path must consume its input
        through this (or :meth:`_note_input_wait`) — lint-enforced by
        DNZ-M002."""
        it = (input_op if input_op is not None else self.input_op).run()
        while True:
            t0 = time.perf_counter()
            try:
                item = next(it)
            except StopIteration:
                return
            self._note_input_wait(time.perf_counter() - t0)
            if (
                self._dr_lineage is not None
                and isinstance(item, RecordBatch)
                and item.num_rows
            ):
                self._dr_lineage.hop(self._dr_node_id, item)
            yield item

    def run(self) -> Iterator[StreamItem]:
        raise NotImplementedError

    @property
    def children(self) -> list["ExecOperator"]:
        return []

    # -- observability (reference exposes DataFusion MetricsSet via
    # ExecutionPlan::metrics, streaming_window.rs:491) ------------------
    def metrics(self) -> dict[str, float]:
        return {}

    def display(self, indent: int = 0, with_metrics: bool = False) -> str:
        line = "  " * indent + self._label()
        if with_metrics:
            m = self.metrics()
            if m:
                parts = ", ".join(
                    f"{k}={v:.6g}" if isinstance(v, float) else f"{k}={v}"
                    for k, v in m.items()
                )
                line += f"  [{parts}]"
        return "\n".join(
            [line] + [c.display(indent + 1, with_metrics) for c in self.children]
        )

    def _label(self) -> str:
        return type(self).__name__
