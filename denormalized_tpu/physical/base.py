"""Physical operator protocol.

The reference's physical layer is DataFusion ``ExecutionPlan`` objects
producing ``RecordBatchStream``s (stream_table.rs, streaming_window.rs).  Ours
is a pull-based pipeline of Python generators flowing :class:`StreamItem`s:

- ``RecordBatch`` — data;
- :class:`Marker` — a checkpoint barrier.  Unlike the reference, which
  delivers barriers out-of-band per stream (orchestrator.rs:55-78, an
  *approximate* Chandy-Lamport — see SURVEY.md §3.4), markers here flow
  **in-band and aligned** through the dataflow, so a checkpoint is a
  consistent cut for free;
- :class:`EndOfStream` — bounded input exhausted (replay/test sources); the
  windowed operator flushes open windows on receipt.

Heavy compute happens inside operators (device steps in the window exec);
the generator plumbing between them moves only batch references.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterator, Union

from denormalized_tpu.common.record_batch import RecordBatch
from denormalized_tpu.common.schema import Schema


@dataclass(frozen=True)
class WatermarkHint:
    """Advisory event-time advance from an idle source: no further rows at
    or before ``ts_ms`` are expected, so stateful operators may close
    windows/sessions up to it.  Emitted by SourceExec when every partition
    has been idle for ``EngineConfig.source_idle_timeout_ms`` (the
    reference — like Kafka consumers generally — simply never closes the
    last windows of a quiet topic; this is the Flink-style idleness
    escape hatch, default off).  Stateless operators pass it through."""

    ts_ms: int


@dataclass(frozen=True)
class Marker:
    """Checkpoint barrier (reference OrchestrationMessage::CheckpointBarrier,
    orchestrator.rs:12-16)."""

    epoch: int


@dataclass(frozen=True)
class EndOfStream:
    pass


EOS = EndOfStream()

StreamItem = Union[RecordBatch, Marker, WatermarkHint, EndOfStream]


class ExecOperator:
    """One node of the physical plan."""

    #: output schema
    schema: Schema

    def run(self) -> Iterator[StreamItem]:
        raise NotImplementedError

    @property
    def children(self) -> list["ExecOperator"]:
        return []

    # -- observability (reference exposes DataFusion MetricsSet via
    # ExecutionPlan::metrics, streaming_window.rs:491) ------------------
    def metrics(self) -> dict[str, float]:
        return {}

    def display(self, indent: int = 0, with_metrics: bool = False) -> str:
        line = "  " * indent + self._label()
        if with_metrics:
            m = self.metrics()
            if m:
                parts = ", ".join(
                    f"{k}={v:.6g}" if isinstance(v, float) else f"{k}={v}"
                    for k, v in m.items()
                )
                line += f"  [{parts}]"
        return "\n".join(
            [line] + [c.display(indent + 1, with_metrics) for c in self.children]
        )

    def _label(self) -> str:
        return type(self).__name__
