"""Physical operator protocol.

The reference's physical layer is DataFusion ``ExecutionPlan`` objects
producing ``RecordBatchStream``s (stream_table.rs, streaming_window.rs).  Ours
is a pull-based pipeline of Python generators flowing :class:`StreamItem`s:

- ``RecordBatch`` — data;
- :class:`Marker` — a checkpoint barrier.  Unlike the reference, which
  delivers barriers out-of-band per stream (orchestrator.rs:55-78, an
  *approximate* Chandy-Lamport — see SURVEY.md §3.4), markers here flow
  **in-band and aligned** through the dataflow, so a checkpoint is a
  consistent cut for free;
- :class:`EndOfStream` — bounded input exhausted (replay/test sources); the
  windowed operator flushes open windows on receipt.

Heavy compute happens inside operators (device steps in the window exec);
the generator plumbing between them moves only batch references.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Iterator, Union

from denormalized_tpu.common.record_batch import RecordBatch
from denormalized_tpu.common.schema import Schema
from denormalized_tpu.obs.registry import NULL as _OBS_NULL


@dataclass(frozen=True)
class WatermarkHint:
    """Event-time advance from the source.  Two kinds:

    - ``"idle"`` — advisory one-shot from a quiet source: no further rows
      at or before ``ts_ms`` are expected, so stateful operators may
      close windows/sessions up to it.  Emitted by SourceExec when every
      partition has been idle for ``EngineConfig.source_idle_timeout_ms``
      (the reference — like Kafka consumers generally — simply never
      closes the last windows of a quiet topic; this is the Flink-style
      idleness escape hatch, default off).
    - ``"partition"`` — AUTHORITATIVE per-partition watermark: the min
      over each partition's own max-of-batch-min-ts (idle partitions
      excluded).  Operators that see one stop advancing their watermark
      from raw batch min-ts: the merged stream's global max-of-min races
      ahead on whichever partition drains fastest and drops the slower
      partitions' backlog as late (replay/catch-up skew — the reference
      shares this flaw).  A hint with ``ts_ms <= WM_ANNOUNCE`` is a pure
      mode announcement carrying no timestamp.

    Stateless operators pass both kinds through."""

    ts_ms: int
    kind: str = "idle"

    @property
    def is_announcement(self) -> bool:
        """Pure mode announcement: switches operators to hint-driven
        watermarks without advancing anything.  Every stateful operator
        must use THIS check (not its own sentinel comparison) so the
        rule cannot drift between call sites."""
        return self.ts_ms <= WM_ANNOUNCE


#: mode-announcement sentinel: a kind="partition" hint at or below this
#: value switches operators to hint-driven watermarks without advancing
#: anything (emitted before the first batch, closing the startup window
#: where batch-driven advance could already race ahead)
WM_ANNOUNCE = -(2**62)


@dataclass(frozen=True)
class Marker:
    """Checkpoint barrier (reference OrchestrationMessage::CheckpointBarrier,
    orchestrator.rs:12-16)."""

    epoch: int


@dataclass(frozen=True)
class EndOfStream:
    pass


EOS = EndOfStream()

StreamItem = Union[RecordBatch, Marker, WatermarkHint, EndOfStream]


class ExecOperator:
    """One node of the physical plan."""

    #: output schema
    schema: Schema

    #: registry handles (no-op defaults so an operator that never calls
    #: bind_obs — test doubles subclassing ExecOperator directly — still
    #: runs; real operators bind in their constructors)
    _obs_rows_in = _OBS_NULL
    _obs_batch_ms = _OBS_NULL
    _obs_input_wait = _OBS_NULL

    #: doctor per-node stats (obs/doctor): plain single-writer attribute
    #: adds — one float/int add per batch or item, independent of the
    #: registry so attribution works even with metrics disabled.  Class
    #: defaults keep un-doctored operator instances (test doubles, direct
    #: build_physical callers) inert.
    _dr_busy_ms = 0.0
    _dr_batches = 0
    _dr_rows_in = 0
    _dr_input_wait_s = 0.0
    _dr_node_id: str | None = None
    _dr_lineage = None  # obs.doctor.lineage.LineageTracker when sampling

    #: state observatory (obs/statewatch.py): stateful operators set
    #: ``_sw`` (and, for the join, ``_sw_right``) to a StateWatch at
    #: construction and implement ``state_info()``.  The class defaults
    #: keep stateless operators entirely inert — one ``is None`` check
    #: in _note_batch is their whole cost.
    _sw = None
    _sw_last_refresh = 0.0
    _state_info_cache: tuple | None = None

    def bind_obs(self, op: str) -> None:
        """Bind this operator's registry instruments (obs subsystem):
        rows-in counter, per-batch processing-time histogram, and the
        doctor's upstream-wait histogram, labeled ``op=<label>``.
        Called once from each operator's constructor; with metrics
        disabled the handles are shared no-op nulls, so the hot path
        stays allocation-free."""
        from denormalized_tpu import obs

        self._obs_rows_in = obs.counter("dnz_op_rows_in_total", op=op)
        self._obs_batch_ms = obs.histogram("dnz_op_batch_ms", op=op)
        self._obs_input_wait = obs.histogram(
            "dnz_op_input_wait_ms", op=op
        )

    # -- doctor handoff instrumentation (obs/doctor, DNZ-M002) -----------
    def _note_batch(self, t0: float, rows: int) -> None:
        """Close a batch-processing bracket opened at ``perf_counter()``
        ``t0``: feeds both the registry histogram and the doctor's
        per-node busy accounting.  Emissions must be materialized before
        calling (time suspended in downstream operators is never this
        operator's busy time — the PR-6 bracket contract)."""
        dt_ms = (time.perf_counter() - t0) * 1e3
        self._obs_batch_ms.observe(dt_ms)
        self._dr_busy_ms += dt_ms
        self._dr_batches += 1
        self._dr_rows_in += rows
        if self._sw is not None:
            self._refresh_hot_gauges()

    def _note_input_wait(self, dt_s: float) -> None:
        """Record one upstream-handoff wait (time this operator spent
        suspended before the next stream item arrived).  Multi-input
        operators (the join's merged queue) call this directly; single-
        input operators get it via :meth:`_doctor_input`."""
        self._dr_input_wait_s += dt_s
        if self._obs_input_wait:
            self._obs_input_wait.observe(dt_s * 1e3)

    def _doctor_input(self, input_op: "ExecOperator | None" = None
                      ) -> Iterator[StreamItem]:
        """Iterate the upstream operator with the doctor's handoff
        instrumentation: every pull is timed (queue-wait attribution)
        and, when record lineage is sampling, rowful batches covering a
        sampled record register a hop at this node.  Every operator that
        overrides the batch-processing path must consume its input
        through this (or :meth:`_note_input_wait`) — lint-enforced by
        DNZ-M002."""
        it = (input_op if input_op is not None else self.input_op).run()
        while True:
            t0 = time.perf_counter()
            try:
                item = next(it)
            except StopIteration:
                return
            self._note_input_wait(time.perf_counter() - t0)
            if (
                self._dr_lineage is not None
                and isinstance(item, RecordBatch)
                and item.num_rows
            ):
                self._dr_lineage.hop(self._dr_node_id, item)
            yield item

    # -- state observatory (obs/statewatch.py, DNZ-M003) -----------------
    def state_info(self) -> dict | None:
        """Exact state accounting of a STATEFUL operator (None for
        stateless ones): live bytes / live keys / slot capacity vs
        occupancy / oldest retained event time.  Pull-only — computed
        when a snapshot or exporter asks, never on the hot path.
        Implementations read single-writer operator state defensively;
        a read racing teardown may return stale numbers, never raise
        into the caller (gauge_fns degrade to 0, the doctor wraps)."""
        return None

    def _state_watch_views(self):
        """(side_label_or_None, watch, resolve_fn) per sketch this
        operator feeds — the hot-key gauge refresh and the doctor's
        /state endpoint both iterate this.  Default: the single ``_sw``
        with no side label and no key resolution."""
        if self._sw is None:
            return []
        return [(None, self._sw, None)]

    def _cached_state_info(self, max_age_s: float = 0.2) -> dict | None:
        """state_info() memoized briefly so the per-node gauge family
        (bytes/keys/slots/lag) costs ONE accounting pass per export
        cycle, not one per instrument."""
        c = self._state_info_cache
        now = time.monotonic()
        if c is not None and now - c[0] < max_age_s:
            return c[1]
        info = self.state_info()
        self._state_info_cache = (now, info)
        return info

    def bind_state_obs(self, node_id: str) -> None:
        """Bind the state observatory's registry view for this operator
        under its plan node id.  Called by ``doctor.register_query``
        once node ids exist (the same DFS ids the checkpointer uses) —
        under the query's bound registry.  Every gauge_fn holds a
        weakref: the registry must never pin a finished query's
        operator graph (the ``dnz_decode_fallback_rows`` rule).

        Reading the state-bytes gauge also appends a growth-ring sample
        to the operator's watch, so the JSONL/Prometheus export cadence
        IS the forecast history."""
        if self.state_info() is None and self._sw is None:
            return  # stateless operator: nothing to account
        import weakref

        from denormalized_tpu import obs

        ref = weakref.ref(self)

        def field(name, sample=False):
            def read():
                op = ref()
                if op is None:
                    return 0
                info = op._cached_state_info()
                if not info:
                    return 0
                v = info.get(name) or 0
                if sample and op._sw is not None:
                    op._sw.record_sample(v)
                return v

            return read

        obs.gauge_fn(
            "dnz_state_bytes", field("state_bytes", sample=True),
            node=node_id,
        )
        obs.gauge_fn(
            "dnz_state_live_keys", field("live_keys"), node=node_id
        )
        obs.gauge_fn(
            "dnz_state_slots", field("slot_capacity"),
            node=node_id, kind="capacity",
        )
        obs.gauge_fn(
            "dnz_state_slots", field("slot_live"),
            node=node_id, kind="live",
        )
        obs.gauge_fn(
            "dnz_state_oldest_event_lag_ms", field("oldest_event_lag_ms"),
            node=node_id,
        )
        # cold tier (state/tiering.py): zero when no budget/backend is
        # configured or nothing is spilled — the same state_info fields
        # the /state endpoint and the spill-thrashing verdict read
        obs.gauge_fn(
            "dnz_state_spilled_bytes", field("spilled_bytes"), node=node_id
        )
        obs.gauge_fn(
            "dnz_state_spilled_keys", field("spilled_keys"), node=node_id
        )

        def skew():
            from denormalized_tpu.obs.statewatch import side_live_keys

            op = ref()
            if op is None or op._sw is None:
                return 0
            info = op._cached_state_info() or {}
            views = op._state_watch_views()
            best = 0.0
            for side, watch, _resolve in views:
                s = watch.skew_factor(side_live_keys(info, side))
                if s is not None and s > best:
                    best = s
            return best

        obs.gauge_fn("dnz_state_skew_factor", skew, node=node_id)

    def _refresh_hot_gauges(self, force: bool = False) -> None:
        """Refresh the ``dnz_state_hot_key_share`` gauge family from
        this operator's sketch(es).  Runs on the operator's own thread
        (single-writer), rate-limited to ~1 Hz from _note_batch; keys
        that drop out of the top-K are zeroed (the registry has no
        series eviction by design)."""
        node = self._dr_node_id
        sw = self._sw
        if not sw or node is None:
            return
        now = time.monotonic()
        if not force and now - self._sw_last_refresh < 1.0:
            return
        self._sw_last_refresh = now
        from denormalized_tpu import obs

        for side, watch, resolve in self._state_watch_views():
            if not watch:
                continue
            labels = {"node": node}
            if side is not None:
                labels["side"] = side
            hot = watch.hot_keys(8, resolve=resolve)
            bound = watch._hot_bound
            live_keys = set()
            for h in hot:
                key = h["key"]
                live_keys.add(key)
                g = bound.get(key)
                if g is None:
                    g = obs.gauge(
                        "dnz_state_hot_key_share", key=key, **labels
                    )
                    bound[key] = g
                g.set(h["share"])
            for key, g in bound.items():
                if key not in live_keys:
                    g.set(0.0)
            if len(bound) > 128:
                # bound the handle map (and this loop) under hot-set
                # churn: stale handles are zeroed above, then dropped —
                # their registry series stay at 0; re-entering the
                # top-K re-binds the same series (idempotent keying)
                for key in [k for k in bound if k not in live_keys]:
                    del bound[key]

    def run(self) -> Iterator[StreamItem]:
        raise NotImplementedError

    @property
    def children(self) -> list["ExecOperator"]:
        return []

    # -- observability (reference exposes DataFusion MetricsSet via
    # ExecutionPlan::metrics, streaming_window.rs:491) ------------------
    def metrics(self) -> dict[str, float]:
        return {}

    def display(self, indent: int = 0, with_metrics: bool = False) -> str:
        line = "  " * indent + self._label()
        if with_metrics:
            m = self.metrics()
            if m:
                parts = ", ".join(
                    f"{k}={v:.6g}" if isinstance(v, float) else f"{k}={v}"
                    for k, v in m.items()
                )
                line += f"  [{parts}]"
        return "\n".join(
            [line] + [c.display(indent + 1, with_metrics) for c in self.children]
        )

    def _label(self) -> str:
        return type(self).__name__
